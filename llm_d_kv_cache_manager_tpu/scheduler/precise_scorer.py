"""Precise prefix-cache scorer: the scheduler-plugin adapter.

Counterpart of the reference's `PrecisePrefixCacheScorer` for the
llm-d inference scheduler (examples/kv_cache_aware_scorer/
kvcache_aware_scorer.go:63-314): owns the whole indexing stack (indexer +
event pool + subscriber manager), keeps per-pod event subscriptions alive
through a TTL cache refreshed on every scoring cycle, handles both
completions and chat-completions request bodies, and returns 0-1
max-normalized scores for the scheduler's weighted sum.

A scheduler embeds this as a scorer plugin:

    scorer = PrecisePrefixCacheScorer(PrecisePrefixCacheScorerConfig())
    ...
    scores = scorer.score(request, pods)   # every scheduling cycle
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER, use_trace
from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
    ApplyChatTemplateRequest,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger
from llm_d_kv_cache_manager_tpu.utils.ttl_cache import TTLCache

logger = get_logger("scheduler.precise_scorer")

PLUGIN_TYPE = "precise-prefix-cache-scorer"


# ----------------------------- request model ------------------------------


@dataclass
class ChatMessage:
    role: str
    content: str


@dataclass
class CompletionsBody:
    prompt: str


@dataclass
class ChatCompletionsBody:
    messages: List[ChatMessage] = field(default_factory=list)
    tools: Optional[List[Dict[str, Any]]] = None
    documents: Optional[List[Dict[str, Any]]] = None
    chat_template: Optional[str] = None
    return_assistant_tokens_mask: bool = False
    continue_final_message: bool = False
    add_generation_prompt: bool = True
    chat_template_kwargs: Optional[Dict[str, Any]] = None


@dataclass
class LLMRequest:
    """What the scheduler hands each scorer per cycle (types.LLMRequest).

    Exactly one body should be set; if both are, chat semantics win
    (matching the reference's defensive priority,
    kvcache_aware_scorer.go:263-267).
    """

    target_model: str
    completions: Optional[CompletionsBody] = None
    chat_completions: Optional[ChatCompletionsBody] = None


@dataclass(frozen=True)
class Pod:
    """Candidate endpoint (types.Pod projection)."""

    namespaced_name: str  # "namespace/name" — the subscriber identity
    address: str  # IP the index's pod entries are keyed by


# ----------------------------- configuration ------------------------------


@dataclass
class PrecisePrefixCacheScorerConfig:
    indexer_config: IndexerConfig = field(default_factory=IndexerConfig)
    events_pool_config: PoolConfig = field(default_factory=PoolConfig)
    # Subscribe to each scored pod's ZMQ endpoint, expiring idle pods.
    discover_pods: bool = True
    pod_socket_port: int = 5557
    subscription_ttl_seconds: float = 600.0
    # Subscriber ids here are scheduler-side namespaced names, not the
    # engines' published pod ids — subscribe to every kv topic.
    topic_filter: str = "kv@"
    # Global-socket mode: one static endpoint carrying every pod's
    # events (kvcache_aware_scorer.go:141-147); None disables.
    zmq_endpoint: Optional[str] = None
    # When a pod's subscription expires (TTL: it stopped being scored,
    # i.e. the scheduler no longer sees it), also purge its index
    # entries so stale claims stop attracting traffic.  Off by default:
    # the reference lets entries linger and rebuild from live events,
    # which is the right call for brief pod blips.
    purge_index_on_expiry: bool = False
    # Attach a predictive-tiering PolicyEngine (tiering/engine.py) to
    # the embedded indexer: the scoring stream feeds its PolicyFeed
    # and embedding schedulers can read compute-or-load advice from
    # ``scorer.policy_engine.advisor``.  Env-configured
    # (TIERING_* knobs, docs/tiering.md).
    tiering: bool = False


# ------------------------------- the scorer -------------------------------


class PrecisePrefixCacheScorer:
    def __init__(
        self,
        config: Optional[PrecisePrefixCacheScorerConfig] = None,
        indexer: Optional[Indexer] = None,
    ) -> None:
        self.config = config or PrecisePrefixCacheScorerConfig()
        self.indexer = indexer or Indexer(self.config.indexer_config)
        self.indexer.run()

        self.policy_engine = None
        if self.config.tiering:
            from llm_d_kv_cache_manager_tpu.tiering import PolicyEngine

            self.policy_engine = PolicyEngine(
                ledger=self.indexer.cache_stats
            )
            self.indexer.set_policy_engine(self.policy_engine)

        self.events_pool = Pool(
            self.indexer.kv_block_index,
            self.indexer.token_processor,
            self.config.events_pool_config,
        )
        self.events_pool.start()
        self.subscribers = SubscriberManager(sink=self.events_pool.add_task)

        self._subscriptions: Optional[TTLCache[str, str]] = None
        if self.config.discover_pods:
            self._subscriptions = TTLCache(
                self.config.subscription_ttl_seconds,
                on_evict=self._on_subscription_expired,
            )
            self._subscriptions.start_sweeper(
                self.config.subscription_ttl_seconds
            )
        if self.config.zmq_endpoint:
            self.subscribers.ensure_subscriber(
                "local-subscriber",
                self.config.zmq_endpoint,
                topic_filter=self.config.topic_filter,
            )

    def shutdown(self) -> None:
        if self._subscriptions is not None:
            self._subscriptions.stop_sweeper()
        self.subscribers.shutdown()
        self.events_pool.shutdown()
        if self.policy_engine is not None:
            self.policy_engine.close()
        self.indexer.shutdown()

    # -- subscriber lifecycle --

    def _on_subscription_expired(self, pod: str, address: str) -> None:
        self.subscribers.remove_subscriber(pod)
        if self.config.purge_index_on_expiry:
            # Off-thread: the expiry callback runs under the TTL cache's
            # callback lock, which every scoring cycle's subscription
            # refresh also takes — an O(index) purge (network I/O on the
            # Redis backend) inline here would stall the hot path.
            threading.Thread(
                target=self._purge_expired_pod,
                args=(pod, address),
                name=f"kvtpu-purge-{pod}",
                daemon=True,
            ).start()

    def _purge_expired_pod(self, pod: str, address: str) -> None:
        try:
            removed = self.indexer.kv_block_index.purge_pod(address)
            logger.info(
                "purged %d index entries for expired pod %s (%s)",
                removed,
                pod,
                address,
            )
        except Exception:  # noqa: BLE001 - purge failure must stay local
            logger.exception(
                "index purge for expired pod %s (%s) failed", pod, address
            )

    def _refresh_subscriptions(self, pods: Sequence[Pod]) -> None:
        """Seen pods stay subscribed; unseen ones age out via TTL."""
        assert self._subscriptions is not None
        for pod in pods:
            self._subscriptions.set(pod.namespaced_name, pod.address)
            self.subscribers.ensure_subscriber(
                pod.namespaced_name,
                f"tcp://{pod.address}:{self.config.pod_socket_port}",
                topic_filter=self.config.topic_filter,
            )

    # -- scoring --

    def score(
        self, request: Optional[LLMRequest], pods: Sequence[Pod]
    ) -> Dict[Pod, float]:
        """One scheduling cycle: returns 0-1 normalized scores per pod."""
        if self.config.discover_pods:
            self._refresh_subscriptions(pods)

        if request is None:
            logger.debug("request is nil; skipping scoring")
            return {}

        # Sampled cycle trace: the embedded stack has no HTTP layer to
        # ingest a traceparent, so the scheduler cycle is the trace root
        # and the indexer's stage spans attach beneath it.
        cycle_trace = TRACER.start_trace("scheduler.score")
        if cycle_trace is not None:
            cycle_trace.set_attr("model", request.target_model)
            cycle_trace.set_attr("candidate_pods", len(pods))
        start = time.perf_counter()
        try:
            with use_trace(cycle_trace):
                raw = self._get_scores(request)
        except Exception as exc:
            if cycle_trace is not None:
                cycle_trace.set_error(repr(exc))
                cycle_trace.finish("error")
            logger.exception("failed to get pod scores")
            return {}
        if cycle_trace is not None:
            cycle_trace.set_attr("scored_pods", len(raw))
            cycle_trace.finish()
        logger.debug(
            "scored %d pods in %.1f ms",
            len(raw),
            (time.perf_counter() - start) * 1e3,
        )
        return self._normalize(raw, pods)

    def _get_scores(self, request: LLMRequest) -> Dict[str, float]:
        if request.chat_completions is not None:
            if request.completions is not None:
                logger.debug(
                    "both bodies present; defaulting to chat/completions"
                )
            body = request.chat_completions
            render_req = ApplyChatTemplateRequest(
                conversation=[
                    {"role": m.role, "content": m.content}
                    for m in body.messages
                ],
                tools=body.tools,
                documents=body.documents,
                chat_template=body.chat_template,
                add_generation_prompt=body.add_generation_prompt,
                continue_final_message=body.continue_final_message,
                chat_template_kwargs=body.chat_template_kwargs,
            )
            return self.indexer.get_pod_scores(
                prompt="",
                model_name=request.target_model,
                pod_identifiers=None,
                render_req=render_req,
            )
        if request.completions is not None:
            return self.indexer.get_pod_scores(
                prompt=request.completions.prompt,
                model_name=request.target_model,
                pod_identifiers=None,
            )
        raise ValueError("no valid input found in request")

    @staticmethod
    def _normalize(
        raw: Dict[str, float], pods: Sequence[Pod]
    ) -> Dict[Pod, float]:
        """Index scores (keyed by pod address) -> 0-1 per candidate pod,
        highest raw score = 1.0; unknown pods score 0."""
        top = max(raw.values(), default=0.0)
        if top <= 0:
            return {pod: 0.0 for pod in pods}
        return {pod: raw.get(pod.address, 0.0) / top for pod in pods}
