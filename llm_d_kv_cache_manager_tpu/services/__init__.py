"""Sidecar services (reference: services/)."""
