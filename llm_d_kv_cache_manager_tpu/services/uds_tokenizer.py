"""Tokenizer sidecar: TokenizationService over a Unix-domain socket.

Counterpart of the reference's Python sidecar
(services/uds_tokenizer/tokenizer_grpc_service.py:32-160): per-model
cached HF tokenizers, ``Tokenize`` with offset mapping, chat-template
rendering, and an init RPC that pre-warms a model.  The reference runs
this to give its Go indexer tokenizer access across a process boundary;
here it exists for the same fleet topology (a shared tokenizer sidecar
serving many indexer replicas) and for reference-client compat — the
in-process backends (tokenization/tokenizers.py) remain the default.

Message caps mirror the reference client's 100 MB limits
(pkg/tokenization/uds_tokenizer.go:64-77).
"""

from __future__ import annotations

import os
import threading
from concurrent import futures
from typing import Dict, Optional

import grpc

from llm_d_kv_cache_manager_tpu.api import tokenizer_pb2
from llm_d_kv_cache_manager_tpu.api.grpc_services import (
    TokenizationServiceServicer,
    add_tokenization_servicer,
    struct_map_to_dict,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    load_auto_tokenizer,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("services.uds_tokenizer")

MAX_MESSAGE_BYTES = 100 * 1024 * 1024


class TokenizerRegistry:
    """Thread-safe per-model tokenizer cache (reference:
    tokenizer_service/tokenizer.py:104-140)."""

    def __init__(self) -> None:
        self._tokenizers: Dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, model_name: str, tokenizer) -> None:
        """Inject a pre-built tokenizer (tests, local models)."""
        with self._lock:
            self._tokenizers[model_name] = tokenizer

    def get(self, model_name: str):
        with self._lock:
            tokenizer = self._tokenizers.get(model_name)
        if tokenizer is None:
            tokenizer = load_auto_tokenizer(model_name)
            with self._lock:
                self._tokenizers[model_name] = tokenizer
        return tokenizer


class TokenizationGrpcService(TokenizationServiceServicer):
    def __init__(self, registry: Optional[TokenizerRegistry] = None) -> None:
        self.registry = registry or TokenizerRegistry()

    def Tokenize(self, request, context):
        response = tokenizer_pb2.TokenizeResponse()
        try:
            tokenizer = self.registry.get(request.model_name)
            output = tokenizer(
                request.input,
                add_special_tokens=request.add_special_tokens,
                return_offsets_mapping=True,
            )
            response.input_ids.extend(output["input_ids"])
            for start, end in output["offset_mapping"]:
                response.offset_pairs.extend((start, end))
            response.success = True
        except Exception as exc:
            logger.exception("Tokenize failed for %s", request.model_name)
            response.success = False
            response.error_message = str(exc)
        return response

    def RenderChatTemplate(self, request, context):
        response = tokenizer_pb2.ChatTemplateResponse()
        try:
            tokenizer = self.registry.get(request.model_name)
            # Turns are a wire-batching artifact; the template sees one
            # flat message list (HF batch mode would otherwise return a
            # list of strings for multi-turn requests).
            conversation = [
                {"role": m.role, "content": m.content}
                for turn in request.conversation_turns
                for m in turn.messages
            ]
            tools = [
                struct_map_to_dict(tool.tool) for tool in request.tools
            ] or None
            documents = [
                struct_map_to_dict(doc.document) for doc in request.documents
            ] or None
            kwargs = struct_map_to_dict(request.chat_template_kwargs)
            rendered = tokenizer.apply_chat_template(
                conversation,
                tools=tools,
                documents=documents,
                chat_template=request.chat_template or None,
                add_generation_prompt=request.add_generation_prompt,
                continue_final_message=request.continue_final_message,
                tokenize=False,
                **kwargs,
            )
            response.rendered_prompt = rendered
            response.success = True
        except Exception as exc:
            logger.exception(
                "RenderChatTemplate failed for %s", request.model_name
            )
            response.success = False
            response.error_message = str(exc)
        return response

    def InitializeTokenizer(self, request, context):
        response = tokenizer_pb2.InitializeTokenizerResponse()
        try:
            self.registry.get(request.model_name)
            response.success = True
        except Exception as exc:
            logger.exception(
                "InitializeTokenizer failed for %s", request.model_name
            )
            response.success = False
            response.error_message = str(exc)
        return response


def serve(
    uds_path: str = "/tmp/kvcache_tokenizer.sock",
    max_workers: Optional[int] = None,
    registry: Optional[TokenizerRegistry] = None,
) -> grpc.Server:
    """Start the sidecar on a UDS endpoint; returns the server."""
    if os.path.exists(uds_path):
        os.unlink(uds_path)
    server = grpc.server(
        futures.ThreadPoolExecutor(
            max_workers=max_workers or os.cpu_count() or 4
        ),
        options=[
            ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
            ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
        ],
    )
    add_tokenization_servicer(TokenizationGrpcService(registry), server)
    server.add_insecure_port(f"unix://{uds_path}")
    server.start()
    logger.info("uds tokenizer service listening on %s", uds_path)
    return server


def main() -> None:  # pragma: no cover - CLI entry
    import signal

    uds_path = os.environ.get("UDS_PATH", "/tmp/kvcache_tokenizer.sock")
    server = serve(uds_path)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.stop(grace=5)


if __name__ == "__main__":  # pragma: no cover
    main()
