"""Tokenizer sidecar: TokenizationService over a Unix-domain socket.

Counterpart of the reference's Python sidecar
(services/uds_tokenizer/tokenizer_grpc_service.py:32-160): per-model
cached HF tokenizers, ``Tokenize`` with offset mapping, chat-template
rendering, and an init RPC that pre-warms a model.  The reference runs
this to give its Go indexer tokenizer access across a process boundary;
here it exists for the same fleet topology (a shared tokenizer sidecar
serving many indexer replicas) and for reference-client compat — the
in-process backends (tokenization/tokenizers.py) remain the default.

Message caps mirror the reference client's 100 MB limits
(pkg/tokenization/uds_tokenizer.go:64-77).
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
import threading
from concurrent import futures
from typing import Dict, Optional

import grpc

from llm_d_kv_cache_manager_tpu.api import tokenizer_pb2
from llm_d_kv_cache_manager_tpu.api.grpc_services import (
    TokenizationServiceServicer,
    add_tokenization_servicer,
    struct_map_to_dict,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    load_auto_tokenizer,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("services.uds_tokenizer")

MAX_MESSAGE_BYTES = 100 * 1024 * 1024

_HUB_SEGMENT = re.compile(r"[A-Za-z0-9_.\-]+")

# Download hygiene (reference: tokenizer_service/tokenizer.py:150-178):
# a tokenizer sidecar must never pull model weights — snapshot downloads
# are restricted to tokenizer-related files.  `tokenizer.model` is added
# beyond the reference's list so sentencepiece-only models work too.
TOKENIZER_FILE_PATTERNS = [
    "tokenizer.json",
    "tokenizer_config.json",
    "special_tokens_map.json",
    "vocab.json",
    "merges.txt",
    "config.json",
    "generation_config.json",
    "tokenizer.model",
]

# A usable cached download: config plus either a fast-tokenizer json or
# a sentencepiece model (reference tokenizer.py:84-88 requires
# tokenizer.json only, which would re-download sentencepiece-only
# models forever).
REQUIRED_CACHED_FILE = "config.json"
ANY_OF_CACHED_FILES = ("tokenizer.json", "tokenizer.model")


def _is_cached(local_path: str) -> bool:
    return os.path.exists(
        os.path.join(local_path, REQUIRED_CACHED_FILE)
    ) and any(
        os.path.exists(os.path.join(local_path, f))
        for f in ANY_OF_CACHED_FILES
    )


def is_remote_model(model_identifier: str) -> bool:
    """Remote hub name (``org/model``) vs local filesystem path
    (reference tokenizer.py:196-214)."""
    if os.path.isabs(model_identifier):
        return False
    if model_identifier.startswith(("./", "../")):
        return False
    if os.path.exists(model_identifier):
        return False
    return True


def _validate_hub_id(model_identifier: str) -> None:
    """Hub ids name the cache subdirectory; refuse anything that could
    traverse out of it (a UDS client controls this string)."""
    parts = model_identifier.split("/")
    if len(parts) > 2 or not all(
        part and part.strip(".") and _HUB_SEGMENT.fullmatch(part)
        for part in parts
    ):
        raise ValueError(
            f"invalid hub model identifier {model_identifier!r}"
        )


def _default_cache_dir() -> str:
    return os.environ.get(
        "TOKENIZER_CACHE_DIR",
        os.path.join(
            os.path.expanduser("~"), ".cache", "kvtpu", "tokenizers"
        ),
    )


def fetch_tokenizer_files(
    model_identifier: str, cache_dir: Optional[str] = None
) -> str:
    """Materialize ONLY tokenizer-related files locally; return the path.

    Resolution order (reference tokenizer.py:60-104):

    1. Local path: returned as-is, nothing downloaded.
    2. Sidecar cache hit (config + tokenizer.json/.model present): reused.
    3. Snapshot download restricted to ``TOKENIZER_FILE_PATTERNS`` from
       ModelScope when ``USE_MODELSCOPE=true``, else Hugging Face.

    Downloads land in a temp directory and are renamed into place only
    when complete, so a half-written download (network blip mid-snapshot)
    can never masquerade as a cache hit on the next call.
    """
    if not is_remote_model(model_identifier):
        return model_identifier
    _validate_hub_id(model_identifier)

    local_path = os.path.join(
        cache_dir or _default_cache_dir(), *model_identifier.split("/")
    )
    if _is_cached(local_path):
        logger.info("using cached tokenizer files at %s", local_path)
        return local_path

    use_modelscope = (
        os.environ.get("USE_MODELSCOPE", "false").lower() == "true"
    )
    if use_modelscope:
        from modelscope import snapshot_download
    else:
        from huggingface_hub import snapshot_download
    # A UNIQUE temp dir per call (mkdtemp, not a pid suffix): concurrent
    # fetches of the same model — two RPC threads, or two sidecar
    # replicas on a shared volume — must never share a staging dir, or
    # one's rename publishes the other's half-written files.
    parent = os.path.dirname(local_path)
    os.makedirs(parent, exist_ok=True)
    tmp_path = tempfile.mkdtemp(
        dir=parent, prefix=f".{os.path.basename(local_path)}.tmp-"
    )
    # mkdtemp's fixed 0700 would survive os.replace and lock other UIDs
    # (shared-volume sidecar replicas) out of the published cache dir.
    os.chmod(tmp_path, 0o755)
    try:
        snapshot_download(
            model_identifier,
            local_dir=tmp_path,
            allow_patterns=TOKENIZER_FILE_PATTERNS,
        )
    except Exception:
        shutil.rmtree(tmp_path, ignore_errors=True)
        logger.exception(
            "tokenizer-file download failed for %s (%s)",
            model_identifier,
            "modelscope" if use_modelscope else "huggingface",
        )
        raise
    try:
        os.replace(tmp_path, local_path)
    except OSError:
        # Lost the publish race (target created between our cache check
        # and now, e.g. ENOTEMPTY); the winner's copy serves everyone.
        shutil.rmtree(tmp_path, ignore_errors=True)
        if not _is_cached(local_path):
            raise
    logger.info(
        "downloaded tokenizer files for %s to %s",
        model_identifier,
        local_path,
    )
    return local_path


def load_sidecar_tokenizer(model_identifier: str):
    """Tokenizer-files-only load for the sidecar.

    Cache-first like ``load_auto_tokenizer``: the standard HF cache is
    tried before any network touch (zero-egress pods with warm caches
    must keep working), then the tokenizer-files-only download, then the
    full ``AutoTokenizer`` path as a last resort.
    """
    from transformers import AutoTokenizer

    if is_remote_model(model_identifier):
        try:
            return AutoTokenizer.from_pretrained(
                model_identifier, use_fast=True, local_files_only=True
            )
        except Exception as exc:
            # Expected on cold pods; the sidecar download path follows.
            logger.debug(
                "%s not in the local HF cache (%s); trying the sidecar "
                "download path",
                model_identifier,
                exc,
            )
    try:
        path = fetch_tokenizer_files(model_identifier)
    except ImportError:  # no hub client available
        return load_auto_tokenizer(model_identifier)
    if path == model_identifier:
        return load_auto_tokenizer(model_identifier)
    return AutoTokenizer.from_pretrained(path, use_fast=True)


class TokenizerRegistry:
    """Thread-safe per-model tokenizer cache (reference:
    tokenizer_service/tokenizer.py:104-140)."""

    def __init__(self, loader=load_sidecar_tokenizer) -> None:
        self._tokenizers: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._loader = loader

    def register(self, model_name: str, tokenizer) -> None:
        """Inject a pre-built tokenizer (tests, local models)."""
        with self._lock:
            self._tokenizers[model_name] = tokenizer

    def get(self, model_name: str):
        with self._lock:
            tokenizer = self._tokenizers.get(model_name)
        if tokenizer is None:
            tokenizer = self._loader(model_name)
            with self._lock:
                self._tokenizers[model_name] = tokenizer
        return tokenizer


class TokenizationGrpcService(TokenizationServiceServicer):
    def __init__(self, registry: Optional[TokenizerRegistry] = None) -> None:
        self.registry = registry or TokenizerRegistry()

    def Tokenize(self, request, context):
        response = tokenizer_pb2.TokenizeResponse()
        try:
            tokenizer = self.registry.get(request.model_name)
            output = tokenizer(
                request.input,
                add_special_tokens=request.add_special_tokens,
                return_offsets_mapping=True,
            )
            response.input_ids.extend(output["input_ids"])
            for start, end in output["offset_mapping"]:
                response.offset_pairs.extend((start, end))
            response.success = True
        except Exception as exc:
            logger.exception("Tokenize failed for %s", request.model_name)
            response.success = False
            response.error_message = str(exc)
        return response

    def RenderChatTemplate(self, request, context):
        response = tokenizer_pb2.ChatTemplateResponse()
        try:
            tokenizer = self.registry.get(request.model_name)
            # Turns are a wire-batching artifact; the template sees one
            # flat message list (HF batch mode would otherwise return a
            # list of strings for multi-turn requests).
            conversation = [
                {"role": m.role, "content": m.content}
                for turn in request.conversation_turns
                for m in turn.messages
            ]
            tools = [
                struct_map_to_dict(tool.tool) for tool in request.tools
            ] or None
            documents = [
                struct_map_to_dict(doc.document) for doc in request.documents
            ] or None
            kwargs = struct_map_to_dict(request.chat_template_kwargs)
            rendered = tokenizer.apply_chat_template(
                conversation,
                tools=tools,
                documents=documents,
                chat_template=request.chat_template or None,
                add_generation_prompt=request.add_generation_prompt,
                continue_final_message=request.continue_final_message,
                tokenize=False,
                **kwargs,
            )
            response.rendered_prompt = rendered
            response.success = True
        except Exception as exc:
            logger.exception(
                "RenderChatTemplate failed for %s", request.model_name
            )
            response.success = False
            response.error_message = str(exc)
        return response

    def InitializeTokenizer(self, request, context):
        response = tokenizer_pb2.InitializeTokenizerResponse()
        try:
            self.registry.get(request.model_name)
            response.success = True
        except Exception as exc:
            logger.exception(
                "InitializeTokenizer failed for %s", request.model_name
            )
            response.success = False
            response.error_message = str(exc)
        return response


def serve(
    uds_path: str = "/tmp/kvcache_tokenizer.sock",
    max_workers: Optional[int] = None,
    registry: Optional[TokenizerRegistry] = None,
) -> grpc.Server:
    """Start the sidecar on a UDS endpoint; returns the server."""
    if os.path.exists(uds_path):
        os.unlink(uds_path)
    server = grpc.server(
        futures.ThreadPoolExecutor(
            max_workers=max_workers or os.cpu_count() or 4,
            thread_name_prefix="kvtpu-uds-tokenizer",
        ),
        options=[
            ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
            ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
        ],
    )
    add_tokenization_servicer(TokenizationGrpcService(registry), server)
    server.add_insecure_port(f"unix://{uds_path}")
    server.start()
    logger.info("uds tokenizer service listening on %s", uds_path)
    return server


def main() -> None:  # pragma: no cover - CLI entry
    import signal

    uds_path = os.environ.get("UDS_PATH", "/tmp/kvcache_tokenizer.sock")
    server = serve(uds_path)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.stop(grace=5)


if __name__ == "__main__":  # pragma: no cover
    main()
