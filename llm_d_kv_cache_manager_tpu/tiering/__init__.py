"""Predictive tiering: the policy brain between analytics and the
offload/index planes.

PRs 1-7 built measurement — the hit-attribution ledger's per-family
reuse EWMA, per-tier score explain, offload job spans, the bench's
readback RTT — but nothing *decided* anything with it: eviction ranked
on recency alone, blocks never moved down the memory ladder until
pressure forced them, and the scheduler never asked "load the
offloaded KV or just recompute it?".  This package turns those signals
into decisions, behind one :class:`PolicyEngine`:

* :mod:`policy_feed` — the stable contract exporting per-family reuse
  predictions from the cachestats ledger (plus the hash-chain
  clustering signal per HashEvict), consumed as immutable snapshots so
  policy reads never take analytics locks;
* :mod:`eviction` — predicted-next-use x byte-cost eviction ranking,
  plugged into ``CostAwareMemoryIndex`` and ``HostTierCache`` (LRU
  remains the escape hatch and the parity oracle);
* :mod:`demotion` — the proactive HBM -> host -> shared_storage
  demotion worker, publishing ``medium``-tagged KVEvents so the
  scorer's tier weights finally rank real residency;
* :mod:`advisor` — the compute-or-load advisor: measured readback RTT
  vs the model's prefill rate, per prefix chunk, returning
  load / recompute / hybrid-overlap.

See docs/tiering.md for the contract, the eviction formula, the
demotion state machine, and the compute-or-load decision rule.
"""

from llm_d_kv_cache_manager_tpu.tiering.advisor import (
    Advice,
    AdvisorConfig,
    ComputeOrLoadAdvisor,
    RttEstimator,
)
from llm_d_kv_cache_manager_tpu.tiering.demotion import (
    DemotionConfig,
    DemotionWorker,
    PodTierState,
    pool_event_sink,
)
from llm_d_kv_cache_manager_tpu.tiering.engine import (
    PolicyEngine,
    TieringConfig,
)
from llm_d_kv_cache_manager_tpu.tiering.eviction import (
    LRU_POLICY,
    PredictiveEvictionPolicy,
)
from llm_d_kv_cache_manager_tpu.tiering.policy_feed import (
    PolicyFeed,
    PolicyFeedConfig,
    PolicySnapshot,
    ReusePrediction,
)
from llm_d_kv_cache_manager_tpu.tiering.staged_target import (
    StagedDemotionTarget,
)

__all__ = [
    "Advice",
    "AdvisorConfig",
    "ComputeOrLoadAdvisor",
    "DemotionConfig",
    "DemotionWorker",
    "LRU_POLICY",
    "PodTierState",
    "PolicyEngine",
    "PolicyFeed",
    "PolicyFeedConfig",
    "PolicySnapshot",
    "PredictiveEvictionPolicy",
    "ReusePrediction",
    "RttEstimator",
    "StagedDemotionTarget",
    "TieringConfig",
    "pool_event_sink",
]
