"""Compute-or-load advisor: the scheduling half of the offload stack.

A prefix chunk resident on host DRAM or shared storage can reach the
chip two ways: **load** the offloaded KV (pay the readback RTT) or
**recompute** it (pay prefill FLOPs) — and, per "Compute Or Load KV
Cache? Why Not Both?" (PAPERS.md), the two overlap: load the head
blocks while the chip prefills the tail, finishing together.  The
advisor prices all three from two rolling estimators and returns the
cheapest:

* :class:`RttEstimator` — readback cost model ``t(nbytes) = floor +
  nbytes x per_byte``, fed by real offload load-job completions
  (``observe``; the offload worker calls it with each job's bytes and
  submit->harvest seconds) with an optional measured floor (the
  bench's ``readback_rtt_s``);
* prefill rate — ``tokens / prefill_seconds`` EWMA (``observe_prefill``)
  or a configured constant.

Decision (documented in docs/tiering.md): compute ``load_s(n)``,
``recompute_s(n)`` and ``hybrid_s = min over k of max(load_s(k),
recompute_s(n - k))`` (the overlap split: head blocks k stream in
while the tail n-k prefills).  The cheapest wins; a pure action is
preferred when it is within ``margin`` of hybrid (simpler execution,
same latency).  With no RTT observations the advisor answers
**recompute** — never stall a request on an unmeasured I/O path — and
with no prefill-rate signal it answers **load**.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tiering.advisor")

EWMA_ALPHA = 0.3

LOAD = "load"
RECOMPUTE = "recompute"
HYBRID = "hybrid"

# Advisor locks are leaves: estimator updates and advice computation
# never call out while held.
# kvlint: lock-order: RttEstimator._lock ascending
lockorder.declare_ascending("RttEstimator._lock")


@dataclass(frozen=True)
class Advice:
    """One compute-or-load decision for a prefix chunk."""

    action: str  # load | recompute | hybrid
    blocks: int
    load_s: Optional[float]
    recompute_s: Optional[float]
    hybrid_s: Optional[float]
    # Hybrid split: head blocks loaded while the tail recomputes.
    load_blocks: int
    reason: str

    def to_dict(self) -> dict:
        def _round(value):
            return None if value is None else round(value, 6)

        return {
            "action": self.action,
            "blocks": self.blocks,
            "load_s": _round(self.load_s),
            "recompute_s": _round(self.recompute_s),
            "hybrid_s": _round(self.hybrid_s),
            "load_blocks": self.load_blocks,
            "reason": self.reason,
        }


class RttEstimator:
    """Rolling readback-cost model ``t(n) = floor_s + n x per_byte_s``.

    ``floor_s`` is the fixed per-transfer cost (RPC/syscall/submit
    latency — what the bench measures as ``readback_rtt_s``);
    ``per_byte_s`` is learned from job observations.  Each observation
    attributes ``max(seconds - floor_s, 0)`` to the bytes moved, so a
    measured floor keeps small transfers from inflating the slope.

    ``gauge`` is the Prometheus gauge the job-latency EWMA lands on —
    the load-side estimator publishes ``tiering_readback_rtt_seconds``,
    the store-side one ``tiering_writeback_rtt_seconds`` (None skips
    publication, for auxiliary estimators).
    """

    def __init__(self, floor_s: float = 0.0, gauge=None) -> None:
        self._lock = lockorder.tracked(
            threading.Lock(), "RttEstimator._lock"
        )
        self._gauge = gauge
        self._floor_s = floor_s  # guarded-by: _lock
        self._per_byte_s: Optional[float] = None  # guarded-by: _lock
        self._ewma_job_s: Optional[float] = None  # guarded-by: _lock
        self._observations = 0  # guarded-by: _lock

    def set_floor(self, floor_s: float) -> None:
        with self._lock:
            self._floor_s = max(0.0, floor_s)

    def observe(self, nbytes: int, seconds: float) -> None:
        """Fold one completed load job (bytes moved, submit->harvest
        seconds) into the model."""
        if nbytes <= 0 or seconds <= 0:
            return
        with self._lock:
            sample = max(seconds - self._floor_s, 0.0) / nbytes
            self._per_byte_s = (
                sample
                if self._per_byte_s is None
                else EWMA_ALPHA * sample
                + (1.0 - EWMA_ALPHA) * self._per_byte_s
            )
            self._ewma_job_s = (
                seconds
                if self._ewma_job_s is None
                else EWMA_ALPHA * seconds
                + (1.0 - EWMA_ALPHA) * self._ewma_job_s
            )
            self._observations += 1
            job_s = self._ewma_job_s
        if self._gauge is not None:
            self._gauge.set(job_s)

    def params(self):
        """(floor_s, per_byte_s) under one lock hit, or None when the
        model has no signal at all — lets callers price many sizes
        (the hybrid split scan) without re-locking per candidate."""
        with self._lock:
            per_byte = self._per_byte_s
            floor = self._floor_s
            if per_byte is None:
                if self._observations == 0 and floor <= 0.0:
                    return None
                per_byte = 0.0
        return floor, per_byte

    def estimate(self, nbytes: int) -> Optional[float]:
        """Predicted seconds to load ``nbytes``; None before any
        observation (unless a floor was measured)."""
        params = self.params()
        if params is None:
            return None
        if nbytes <= 0:
            return 0.0
        floor, per_byte = params
        return floor + nbytes * per_byte

    def stats(self) -> dict:
        with self._lock:
            return {
                "observations": self._observations,
                "floor_s": round(self._floor_s, 6),
                "per_byte_s": (
                    None
                    if self._per_byte_s is None
                    else self._per_byte_s
                ),
                "ewma_job_s": (
                    None
                    if self._ewma_job_s is None
                    else round(self._ewma_job_s, 6)
                ),
            }


@dataclass
class AdvisorConfig:
    # Host bytes of one KV block (the offload connector's
    # pool.block_nbytes); 0 = unknown, advise() answers recompute.
    bytes_per_block: int = 0
    # Tokens per KV block (the fleet block_size invariant).
    block_tokens: int = 16
    # Configured prefill rate (tokens/s); 0 = learn from
    # observe_prefill.
    prefill_tokens_per_s: float = 0.0
    # Fixed readback floor seeded into the estimator.
    rtt_floor_s: float = 0.0
    # Offer hybrid overlap at all.
    hybrid: bool = True
    # Prefer a pure action when it is within this fraction of hybrid.
    margin: float = 0.05


class ComputeOrLoadAdvisor:
    """Per-prefix-chunk load / recompute / hybrid decisions."""

    def __init__(self, config: Optional[AdvisorConfig] = None) -> None:
        self.config = config or AdvisorConfig()
        self.rtt = RttEstimator(
            floor_s=self.config.rtt_floor_s,
            gauge=METRICS.tiering_readback_rtt,
        )
        # Write-side cost model: fed by the offload store path
        # (device->host->file), so demotion is priced from measured
        # transfers, not the readback model's mirror image.
        self.rtt_store = RttEstimator(gauge=METRICS.tiering_writeback_rtt)
        # EWMA of the store path's device-transfer (gather + DMA)
        # per-byte cost — the half of a demotion the file write hides.
        self._store_device_per_byte: Optional[float] = None
        self._store_device_observations = 0
        self._prefill_rate: Optional[float] = (
            self.config.prefill_tokens_per_s
            if self.config.prefill_tokens_per_s > 0
            else None
        )
        # Advice tallies (racy-tolerant ints for status; exact counts
        # live in the Prometheus counter).
        self.advice_counts = {LOAD: 0, RECOMPUTE: 0, HYBRID: 0}
        self._advice_children = {
            action: METRICS.tiering_advice.labels(action=action)
            for action in (LOAD, RECOMPUTE, HYBRID)
        }

    # -- estimator feeds ------------------------------------------------

    def observe_load(self, nbytes: int, seconds: float) -> None:
        self.rtt.observe(nbytes, seconds)

    def observe_store(
        self,
        nbytes: int,
        io_seconds: float,
        device_seconds: Optional[float] = None,
    ) -> None:
        """Fold one completed store job into the write-side model:
        ``io_seconds`` is the host->file window (submit to harvest),
        ``device_seconds`` the device->host half (gather + DMA) when
        the path measured it (the staging engine and the one-shot
        handler both do)."""
        self.rtt_store.observe(nbytes, io_seconds)
        if device_seconds is None or device_seconds <= 0 or nbytes <= 0:
            return
        sample = device_seconds / nbytes
        self._store_device_per_byte = (
            sample
            if self._store_device_per_byte is None
            else EWMA_ALPHA * sample
            + (1.0 - EWMA_ALPHA) * self._store_device_per_byte
        )
        self._store_device_observations += 1

    def estimate_store_s(self, nbytes: int) -> Optional[float]:
        """Predicted seconds to demote ``nbytes`` down one rung
        (device transfer + file write); None before any store
        observation."""
        io_s = self.rtt_store.estimate(nbytes)
        if io_s is None:
            return None
        device = self._store_device_per_byte or 0.0
        return io_s + nbytes * device

    def observe_prefill(self, tokens: int, seconds: float) -> None:
        if tokens <= 0 or seconds <= 0:
            return
        if self.config.prefill_tokens_per_s > 0:
            return  # configured rate wins
        rate = tokens / seconds
        self._prefill_rate = (
            rate
            if self._prefill_rate is None
            else EWMA_ALPHA * rate + (1.0 - EWMA_ALPHA) * self._prefill_rate
        )

    @property
    def prefill_tokens_per_s(self) -> Optional[float]:
        return self._prefill_rate

    # -- the decision ---------------------------------------------------

    def _load_s(self, blocks: int) -> Optional[float]:
        if blocks <= 0:
            return 0.0
        bpb = self.config.bytes_per_block
        if bpb <= 0:
            return None
        return self.rtt.estimate(blocks * bpb)

    def _recompute_s(self, blocks: int) -> Optional[float]:
        if blocks <= 0:
            return 0.0
        rate = self._prefill_rate
        if rate is None or rate <= 0:
            return None
        return blocks * self.config.block_tokens / rate

    def advise(self, blocks: int, tier: Optional[str] = None) -> Advice:
        """Decide for a ``blocks``-long offloaded prefix chunk.

        ``tier`` is advisory context (recorded in the reason); the cost
        model is tier-agnostic because the estimator is fed by whatever
        path actually serves loads.
        """
        load_s = self._load_s(blocks)
        recompute_s = self._recompute_s(blocks)
        if blocks <= 0:
            return self._record(
                Advice(RECOMPUTE, 0, 0.0, 0.0, None, 0, "empty-chunk")
            )
        if load_s is None and recompute_s is None:
            return self._record(
                Advice(
                    RECOMPUTE, blocks, None, None, None, 0,
                    "no-rtt-and-no-prefill-signal",
                )
            )
        if load_s is None:
            return self._record(
                Advice(
                    RECOMPUTE, blocks, None, recompute_s, None, 0,
                    "no-rtt-observations",
                )
            )
        if recompute_s is None:
            return self._record(
                Advice(
                    LOAD, blocks, load_s, None, None, blocks,
                    "no-prefill-rate",
                )
            )

        hybrid_s: Optional[float] = None
        split = blocks
        if self.config.hybrid and blocks > 1:
            hybrid_s, split = self._best_split(blocks)

        margin = self.config.margin
        pure_best = min(load_s, recompute_s)
        if hybrid_s is not None and hybrid_s < pure_best * (1.0 - margin):
            return self._record(
                Advice(
                    HYBRID, blocks, load_s, recompute_s, hybrid_s, split,
                    f"overlap saves {pure_best - hybrid_s:.4f}s"
                    + (f" (tier {tier})" if tier else ""),
                )
            )
        if load_s <= recompute_s:
            action, load_blocks, reason = LOAD, blocks, "load cheaper"
        else:
            action, load_blocks, reason = RECOMPUTE, 0, "recompute cheaper"
        return self._record(
            Advice(
                action, blocks, load_s, recompute_s, hybrid_s, load_blocks,
                reason + (f" (tier {tier})" if tier else ""),
            )
        )

    def _best_split(self, blocks: int):
        """min over k of max(load(k), recompute(blocks - k)).

        Both arms are monotone in k (load rising, recompute falling),
        so the max is unimodal; the direct scan is O(blocks) over a few
        hundred candidates — robust over clever algebra, and exact for
        the floor discontinuity at k=0.  RTT/rate parameters are read
        ONCE (the estimator lock must not be taken per candidate — an
        explain over a 128k-token prompt scans thousands of splits).
        """
        params = self.rtt.params()
        floor, per_byte = params if params is not None else (0.0, 0.0)
        bpb = self.config.bytes_per_block
        rate = self._prefill_rate
        block_tokens = self.config.block_tokens
        best_s = None
        best_k = blocks
        for k in range(blocks + 1):
            load_k = floor + k * bpb * per_byte if k else 0.0
            comp_k = (blocks - k) * block_tokens / rate
            cell = max(load_k, comp_k)
            if best_s is None or cell < best_s:
                best_s = cell
                best_k = k
        return best_s, best_k

    def _record(self, advice: Advice) -> Advice:
        self.advice_counts[advice.action] += 1
        self._advice_children[advice.action].inc()
        return advice

    def stats(self) -> dict:
        return {
            "rtt": self.rtt.stats(),
            "rtt_store": self.rtt_store.stats(),
            "store_device_per_byte_s": self._store_device_per_byte,
            "store_device_observations": self._store_device_observations,
            "prefill_tokens_per_s": (
                None
                if self._prefill_rate is None
                else round(self._prefill_rate, 1)
            ),
            "bytes_per_block": self.config.bytes_per_block,
            "block_tokens": self.config.block_tokens,
            "hybrid": self.config.hybrid,
            "advice_counts": dict(self.advice_counts),
        }
