"""Proactive demotion: HBM -> host -> shared_storage ahead of pressure.

Reactive offload (store when the engine evicts) loses the race under
churn: by the time pressure forces an eviction the block is gone and
the next request pays a full prefill.  The demotion worker moves
**cold-but-reusable** block groups down the ladder *before* pressure —
cold: idle past the tier's threshold (or HBM utilization above the
watermark); reusable: the PolicyFeed still predicts a next use — and
publishes ``medium``-tagged KVEvents for every transition so the fleet
index (and therefore ``LongestPrefixScorer.tier_weights``) scores real
tier residency, not guesses.

State machine (docs/tiering.md), per block group::

      hbm --(idle >= demote_host_idle_s, or pressure)--> host
      host --(idle >= demote_storage_idle_s)--> shared_storage

Each ``hbm -> host`` transition emits ``BlockStored(medium="host")``
then ``BlockRemoved(medium="hbm")``; ``host -> shared_storage`` emits
``BlockStored(medium="shared_storage")`` then
``BlockRemoved(medium="host")``.  Store-before-remove means the index
never sees a window where the pod holds nothing (a scorer racing the
transition sees two tiers, max-weight wins — conservative).

The worker is driver-agnostic: it decides *what* and *when*; a
:class:`DemotionTarget` does the move and owns event publication.
:class:`PodTierState` is the in-repo reference target — it models a
pod's group residency, optionally pages bytes into a
``HostTierCache``, and publishes through any sink callable
(:func:`pool_event_sink` adapts the kvevents ingestion pool for tests,
the bench, and the smoke gate; a real pod would hand it its ZMQ
publisher).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tiering.demotion")

HBM = "hbm"
HOST = "host"
SHARED_STORAGE = "shared_storage"

_NEXT_TIER = {HBM: HOST, HOST: SHARED_STORAGE}
_TRANSITION = {HBM: "hbm_to_host", HOST: "host_to_storage"}

# PodTierState._lock is a leaf: event publication happens outside it.
# kvlint: lock-order: PodTierState._lock ascending
lockorder.declare_ascending("PodTierState._lock")
# kvlint: lock-order: DemotionWorker._lock ascending
lockorder.declare_ascending("DemotionWorker._lock")


@dataclass
class DemotionCandidate:
    """One block group as seen by the worker's scan."""

    group_key: int
    tier: str
    nbytes: int
    idle_s: float
    # Ledger family the group's blocks belong to (None = unknown).
    family: Optional[int] = None


@dataclass
class DemotionConfig:
    interval_s: float = 5.0
    # Idle thresholds per rung (seconds since last use).
    demote_host_idle_s: float = 30.0
    demote_storage_idle_s: float = 120.0
    # HBM utilization above which hbm->host demotion ignores the idle
    # threshold (demote the coldest reusable groups NOW).
    pressure_watermark: float = 0.85
    # Transition budget per cycle (keeps a cold start from issuing an
    # I/O storm).
    max_moves_per_cycle: int = 8
    # Only demote groups the feed still predicts a next use for unless
    # pressure forces the move ("cold-but-reusable"); groups with no
    # prediction are left for ordinary eviction to reap.
    require_prediction: bool = True


class DemotionTarget:
    """What a demotion driver must provide (duck-typed protocol)."""

    def scan(self) -> List[DemotionCandidate]:  # pragma: no cover
        raise NotImplementedError

    def pressure(self) -> float:  # pragma: no cover
        raise NotImplementedError

    def demote(
        self, group_key: int, to_tier: str
    ) -> bool:  # pragma: no cover
        raise NotImplementedError


def pool_event_sink(pool, pod_identifier: str, model_name: str) -> Callable:
    """Adapt a kvevents ingestion pool into a demotion event sink.

    Returns ``sink(events)`` that wraps the tier-transition events in
    an ``EventBatch`` message exactly as the pod's publisher would put
    them on the wire, so the index applies them through the same
    decode/apply path as live traffic (the demotion round-trip tests
    and the smoke gate ride this).
    """
    from llm_d_kv_cache_manager_tpu.kvevents.pool import Message

    def sink(events: Sequence[object]) -> None:
        if not events:
            return
        batch = EventBatch(ts=time.time(), events=list(events))
        pool.add_task(
            Message(
                topic=f"kv@{pod_identifier}@{model_name}",
                payload=batch.encode(),
                pod_identifier=pod_identifier,
                model_name=model_name,
            )
        )

    return sink


@dataclass
class _Group:
    engine_hashes: List[int]
    token_ids: List[int]
    parent_hash: Optional[int]
    block_size: int
    nbytes: int
    tier: str
    last_use: float
    family: Optional[int] = None
    group: Optional[object] = None  # host-tier payload (np.ndarray)


class PodTierState(DemotionTarget):
    """Reference demotion target: one pod's block-group residency.

    Tracks each group's tier, bytes, and last use; ``demote`` performs
    the transition (optionally paging bytes into a ``HostTierCache``
    on hbm->host) and publishes the medium-tagged events through the
    sink OUTSIDE its lock.  ``capacity_bytes`` bounds the hbm tier for
    the pressure signal.
    """

    def __init__(
        self,
        capacity_bytes: int,
        event_sink: Optional[Callable] = None,
        host_cache=None,
        feed=None,
    ) -> None:
        if capacity_bytes <= 0:
            raise ValueError("capacity_bytes must be positive")
        self.capacity_bytes = capacity_bytes
        self._event_sink = event_sink
        self._host_cache = host_cache
        self._feed = feed
        self._lock = lockorder.tracked(
            threading.Lock(), "PodTierState._lock"
        )
        self._groups: Dict[int, _Group] = {}  # guarded-by: _lock
        self._hbm_bytes = 0  # guarded-by: _lock

    def register_group(
        self,
        group_key: int,
        engine_hashes: Sequence[int],
        token_ids: Sequence[int],
        nbytes: int,
        parent_hash: Optional[int] = None,
        block_size: int = 16,
        tier: str = HBM,
        family: Optional[int] = None,
        group=None,
        now: Optional[float] = None,
    ) -> None:
        """Admit (or refresh) a resident block group."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            old = self._groups.get(group_key)
            if old is not None and old.tier == HBM:
                self._hbm_bytes -= old.nbytes
            self._groups[group_key] = _Group(
                engine_hashes=list(engine_hashes),
                token_ids=list(token_ids),
                parent_hash=parent_hash,
                block_size=block_size,
                nbytes=nbytes,
                tier=tier,
                last_use=now,
                family=family,
                group=group,
            )
            if tier == HBM:
                self._hbm_bytes += nbytes

    def touch(self, group_key: int, now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        with self._lock:
            group = self._groups.get(group_key)
            if group is not None:
                group.last_use = now

    def scan(self) -> List[DemotionCandidate]:
        now = time.monotonic()
        with self._lock:
            return [
                DemotionCandidate(
                    group_key=key,
                    tier=group.tier,
                    nbytes=group.nbytes,
                    idle_s=now - group.last_use,
                    family=group.family,
                )
                for key, group in self._groups.items()
                if group.tier in _NEXT_TIER
            ]

    def pressure(self) -> float:
        with self._lock:
            return self._hbm_bytes / self.capacity_bytes

    def demote(self, group_key: int, to_tier: str) -> bool:
        """Move one group down a rung; publishes events on success."""
        events: List[object] = []
        with self._lock:
            group = self._groups.get(group_key)
            if group is None or _NEXT_TIER.get(group.tier) != to_tier:
                return False
            if to_tier == HOST and self._host_cache is not None:
                if group.group is None or not self._host_cache.put(
                    group_key, group.group
                ):
                    # Not admitted into host DRAM: the group stays put
                    # (advertising an unadmitted tier would poison the
                    # index; kvlint KV008 has nothing to close here).
                    return False
            from_tier = group.tier
            group.tier = to_tier
            if from_tier == HBM:
                self._hbm_bytes -= group.nbytes
            events.append(
                BlockStored(
                    block_hashes=list(group.engine_hashes),
                    parent_block_hash=group.parent_hash,
                    token_ids=list(group.token_ids),
                    block_size=group.block_size,
                    medium=to_tier,
                )
            )
            events.append(
                BlockRemoved(
                    block_hashes=list(group.engine_hashes),
                    medium=from_tier,
                )
            )
            nbytes = group.nbytes
            family = group.family
        # Sink + feed registration OUTSIDE the lock (leaf discipline).
        if self._event_sink is not None:
            self._event_sink(events)
        if self._feed is not None and family is not None:
            self._feed.observe_keys([group_key], family)
        METRICS.tiering_demotions.labels(
            transition=_TRANSITION[from_tier]
        ).inc()
        METRICS.tiering_demotion_bytes.labels(
            transition=_TRANSITION[from_tier]
        ).inc(nbytes)
        return True

    def tiers(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for group in self._groups.values():
                out[group.tier] = out.get(group.tier, 0) + 1
            return out


@dataclass
class _DemotionRecord:
    at: float
    group_key: int
    transition: str
    nbytes: int
    idle_s: float
    predicted_next_use_s: Optional[float]
    forced_by_pressure: bool

    def to_dict(self) -> dict:
        return {
            "age_s": round(time.monotonic() - self.at, 1),
            "group": f"{self.group_key:016x}",
            "transition": self.transition,
            "nbytes": self.nbytes,
            "idle_s": round(self.idle_s, 3),
            "predicted_next_use_s": (
                None
                if self.predicted_next_use_s is None
                else round(self.predicted_next_use_s, 3)
            ),
            "forced_by_pressure": self.forced_by_pressure,
        }


class DemotionWorker:
    """Background policy loop over one :class:`DemotionTarget`.

    ``run_cycle()`` is the testable unit (scan -> rank -> demote);
    ``start()`` runs it every ``interval_s`` on a daemon thread until
    ``close()``.
    """

    def __init__(
        self,
        target: DemotionTarget,
        feed,
        config: Optional[DemotionConfig] = None,
    ) -> None:
        self.target = target
        self.feed = feed
        self.config = config or DemotionConfig()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = lockorder.tracked(
            threading.Lock(), "DemotionWorker._lock"
        )
        self._recent: deque = deque(maxlen=32)  # guarded-by: _lock
        self._cycles = 0  # guarded-by: _lock
        self._moves = 0  # guarded-by: _lock
        self._last_pressure = 0.0  # guarded-by: _lock

    def start(self) -> None:
        if self._thread is not None:
            return
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run, name="kvtpu-tiering-demotion", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("demotion cycle failed")

    def run_cycle(self, now: Optional[float] = None) -> int:
        """One scan -> rank -> demote pass; returns moves performed."""
        if now is None:
            now = time.monotonic()
        config = self.config
        snapshot = self.feed.refresh(now) if self.feed is not None else None
        pressure = self.target.pressure()
        candidates = self.target.scan()
        # Coldest first: expected next use descending (idle as the
        # tiebreak for unpredicted groups under pressure).
        ranked = []
        for candidate in candidates:
            expected = None
            if snapshot is not None and candidate.family is not None:
                prediction = snapshot.predictions.get(candidate.family)
                if prediction is not None:
                    expected = max(0.0, prediction.expected_next_use_s(now))
            ranked.append((candidate, expected))
        ranked.sort(
            key=lambda pair: (
                -(pair[1] if pair[1] is not None else -1.0),
                -pair[0].idle_s,
            )
        )
        moves = 0
        under_pressure = pressure >= config.pressure_watermark
        for candidate, expected in ranked:
            if moves >= config.max_moves_per_cycle:
                break
            if candidate.tier == HBM:
                due = candidate.idle_s >= config.demote_host_idle_s
                forced = under_pressure
                if not (due or forced):
                    continue
                if (
                    config.require_prediction
                    and expected is None
                    and not forced
                ):
                    # Cold but NOT reusable: leave it to plain eviction.
                    continue
                to_tier = HOST
            else:
                if candidate.idle_s < config.demote_storage_idle_s:
                    continue
                if config.require_prediction and expected is None:
                    continue
                to_tier = SHARED_STORAGE
                forced = False
            if self.target.demote(candidate.group_key, to_tier):
                moves += 1
                with self._lock:
                    self._moves += 1
                    self._recent.append(
                        _DemotionRecord(
                            at=now,
                            group_key=candidate.group_key,
                            transition=_TRANSITION[candidate.tier],
                            nbytes=candidate.nbytes,
                            idle_s=candidate.idle_s,
                            predicted_next_use_s=expected,
                            forced_by_pressure=forced,
                        )
                    )
        with self._lock:
            self._cycles += 1
            self._last_pressure = pressure
        return moves

    def stats(self) -> dict:
        with self._lock:
            return {
                "running": self._thread is not None,
                "cycles": self._cycles,
                "moves": self._moves,
                "last_pressure": round(self._last_pressure, 4),
                "config": {
                    "interval_s": self.config.interval_s,
                    "demote_host_idle_s": self.config.demote_host_idle_s,
                    "demote_storage_idle_s": (
                        self.config.demote_storage_idle_s
                    ),
                    "pressure_watermark": self.config.pressure_watermark,
                    "max_moves_per_cycle": self.config.max_moves_per_cycle,
                },
                "recent": [record.to_dict() for record in self._recent],
            }
