"""PolicyEngine: one handle over the four tiering engines.

Embedding applications construct one engine, bind it to the indexer's
cachestats ledger, and get:

* ``feed`` — the PolicyFeed (per-family reuse predictions + clusters);
* ``eviction_policy(backend)`` — a predictive ranker to hand to
  ``CostAwareIndexConfig.eviction_policy`` / ``HostTierCache``;
* ``advisor`` — the compute-or-load advisor (fed by the offload
  worker's load completions through ``observe_load``);
* ``start_demotion(target)`` — the proactive demotion worker.

The indexer calls :meth:`observe_scored` after each sampled scoring
request (outside index locks): it feeds the chain into the feed and
refreshes the policy snapshot at most every ``refresh_s`` seconds — a
cheap monotonic compare on the hot path, the full ledger export only
on the throttle's cadence (or the demotion worker's own cycles).

Wired by ``TIERING=1`` in the HTTP service, by
``PrecisePrefixCacheScorerConfig.tiering`` in the scheduler plugin,
and directly in tests/bench.  Every knob is env-resolvable
(docs/configuration.md §Tiering).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.tiering.advisor import (
    AdvisorConfig,
    ComputeOrLoadAdvisor,
)
from llm_d_kv_cache_manager_tpu.tiering.demotion import (
    DemotionConfig,
    DemotionWorker,
)
from llm_d_kv_cache_manager_tpu.tiering.eviction import (
    DEFAULT_SAMPLE,
    DEFAULT_UNKNOWN_NEXT_USE_S,
    PredictiveEvictionPolicy,
)
from llm_d_kv_cache_manager_tpu.tiering.policy_feed import (
    DEFAULT_CLUSTER_BLOCKS,
    DEFAULT_KEY_MAP_SIZE,
    PolicyFeed,
    PolicyFeedConfig,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tiering.engine")

DEFAULT_REFRESH_S = 1.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


@dataclass
class TieringConfig:
    # Minimum seconds between policy-snapshot refreshes triggered from
    # the scoring path (the demotion worker refreshes on its own
    # cycles regardless).
    refresh_s: float = DEFAULT_REFRESH_S
    feed: PolicyFeedConfig = field(default_factory=PolicyFeedConfig)
    # Predictive-eviction candidate sample + the unknown-key horizon.
    eviction_sample: int = DEFAULT_SAMPLE
    unknown_next_use_s: float = DEFAULT_UNKNOWN_NEXT_USE_S
    advisor: AdvisorConfig = field(default_factory=AdvisorConfig)
    demotion: DemotionConfig = field(default_factory=DemotionConfig)

    @classmethod
    def from_env(cls) -> "TieringConfig":
        return cls(
            refresh_s=_env_float("TIERING_REFRESH_S", DEFAULT_REFRESH_S),
            feed=PolicyFeedConfig(
                cluster_blocks=_env_int(
                    "TIERING_CLUSTER_BLOCKS", DEFAULT_CLUSTER_BLOCKS
                ),
                key_map_size=_env_int(
                    "TIERING_KEY_MAP_SIZE", DEFAULT_KEY_MAP_SIZE
                ),
            ),
            eviction_sample=_env_int(
                "TIERING_EVICTION_SAMPLE", DEFAULT_SAMPLE
            ),
            unknown_next_use_s=_env_float(
                "TIERING_UNKNOWN_NEXT_USE_S", DEFAULT_UNKNOWN_NEXT_USE_S
            ),
            advisor=AdvisorConfig(
                bytes_per_block=_env_int("TIERING_BLOCK_BYTES", 0),
                block_tokens=_env_int("BLOCK_SIZE", 16),
                prefill_tokens_per_s=_env_float(
                    "TIERING_PREFILL_TOKENS_PER_S", 0.0
                ),
                hybrid=os.environ.get("TIERING_HYBRID", "1").lower()
                not in ("0", "false", "off"),
            ),
            demotion=DemotionConfig(
                interval_s=_env_float("TIERING_DEMOTION_INTERVAL_S", 5.0),
                demote_host_idle_s=_env_float(
                    "TIERING_DEMOTE_HOST_IDLE_S", 30.0
                ),
                demote_storage_idle_s=_env_float(
                    "TIERING_DEMOTE_STORAGE_IDLE_S", 120.0
                ),
                pressure_watermark=_env_float(
                    "TIERING_PRESSURE_WATERMARK", 0.85
                ),
            ),
        )


class PolicyEngine:
    """Composition root for the tiering subsystem."""

    def __init__(
        self,
        ledger=None,
        config: Optional[TieringConfig] = None,
    ) -> None:
        self.config = config or TieringConfig.from_env()
        self.feed = PolicyFeed(ledger=ledger, config=self.config.feed)
        self.advisor = ComputeOrLoadAdvisor(self.config.advisor)
        self._workers = []
        self._policies = []
        # Lock-free throttle (GIL-atomic float store): a racy double
        # refresh is harmless, a missed one is caught next request.
        self._last_refresh = 0.0

    def bind_ledger(self, ledger) -> None:
        self.feed.bind_ledger(ledger)

    # -- scoring-path hook ----------------------------------------------

    def observe_scored(
        self,
        chain_keys: Sequence[int],
        family: Optional[int],
        now: Optional[float] = None,
    ) -> None:
        """Called by the indexer after each sampled scored request,
        outside every index lock.  Must never raise into scoring."""
        try:
            if now is None:
                now = time.monotonic()
            self.feed.observe_chain(chain_keys, family, now)
            self.maybe_refresh(now)
        except Exception:  # noqa: BLE001 — policy bugs stay out of scoring
            logger.exception("tiering observe failed")

    def maybe_refresh(self, now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        if now - self._last_refresh >= self.config.refresh_s:
            self._last_refresh = now
            self.feed.refresh(now)
            METRICS.tiering_snapshot_age.set(0.0)

    # -- factories -------------------------------------------------------

    def eviction_policy(
        self, backend: str = "cost_aware"
    ) -> PredictiveEvictionPolicy:
        policy = PredictiveEvictionPolicy(
            self.feed,
            backend=backend,
            sample=self.config.eviction_sample,
            unknown_next_use_s=self.config.unknown_next_use_s,
        )
        self._policies.append(policy)
        return policy

    def start_demotion(
        self,
        target,
        config: Optional[DemotionConfig] = None,
        start: bool = True,
    ) -> DemotionWorker:
        worker = DemotionWorker(
            target, self.feed, config or self.config.demotion
        )
        self._workers.append(worker)
        if start:
            worker.start()
        return worker

    def close(self) -> None:
        for worker in self._workers:
            worker.close()

    # -- status (the /debug/tiering payload) -----------------------------

    def status(self) -> dict:
        snapshot = self.feed.snapshot()
        METRICS.tiering_snapshot_age.set(
            max(0.0, time.monotonic() - snapshot.at)
            if snapshot.at
            else 0.0
        )
        return {
            "config": {
                "refresh_s": self.config.refresh_s,
                "cluster_blocks": self.config.feed.cluster_blocks,
                "key_map_size": self.config.feed.key_map_size,
                "eviction_sample": self.config.eviction_sample,
                "unknown_next_use_s": self.config.unknown_next_use_s,
            },
            "feed": self.feed.stats(),
            "advisor": self.advisor.stats(),
            "eviction": [policy.stats() for policy in self._policies],
            "demotion": [worker.stats() for worker in self._workers],
        }
