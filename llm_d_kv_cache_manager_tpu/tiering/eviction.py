"""Predictive eviction ranking: predicted-next-use x byte-cost.

The cost-aware index and the host-tier cache both evict
least-recently-used first.  Recency is a one-bit prediction ("used
recently => used again soon"); the ledger's per-family inter-arrival
EWMA is a real one — a block whose family returns every 2 seconds is
worth more than a same-cost block whose family returns hourly, however
recently the latter was touched.

Contract (what lets this run inside index locks):

* the backend hands :meth:`select_victim` a small LRU-ordered sample
  of ``(key, byte_cost)`` candidates (it already holds its own lock);
* the policy ranks them against the feed's latest immutable
  :class:`~..tiering.policy_feed.PolicySnapshot` — **no locks, no
  allocation beyond a few floats**, so the backend's lock-order leaf
  status is preserved (kvlint KV006: these backends stay leaves);
* score = ``expected_next_use_s x max(byte_cost, 1)``; the candidate
  with the **highest** score (needed farthest away, holding the most
  bytes) is evicted.  Keys the snapshot cannot predict fall back to an
  LRU-position proxy: the oldest unknown candidate gets the largest
  unknown score, so with no predictions at all the policy degrades to
  (byte-cost-weighted) LRU rather than noise.

``policy=None`` in the backends is the escape hatch AND the parity
oracle: the pristine pop-LRU-first code path runs, bit-identical to
pre-tiering behavior (pinned by tests/test_tiering.py and the bench's
``tiered_churn`` parity cell).  :data:`LRU_POLICY` exercises the
policy plumbing while still always choosing the LRU-first victim —
useful for asserting the plumbing itself changes nothing.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tiering.eviction")

DEFAULT_SAMPLE = 8
DEFAULT_UNKNOWN_NEXT_USE_S = 600.0


class LRUEvictionPolicy:
    """Escape hatch: always evicts the LRU-first candidate.

    Drives the exact same victim choice as ``policy=None`` (the
    backends' pristine pop-first path) through the policy plumbing —
    the parity oracle for the plumbing itself.
    """

    sample = 1

    def select_victim(
        self,
        candidates: Sequence[Tuple[int, int]],
        now: Optional[float] = None,
    ) -> int:
        return 0


LRU_POLICY = LRUEvictionPolicy()


class PredictiveEvictionPolicy:
    """Ranks eviction candidates by predicted-next-use x byte-cost.

    One instance per backend (its counters label a backend name); all
    instances share the engine's feed, reading whatever snapshot is
    current when an eviction happens.
    """

    def __init__(
        self,
        feed,
        backend: str = "cost_aware",
        sample: int = DEFAULT_SAMPLE,
        unknown_next_use_s: float = DEFAULT_UNKNOWN_NEXT_USE_S,
    ) -> None:
        if sample <= 0:
            raise ValueError("sample must be positive")
        self.feed = feed
        self.backend = backend
        self.sample = sample
        self.unknown_next_use_s = unknown_next_use_s
        # Racy-tolerant counters (read for /debug/tiering; increments
        # happen under the owning backend's lock, one writer at a time
        # per backend).
        self.predicted_choices = 0
        self.fallback_choices = 0
        self._predicted_child = METRICS.tiering_evictions.labels(
            backend=backend, mode="predicted"
        )
        self._fallback_child = METRICS.tiering_evictions.labels(
            backend=backend, mode="fallback_lru"
        )

    def select_victim(
        self,
        candidates: Sequence[Tuple[int, int]],
        now: Optional[float] = None,
    ) -> int:
        """Index (into ``candidates``) of the entry to evict.

        ``candidates`` are LRU-ordered (oldest first) ``(key,
        byte_cost)`` pairs.  Runs under the calling backend's lock:
        reads only the immutable snapshot, takes no locks itself.
        """
        if len(candidates) == 1:
            self.fallback_choices += 1
            self._fallback_child.inc()
            return 0
        if now is None:
            now = time.monotonic()
        snapshot = self.feed.snapshot()
        unknown_s = self.unknown_next_use_s
        n = len(candidates)
        best_index = 0
        best_score = -1.0
        any_prediction = False
        for i, (key, cost) in enumerate(candidates):
            expected = snapshot.expected_next_use_s(key, now)
            if expected is None:
                # LRU proxy: oldest unknown ranks as farthest away.
                expected = unknown_s * (n - i) / n
            else:
                any_prediction = True
                expected = max(0.0, expected)
            score = expected * max(cost, 1)
            if score > best_score:
                best_score = score
                best_index = i
        if any_prediction:
            self.predicted_choices += 1
            self._predicted_child.inc()
        else:
            self.fallback_choices += 1
            self._fallback_child.inc()
        return best_index

    def stats(self) -> dict:
        return {
            "backend": self.backend,
            "sample": self.sample,
            "unknown_next_use_s": self.unknown_next_use_s,
            "predicted_choices": self.predicted_choices,
            "fallback_choices": self.fallback_choices,
        }
