"""PolicyFeed: the stable reuse-prediction contract over the ledger.

The cachestats ledger (analytics/ledger.py) already keeps a per-family
inter-arrival EWMA — PR 7 shipped it explicitly as "ROADMAP-4's
eviction signal".  This module is the contract that makes the signal
consumable by policy code (eviction ranking, the demotion worker, the
compute-or-load advisor) without coupling any of them to ledger
internals or the ``/debug/cachestats`` payload shape:

* :class:`ReusePrediction` — one family's prediction: its EWMA of
  inter-arrival seconds, when it was last seen, and how the prediction
  was derived (own history vs its cluster's);
* :class:`PolicySnapshot` — an immutable point-in-time export: block
  key -> family, family -> prediction, cluster fallbacks.  Policy code
  (which often runs under index/cache locks) reads snapshots
  **lock-free**; only :meth:`PolicyFeed.refresh` touches ledger
  stripe locks, and never while holding the feed lock;
* :class:`PolicyFeed` — the live side: the scoring path calls
  :meth:`observe_chain` after each sampled request (outside index
  locks) so the feed learns which block keys belong to which family,
  and which coarse cluster each family belongs to.

Clustering (the HashEvict adaptation, PAPERS.md): chained block keys
ARE locality-sensitive hashes of the token prefix — two prompts share
a chain key iff they share every token up to it — so the key at block
``cluster_blocks - 1`` (coarser than the family key at
``family_blocks - 1``) clusters similar prefixes with zero extra
hashing and without storing token text.  A family seen only once has
no EWMA of its own; its cluster's EWMA is the fallback prediction, so
brand-new variants of a hot prefix inherit the family-of-families
rhythm instead of looking cold.

Key-space agnosticism: the feed never hashes anything itself — callers
observe whatever chain they score with (the indexer feeds request
keys; an engine-side user can feed its own engine hashes; the demotion
worker registers offload file hashes via :meth:`observe_keys`).  All
keys in one feed must share a key space, which the single-writer
wiring guarantees.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tiering.policy_feed")

DEFAULT_CLUSTER_BLOCKS = 2
DEFAULT_KEY_MAP_SIZE = 65536
DEFAULT_MAX_CLUSTERS = 4096
DEFAULT_MAX_FAMILIES = 8192

# Same smoothing as the ledger's family EWMA (analytics/ledger.py):
# the last ~6-7 arrivals dominate.
EWMA_ALPHA = 0.3

# The feed lock is a leaf: observe() does dict surgery only, and
# refresh() pulls the ledger BEFORE taking it (never nested).
# kvlint: lock-order: PolicyFeed._lock ascending
lockorder.declare_ascending("PolicyFeed._lock")


@dataclass(frozen=True)
class ReusePrediction:
    """One family's reuse forecast at a point in time."""

    family: int
    # EWMA of seconds between consecutive encounters.
    predicted_interarrival_s: float
    # time.monotonic() of the last encounter.
    last_seen: float
    # Encounters contributing ("family" source) or the cluster's count.
    requests: int
    # "family" = the family's own history; "cluster" = inherited from
    # its coarse-prefix cluster (the family was seen < 2 times).
    source: str = "family"

    def expected_next_use_s(self, now: float) -> float:
        """Seconds until the predicted next encounter.

        ``last_seen + ewma - now`` while the family is inside its
        rhythm; once overdue, the estimate backs off linearly — the
        longer a family stays silent past its own rhythm, the farther
        away (more likely never) its next use:
        ``max(last_seen + ewma - now, (now - last_seen) - ewma)``.
        Always >= 0 only at the exact due instant; callers clamp if
        they need non-negative values.
        """
        idle = now - self.last_seen
        ewma = self.predicted_interarrival_s
        return max(ewma - idle, idle - ewma)


@dataclass
class PolicyFeedConfig:
    # Coarse-prefix cluster identity: the chain key at this block - 1.
    # Must be <= the ledger's family_blocks for the containment to hold.
    cluster_blocks: int = DEFAULT_CLUSTER_BLOCKS
    # LRU bound on the block-key -> family map.
    key_map_size: int = DEFAULT_KEY_MAP_SIZE
    # LRU bound on tracked clusters.
    max_clusters: int = DEFAULT_MAX_CLUSTERS
    # LRU bound on the family -> cluster map (and so on snapshot
    # prediction size); sized past the ledger's own family LRU
    # (CACHESTATS_MAX_FAMILIES, default 4096) so the two evict in
    # roughly the same working set.
    max_families: int = DEFAULT_MAX_FAMILIES


class _ClusterStats:
    __slots__ = ("last_seen", "ewma_interarrival_s", "requests")

    def __init__(self, now: float) -> None:
        self.last_seen = now
        self.ewma_interarrival_s: Optional[float] = None
        self.requests = 1


class PolicySnapshot:
    """Immutable export of the feed + ledger state.

    Built by :meth:`PolicyFeed.refresh`; consumers hold a reference and
    read without any lock (the dicts are never mutated after
    construction).  ``expected_next_use_s`` is the one call policy code
    makes per candidate: key -> family -> prediction, with the cluster
    fallback applied at refresh time.
    """

    __slots__ = ("at", "key_family", "predictions")

    def __init__(
        self,
        at: float,
        key_family: Dict[int, int],
        predictions: Dict[int, ReusePrediction],
    ) -> None:
        self.at = at
        self.key_family = key_family
        self.predictions = predictions

    def family_of(self, key: int) -> Optional[int]:
        return self.key_family.get(key)

    def prediction_for_key(self, key: int) -> Optional[ReusePrediction]:
        family = self.key_family.get(key)
        if family is None:
            return None
        return self.predictions.get(family)

    def expected_next_use_s(
        self, key: int, now: Optional[float] = None
    ) -> Optional[float]:
        """Predicted seconds until the block named by ``key`` is needed
        again; None when the key's family (or its prediction) is
        unknown."""
        prediction = self.prediction_for_key(key)
        if prediction is None:
            return None
        if now is None:
            now = time.monotonic()
        return prediction.expected_next_use_s(now)

    def stats(self) -> dict:
        return {
            "keys_mapped": len(self.key_family),
            "families_predicted": len(self.predictions),
            "age_s": round(time.monotonic() - self.at, 3),
        }


_EMPTY_SNAPSHOT = PolicySnapshot(at=0.0, key_family={}, predictions={})


class PolicyFeed:
    """Live observation surface + snapshot factory.

    One feed per key space.  ``observe_chain`` is the per-request hook
    (called by the indexer after scoring, outside index locks, only
    for ledger-sampled requests — the feed's learning rate follows
    ``CACHESTATS_SAMPLE_RATE``); ``refresh`` is the periodic bulk
    export (called by the engine's throttle or the demotion worker's
    cycle, never per request).
    """

    def __init__(
        self,
        ledger=None,
        config: Optional[PolicyFeedConfig] = None,
    ) -> None:
        self.config = config or PolicyFeedConfig()
        if self.config.cluster_blocks <= 0:
            raise ValueError("cluster_blocks must be positive")
        self._ledger = ledger
        self._lock = lockorder.tracked(
            threading.Lock(), "PolicyFeed._lock"
        )
        # Insertion order == recency (move-to-end on repeat), the
        # ledger-stripe LRU idiom.
        self._key_family: Dict[int, int] = {}  # guarded-by: _lock
        self._family_cluster: Dict[int, int] = {}  # guarded-by: _lock
        self._clusters: Dict[int, _ClusterStats] = {}  # guarded-by: _lock
        self._observed = 0  # guarded-by: _lock
        # Latest snapshot; atomic reference swap, read lock-free.
        self._snapshot: PolicySnapshot = _EMPTY_SNAPSHOT
        self._refreshes = 0

    def bind_ledger(self, ledger) -> None:
        """Late ledger attachment (the Indexer constructs its own
        ledger; the engine binds after)."""
        # gil-atomic: late-bind wiring; single ref store before traffic
        self._ledger = ledger

    @property
    def ledger(self):
        return self._ledger

    # -- observation (hot-ish path: once per sampled scored request) --

    def observe_chain(
        self,
        chain_keys: Sequence[int],
        family: Optional[int],
        now: Optional[float] = None,
    ) -> None:
        """Learn from one scored request's chained block keys.

        Registers every chain key under ``family`` and folds the
        arrival into the family's cluster rhythm.  ``family`` is the
        ledger's family id for the same request (``family_key``); when
        None (empty chain) nothing is learned.
        """
        if family is None or not chain_keys:
            return
        if now is None:
            now = time.monotonic()
        cluster = chain_keys[
            min(self.config.cluster_blocks, len(chain_keys)) - 1
        ]
        with self._lock:
            self._observed += 1
            self._register_keys_locked(chain_keys, family)
            # Bounded family -> cluster map: move-to-end keeps
            # insertion order == recency, oldest evicts at the cap.
            if family in self._family_cluster:
                del self._family_cluster[family]
            elif len(self._family_cluster) >= self.config.max_families:
                del self._family_cluster[next(iter(self._family_cluster))]
            self._family_cluster[family] = cluster
            stats = self._clusters.get(cluster)
            if stats is None:
                if len(self._clusters) >= self.config.max_clusters:
                    del self._clusters[next(iter(self._clusters))]
                self._clusters[cluster] = _ClusterStats(now)
            else:
                # Move-to-end keeps insertion order == recency.
                del self._clusters[cluster]
                self._clusters[cluster] = stats
                interarrival = max(0.0, now - stats.last_seen)
                stats.ewma_interarrival_s = (
                    interarrival
                    if stats.ewma_interarrival_s is None
                    else EWMA_ALPHA * interarrival
                    + (1.0 - EWMA_ALPHA) * stats.ewma_interarrival_s
                )
                stats.last_seen = now
                stats.requests += 1

    def observe_keys(self, keys: Iterable[int], family: int) -> None:
        """Register extra keys under an already-observed family (the
        demotion worker maps offload file hashes to the family whose
        blocks it is moving, so host-tier eviction can rank them)."""
        with self._lock:
            self._register_keys_locked(list(keys), family)

    def _register_keys_locked(
        self, keys: Sequence[int], family: int
    ) -> None:
        """Insert/refresh key -> family mappings with LRU eviction.

        Room is made only for keys NOT already mapped (re-observing an
        at-capacity map's own keys must not evict unrelated entries —
        their predictions would silently degrade to the LRU proxy);
        already-present keys just move to the recency tail."""
        key_map = self._key_family
        overflow = (
            len(key_map)
            + sum(1 for key in keys if key not in key_map)
            - self.config.key_map_size
        )
        while overflow > 0 and key_map:
            del key_map[next(iter(key_map))]
            overflow -= 1
        for key in keys:
            if key in key_map:
                del key_map[key]
            key_map[key] = family
        # Final clamp: pre-eviction can undercount when an evicted
        # oldest key is simultaneously being re-observed.
        while len(key_map) > self.config.key_map_size:
            del key_map[next(iter(key_map))]

    # -- export ----------------------------------------------------------

    def prediction(
        self, family: int, now: Optional[float] = None
    ) -> Optional[ReusePrediction]:
        """Live per-family prediction: the family's own ledger EWMA
        when it has one, else its cluster's rhythm, else None.  Takes
        one ledger stripe lock; snapshot readers should prefer
        :meth:`snapshot`."""
        if now is None:
            now = time.monotonic()
        ledger = self._ledger
        if ledger is not None:
            detail = ledger.family_detail(family, now)
            if detail is not None and detail["ewma_interarrival_s"] is not None:
                return ReusePrediction(
                    family=family,
                    predicted_interarrival_s=detail["ewma_interarrival_s"],
                    last_seen=now - detail["idle_s"],
                    requests=detail["requests"],
                    source="family",
                )
        with self._lock:
            cluster = self._family_cluster.get(family)
            stats = self._clusters.get(cluster) if cluster is not None else None
            if stats is None or stats.ewma_interarrival_s is None:
                return None
            return ReusePrediction(
                family=family,
                predicted_interarrival_s=stats.ewma_interarrival_s,
                last_seen=stats.last_seen,
                requests=stats.requests,
                source="cluster",
            )

    def refresh(self, now: Optional[float] = None) -> PolicySnapshot:
        """Build + install a fresh snapshot.

        Ledger stripe locks are taken by ``reuse_predictions()``
        BEFORE the feed lock (one at a time, never nested with it), so
        the lock graph stays a forest of leaves.
        """
        if now is None:
            now = time.monotonic()
        ledger = self._ledger
        family_rows: Sequence[Tuple[int, float, float, int]] = (
            ledger.reuse_predictions() if ledger is not None else ()
        )
        predictions: Dict[int, ReusePrediction] = {}
        for family, ewma, last_seen, requests in family_rows:
            predictions[family] = ReusePrediction(
                family=family,
                predicted_interarrival_s=ewma,
                last_seen=last_seen,
                requests=requests,
                source="family",
            )
        with self._lock:
            key_family = dict(self._key_family)
            family_cluster = dict(self._family_cluster)
            clusters = {
                cluster: (
                    stats.ewma_interarrival_s,
                    stats.last_seen,
                    stats.requests,
                )
                for cluster, stats in self._clusters.items()
            }
        # Cluster fallback resolved AT REFRESH so snapshot reads stay
        # one dict hit: families the ledger has no EWMA for (seen once,
        # or evicted from the family table) inherit their cluster's.
        for family, cluster in family_cluster.items():
            if family in predictions:
                continue
            row = clusters.get(cluster)
            if row is None or row[0] is None:
                continue
            predictions[family] = ReusePrediction(
                family=family,
                predicted_interarrival_s=row[0],
                last_seen=row[1],
                requests=row[2],
                source="cluster",
            )
        snapshot = PolicySnapshot(
            at=now, key_family=key_family, predictions=predictions
        )
        # gil-atomic: immutable snapshot swap; readers see old or new
        self._snapshot = snapshot
        # gil-atomic: stats counter; refresh is single-threaded
        self._refreshes += 1
        return snapshot

    def snapshot(self) -> PolicySnapshot:
        """Latest refreshed snapshot (possibly the empty sentinel before
        the first refresh); never blocks, never takes locks."""
        return self._snapshot

    def stats(self) -> dict:
        with self._lock:
            observed = self._observed
            keys = len(self._key_family)
            clusters = len(self._clusters)
            families = len(self._family_cluster)
        out = {
            "observed_chains": observed,
            "keys_mapped": keys,
            "families_mapped": families,
            "clusters": clusters,
            "refreshes": self._refreshes,
            "snapshot": self._snapshot.stats(),
        }
        return out
