"""Staging-backed demotion target: demotion cycles move REAL bytes.

:class:`~llm_d_kv_cache_manager_tpu.tiering.demotion.PodTierState`
models residency — its demotions flip a tier tag and publish events.
This target makes the PR-8 state machine a data plane:

* ``hbm -> host``: the group's blocks are gathered from the TPU pool
  (block-major, pinned-host DMA when the backend has the memory space
  — the staging engine's primitive) and admitted into the
  :class:`~llm_d_kv_cache_manager_tpu.offload.host_tier.HostTierCache`
  **before** the ``host``-medium event publishes;
* ``host -> shared_storage``: the cached group is written to its
  block-hash file synchronously on the demotion thread (the engine's
  atomic tmp+rename primitive, :func:`~llm_d_kv_cache_manager_tpu.
  native.engine.store_file`) and the write **completes** before the
  ``shared_storage`` event publishes — the index never advertises a
  tier that does not hold the bytes yet.  The write deliberately does
  NOT ride the shared async engine: its completion stream is drained
  by the connector's ``get_finished`` poll, which would race the
  demotion thread's harvest.

Keying contract: ``group_key`` IS the group's offload **file hash**, so
the bytes this target pages into the host cache are served by the load
handlers' host-tier probe, and the files it writes are found by
``SharedStorageOffloadManager.lookup`` — one keyspace across the
demotion plane and the offload connector.

Measured write costs feed the advisor's write-side estimator
(``observe_store``) so demotion is priced from real transfers
(docs/host-offload.md).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from llm_d_kv_cache_manager_tpu.native.engine import store_file
from llm_d_kv_cache_manager_tpu.tiering.demotion import (
    HBM,
    HOST,
    SHARED_STORAGE,
    PodTierState,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tiering.staged_target")


class StagedDemotionTarget(PodTierState):
    """A :class:`PodTierState` whose transitions move group bytes.

    One per (pool, connector) pair; reuses the connector's file mapper
    and host cache so demoted bytes land exactly where the
    serving-path load handlers look for them.
    """

    def __init__(
        self,
        capacity_bytes: int,
        pool,
        file_mapper,
        host_cache,
        event_sink=None,
        feed=None,
        store_rtt_observer=None,
    ) -> None:
        if host_cache is None:
            raise ValueError(
                "StagedDemotionTarget needs a HostTierCache: without "
                "one the hbm->host rung has nowhere to put the bytes "
                "(use plain PodTierState for residency-only modeling)"
            )
        super().__init__(
            capacity_bytes,
            event_sink=event_sink,
            host_cache=host_cache,
            feed=feed,
        )
        self.pool = pool
        self.file_mapper = file_mapper
        self._store_rtt_observer = store_rtt_observer
        # group_key (= file hash) -> device block ids at registration.
        # Written only by register_pool_group before the group is
        # eligible, read by demote; per-key writes are atomic (GIL).
        self._block_ids: Dict[int, List[int]] = {}

    # -- registration -----------------------------------------------------

    def register_pool_group(
        self,
        group_key: int,
        block_ids: Sequence[int],
        engine_hashes: Sequence[int],
        token_ids: Sequence[int],
        parent_hash: Optional[int] = None,
        block_size: int = 16,
        family: Optional[int] = None,
        now: Optional[float] = None,
    ) -> None:
        """Admit a pool-resident group; bytes are derived from the
        pool's block geometry (``group_key`` must be the group's file
        hash — see module docstring)."""
        self._block_ids[group_key] = list(block_ids)
        self.register_group(
            group_key,
            engine_hashes=engine_hashes,
            token_ids=token_ids,
            nbytes=len(block_ids) * self.pool.block_nbytes,
            parent_hash=parent_hash,
            block_size=block_size,
            tier=HBM,
            family=family,
            now=now,
        )

    # -- the byte-moving transitions --------------------------------------

    def demote(self, group_key: int, to_tier: str) -> bool:
        if to_tier == HOST:
            return self._demote_to_host(group_key)
        if to_tier == SHARED_STORAGE:
            return self._demote_to_storage(group_key)
        return False

    def _demote_to_host(self, group_key: int) -> bool:
        block_ids = self._block_ids.get(group_key)
        if block_ids is None:
            return False
        try:
            # The staging primitive: device gather + transpose, pinned
            # DMA when supported — file-layout bytes in host DRAM.
            payload = self.pool.gather_block_major(block_ids)
        except Exception:
            logger.exception(
                "hbm->host gather failed for group %016x", group_key
            )
            return False
        with self._lock:
            group = self._groups.get(group_key)
            if group is None or group.tier != HBM:
                return False
            group.group = payload
        # Parent demote pages the payload into the host cache and
        # publishes store-before-remove events outside its lock.
        return super().demote(group_key, HOST)

    def _demote_to_storage(self, group_key: int) -> bool:
        with self._lock:
            group = self._groups.get(group_key)
            if group is None or group.tier != HOST:
                return False
        payload = (
            self._host_cache.get(group_key)
            if self._host_cache is not None
            else None
        )
        if payload is None:
            # Host copy already evicted: page from the pool (pinned
            # DMA when available) if the blocks are still registered.
            block_ids = self._block_ids.get(group_key)
            if block_ids is None:
                return False
            try:
                payload = self.pool.gather_block_major(block_ids)
            except Exception:
                logger.exception(
                    "host->storage gather failed for group %016x",
                    group_key,
                )
                return False
        t0 = time.perf_counter()
        # Synchronous atomic write on THIS thread — the shared async
        # engine's completion stream belongs to the connector's
        # get_finished poll (module docstring: harvest race).
        ok = store_file(
            self.file_mapper.get_file_name(group_key),
            np.ascontiguousarray(payload),
            skip_existing=True,
        )
        nbytes = payload.nbytes
        if not ok:
            logger.warning(
                "host->storage write failed for group %016x; "
                "tier NOT advanced",
                group_key,
            )
            return False
        if self._store_rtt_observer is not None:
            try:
                self._store_rtt_observer(
                    nbytes, time.perf_counter() - t0, None
                )
            except Exception:  # noqa: BLE001 — advisory feed only
                logger.exception("demotion store rtt observer failed")
        ok = super().demote(group_key, SHARED_STORAGE)
        if ok:
            # The group left host DRAM: free the cache entry and the
            # registration payload (the file is now the source).
            if self._host_cache is not None:
                self._host_cache.evict(group_key)
            with self._lock:
                group = self._groups.get(group_key)
                if group is not None:
                    group.group = None
        return ok
