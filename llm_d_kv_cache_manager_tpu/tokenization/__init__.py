from llm_d_kv_cache_manager_tpu.tokenization.pool import (  # noqa: F401
    TokenizationPool,
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: F401
    CompositeTokenizer,
    Encoding,
    LocalFastTokenizer,
    Tokenizer,
    TransformersTokenizer,
)
