"""Tokenization worker pool: the prompt -> tokens stage of the read path.

Sync (``tokenize`` blocks on a future) and async (``enqueue_tokenization``
fire-and-forget, warming the prefix store) modes over a bounded queue and N
worker threads, mirroring the reference pool's shape
(pkg/tokenization/pool.go).

Fast path: the prefix store resolves the prompt's cached prefix; a full
tokenizer run happens only when coverage < ``min_prefix_overlap_ratio``
(default 0.8).  Chat-completions requests are rendered to a prompt string
first, after which special tokens are NOT re-added (the template already
placed them — matching vLLM's serving behavior, pool.go:220-231).

Failed tasks retry up to 3 times, then fail the caller.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.obs.trace import (
    Trace,
    current_trace,
    span as obs_span,
    use_trace,
)
from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
    ApplyChatTemplateRequest,
    ChatTemplatingProcessor,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (
    LRUTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Tokenizer
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger, trace

logger = get_logger("tokenization.pool")

DEFAULT_WORKERS = 5
DEFAULT_MIN_PREFIX_OVERLAP_RATIO = 0.8
DEFAULT_MAX_RETRIES = 3


@dataclass
class TokenizationPoolConfig:
    workers: int = DEFAULT_WORKERS
    min_prefix_overlap_ratio: float = DEFAULT_MIN_PREFIX_OVERLAP_RATIO
    max_retries: int = DEFAULT_MAX_RETRIES
    queue_size: int = 10_000
    model_name: str = ""


@dataclass
class TokenizedPrompt:
    """One resolved tokenization: the token stream, the final prompt
    text it came from (chat-rendered when a template applied), and —
    when the prefix store carried a block-key memoization record — the
    already-chained block keys for the first ``len(memo_keys)`` full
    blocks of ``tokens`` (see docs/performance.md)."""

    tokens: List[int]
    text: str
    memo_keys: Tuple[int, ...] = field(default=())


@dataclass
class _Task:
    prompt: str
    model_name: str
    render_req: Optional[ApplyChatTemplateRequest]
    future: Optional["Future[TokenizedPrompt]"]
    attempts: int = 0
    # Token-processor hash-space identity for block-key memoization;
    # None skips the memo read on the worker-side store probe.
    key_space: Optional[tuple] = None
    # True when the submitting thread already probed the prefix store
    # for this exact prompt and missed: the worker skips its own probe
    # (one store read per miss, not two).  Chat-rendered and
    # fire-and-forget tasks were never pre-probed, so they keep the
    # worker-side probe.
    store_probed: bool = False
    # Explicit trace propagation across the pool boundary: the
    # submitting thread's active trace rides the task so worker-side
    # spans (queue wait, chat render, encode) land on the same trace.
    trace: Optional[Trace] = None
    submitted_at: float = 0.0


class TokenizationPool:
    def __init__(
        self,
        tokenizer: Tokenizer,
        prefix_store: LRUTokenStore,
        config: Optional[TokenizationPoolConfig] = None,
        chat_processor: Optional[ChatTemplatingProcessor] = None,
    ) -> None:
        self.config = config or TokenizationPoolConfig()
        if self.config.workers <= 0:
            raise ValueError("pool workers must be positive")
        self._tokenizer = tokenizer
        self._prefix_store = prefix_store
        self._chat_processor = chat_processor or ChatTemplatingProcessor()
        self._queue: "queue.Queue[Optional[_Task]]" = queue.Queue(
            self.config.queue_size
        )
        self._threads: List[threading.Thread] = []  # guarded-by: _lock
        # Lifecycle-only lock (start/shutdown); worker tokenization
        # never runs under it, so it stays a hierarchy leaf.
        self._lock = lockorder.tracked(
            threading.Lock(), "TokenizationPool._lock"
        )
        self._started = False  # guarded-by: _lock

    def set_tokenizer(self, tokenizer: Tokenizer, model_name: str) -> None:
        # gil-atomic: wiring-time single ref store before start()
        self._tokenizer = tokenizer
        self.config.model_name = model_name

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            for i in range(self.config.workers):
                thread = threading.Thread(
                    target=self._worker,
                    name=f"kvtpu-tokenize-{i}",
                    daemon=True,
                )
                thread.start()
                self._threads.append(thread)

    def shutdown(self) -> None:
        with self._lock:
            if not self._started:
                return
            for _ in self._threads:
                self._queue.put(None)
            for thread in self._threads:
                thread.join(timeout=10)
            self._threads.clear()
            self._started = False

    def tokenize(
        self,
        prompt: str,
        model_name: Optional[str] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
        timeout: Optional[float] = 60.0,
    ) -> List[int]:
        """Synchronous tokenization through the pool (tokens only)."""
        return self.tokenize_with_keys(
            prompt, model_name, render_req, None, timeout
        ).tokens

    def tokenize_with_keys(
        self,
        prompt: str,
        model_name: Optional[str] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
        key_space: Optional[tuple] = None,
        timeout: Optional[float] = 60.0,
    ) -> TokenizedPrompt:
        """Synchronous tokenization, with block-key memoization.

        Plain prompts probe the prefix store in the CALLING thread
        first: a steady-state scoring request whose stream is cached
        skips the queue + worker round-trip entirely (the pool exists
        to parallelize the SLOW full tokenizer, not a store read —
        the store is already read concurrently by the workers, so the
        extra reader is safe).  A miss carries ``store_probed`` on the
        queued task so the worker does not pay a second store read for
        the same prompt (the store could have been warmed while the
        task sat queued, but trading that sliver of extra coverage for
        one probe per miss is the right call on the hot path).
        Chat-rendered prompts must render first and stay on the
        queue.  ``key_space`` (the token processor's hash-space
        identity) opts the probe into returning the prefix's
        already-chained block keys alongside the tokens (the read-path
        fast lane; see docs/performance.md)."""
        probed = False
        if render_req is None:
            served = self._try_prefix_fast_path(
                prompt, model_name or self.config.model_name, key_space
            )
            if served is not None:
                return served
            probed = True
        future: "Future[TokenizedPrompt]" = Future()
        self._submit(
            prompt,
            model_name,
            render_req,
            future,
            store_probed=probed,
            key_space=key_space,
        )
        return future.result(timeout=timeout)

    def _try_prefix_fast_path(
        self,
        prompt: str,
        model_name: str,
        key_space: Optional[tuple] = None,
    ) -> Optional[TokenizedPrompt]:
        """The cached token stream when store coverage clears the
        fast-path threshold; None otherwise.  Shared by the sync
        caller path and the worker (_process)."""
        with obs_span("tokenize.prefix_probe", parent="tokenize") as s:
            probe = self._prefix_store.probe(prompt, model_name, key_space)
            s.set_attr("coverage", round(probe.coverage, 4))
        if probe.coverage >= self.config.min_prefix_overlap_ratio:
            METRICS.tokenization_prefix_fast_path.inc()
            trace(
                logger,
                "prefix-store fast path: %d tokens at %.2f coverage "
                "(%d memoized blocks)",
                len(probe.tokens),
                probe.coverage,
                probe.blocks,
            )
            return TokenizedPrompt(probe.tokens, prompt, probe.keys)
        return None

    def enqueue_tokenization(
        self,
        prompt: str,
        model_name: Optional[str] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
    ) -> None:
        """Fire-and-forget: warm the prefix store off the hot path."""
        self._submit(prompt, model_name, render_req, None)

    def _submit(
        self,
        prompt,
        model_name,
        render_req,
        future,
        store_probed=False,
        key_space=None,
    ) -> None:
        self.start()
        # Waiting callers (future set) carry their trace to the worker;
        # fire-and-forget warmers are not request-scoped.
        task_trace = current_trace() if future is not None else None
        self._queue.put(
            _Task(
                prompt=prompt,
                model_name=model_name or self.config.model_name,
                render_req=render_req,
                future=future,
                store_probed=store_probed,
                key_space=key_space,
                trace=task_trace,
                submitted_at=(
                    time.perf_counter() if task_trace is not None else 0.0
                ),
            )
        )

    def _worker(self) -> None:
        while True:
            task = self._queue.get()
            try:
                if task is None:
                    return
                self._run_task(task)
            finally:
                self._queue.task_done()

    def _run_task(self, task: _Task) -> None:
        # Queue wait recorded once, before the retry loop (retries are
        # worker-inline, not re-queued).
        if task.trace is not None:
            task.trace.add_completed(
                "tokenize.queue_wait", task.submitted_at, parent="tokenize"
            )
        # Retries run inline on this worker: re-enqueueing would block on a
        # full queue (deadlocking the pool under backend outage) and could
        # strand the task behind shutdown sentinels with its future
        # forever pending.
        while True:
            try:
                result = self._process(task)
            except Exception as exc:  # noqa: BLE001 — retried below
                task.attempts += 1
                if task.attempts < self.config.max_retries:
                    trace(
                        logger,
                        "tokenization attempt %d failed (%s); retrying",
                        task.attempts,
                        exc,
                    )
                    continue
                logger.error(
                    "tokenization failed after %d attempts: %s",
                    task.attempts,
                    exc,
                )
                if task.future is not None:
                    task.future.set_exception(exc)
                return
            if task.future is not None:
                task.future.set_result(result)
            return

    def _process(self, task: _Task) -> TokenizedPrompt:
        # Re-enter the submitter's trace on this worker thread so stage
        # spans (template, probe, encode) attach to the request.
        with use_trace(task.trace):
            return self._process_in_context(task)

    def _process_in_context(self, task: _Task) -> TokenizedPrompt:
        prompt = task.prompt
        # vLLM adds special tokens to raw completion prompts but not to
        # chat-rendered ones (the template already placed them).
        add_special_tokens = True
        if task.render_req is not None:
            with obs_span("tokenize.chat_template", parent="tokenize") as s:
                prompt = self._chat_processor.apply_chat_template(
                    task.model_name, task.render_req
                )
                s.set_attr("rendered_chars", len(prompt))
            add_special_tokens = False

        if not task.store_probed:
            served = self._try_prefix_fast_path(
                prompt, task.model_name, task.key_space
            )
            if served is not None:
                return served

        with obs_span("tokenize.encode", parent="tokenize") as s:
            encoding = self._tokenizer.encode(
                prompt, task.model_name, add_special_tokens
            )
            s.set_attr("tokens", len(encoding.tokens))
        self._prefix_store.add_tokenization(
            prompt, encoding.tokens, encoding.offsets, task.model_name
        )
        return TokenizedPrompt(encoding.tokens, prompt)
