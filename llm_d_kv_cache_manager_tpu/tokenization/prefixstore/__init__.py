from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (  # noqa: F401
    LRUStoreConfig,
    LRUTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.trie_store import (  # noqa: F401
    TrieTokenStore,
)
