"""Prefix store: amortizes tokenization on the scoring hot path.

Prompts in a KV-aware fleet share long prefixes (system prompts, few-shot
preambles).  The store caches *text-chunk -> tokens* so a new prompt's
shared prefix resolves to tokens without running the tokenizer; only when
coverage falls below the pool's overlap threshold does a full tokenization
run.

Design (capability parity: pkg/tokenization/prefixstore/lru_store.go):
fixed-size text chunks, chained xxhash64 keyed on
``little_endian(prev_hash) || chunk_bytes`` so a chunk's identity encodes
its whole prefix; each block stores the tokens whose end offset falls
inside the chunk; lookups walk the chain until the first miss and report
the covered fraction of the prompt.

This store is purely indexer-internal (no cross-system hash contract), so
it chunks the UTF-8 *bytes* of the prompt and expects tokenizer offsets in
byte units (see ``tokenization.tokenizers.Encoding``).

Read-path fast lane (docs/performance.md): alongside tokens, chunks can
carry *block-key memoization records* — the already-chained KV block keys
for the token prefix ending in that chunk, attached by the indexer after
it hashes a chain (:meth:`LRUTokenStore.attach_block_keys`) and returned
by :meth:`LRUTokenStore.probe` so a multi-turn conversation only hashes
its new suffix.  Records are keyed by ``(chunk hash, key space)`` where
the key space is the token processor's ``(seed hash, block size)``
identity.  The chunk hash pins the exact text prefix but NOT the token
split — a later tokenization of an overlapping prompt may re-split
tokens across a shared chunk boundary (straddling tokens belong to the
later chunk) — so each record also anchors the exact chunk token-tuple
OBJECTS its keys were derived from: every overwrite installs fresh
tuples, so an ``is``-walk at probe time (microseconds) proves the
tokens being returned are bit-identical to the ones the keys were
hashed from (attach validates content against its caller's token list,
so anchor identity implies token equality).  A failed check, like an
evicted or missing record, only costs a re-hash; records never need
explicit invalidation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence, Tuple

import xxhash

from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache

DEFAULT_CHUNK_BYTES = 256
DEFAULT_MAX_BLOCKS = 500_000


@dataclass
class LRUStoreConfig:
    cache_size: int = DEFAULT_MAX_BLOCKS
    # Chunk size in bytes of UTF-8 prompt text.
    block_size: int = DEFAULT_CHUNK_BYTES


def _chain_hash(prev_hash: int, chunk: bytes) -> int:
    # One C call over the concatenated input; bit-identical to the
    # two-update form (xxh64 is stream-position independent).
    return xxhash.xxh64_intdigest(
        prev_hash.to_bytes(8, "little") + chunk
    )


def _chain_seed(model_name: str) -> int:
    """Root of the chunk chain.

    Tokenizations from different models must never alias — the same text
    tokenized by two vocabularies yields different tokens — so the chain is
    rooted in the model name.
    """
    if not model_name:
        return 0
    return xxhash.xxh64(model_name.encode("utf-8")).intdigest()


def _chunk_hashes(data: bytes, model_name: str, size: int) -> List[int]:
    """Chained hash of each full ``size``-byte chunk of ``data``.

    The single definition of the chunking rule (stride, seed, tail
    handling): every chain walk — indexing, probing, attaching — must
    agree on which hash pairs with which chunk, so they all call here.
    Hashes depend only on the text, never on cache contents, so callers
    compute the whole chain up front (xxhash is C-speed) and batch
    their cache reads.
    """
    hashes: List[int] = []
    prev_hash = _chain_seed(model_name)
    for start in range(0, len(data) - size + 1, size):
        prev_hash = _chain_hash(prev_hash, data[start : start + size])
        hashes.append(prev_hash)
    return hashes


class ProbeResult(NamedTuple):
    """One prefix-store probe: cached tokens, their byte coverage, and —
    when a key space was supplied and a memo record matched — the
    already-chained block keys covering ``blocks`` full blocks of the
    returned token list (``keys[i]`` is the chain value after block
    ``i``; tokens beyond ``blocks * block_size`` still need hashing)."""

    tokens: List[int]
    coverage: float
    keys: Tuple[int, ...]
    blocks: int


class LRUTokenStore:
    def __init__(self, config: LRUStoreConfig | None = None) -> None:
        self.config = config or LRUStoreConfig()
        if self.config.block_size <= 0:
            raise ValueError("block_size must be positive")
        self._cache: LRUCache[int, Tuple[int, ...]] = LRUCache(
            self.config.cache_size
        )
        # chunk hash + key space -> (full blocks ending by that chunk,
        # shared block-key tuple).  One tuple object is shared by every
        # chunk record of an attach pass, so memory stays O(chain), not
        # O(chain^2).
        self._keys_cache: LRUCache[tuple, Tuple[int, Tuple[int, ...]]] = (
            LRUCache(self.config.cache_size)
        )

    def add_tokenization(
        self,
        prompt: str,
        tokens: Sequence[int],
        offsets: Sequence[Tuple[int, int]],
        model_name: str = "",
    ) -> None:
        """Index a full tokenization of ``prompt``.

        ``offsets[i]`` is the byte range of ``tokens[i]`` in the UTF-8
        prompt.  A token belongs to the chunk its *end* offset falls in;
        tokens straddling a boundary belong to the later chunk.
        """
        if not prompt or not tokens:
            return
        if len(tokens) != len(offsets):
            raise ValueError("tokens and offsets length mismatch")

        data = prompt.encode("utf-8")
        size = self.config.block_size
        token_idx = 0
        for i, chunk_hash in enumerate(_chunk_hashes(data, model_name, size)):
            end = (i + 1) * size
            block_tokens: List[int] = []
            while token_idx < len(tokens) and offsets[token_idx][1] <= end:
                block_tokens.append(tokens[token_idx])
                token_idx += 1
            self._cache.put(chunk_hash, tuple(block_tokens))

    def probe(
        self,
        prompt: str,
        model_name: str = "",
        key_space: Optional[tuple] = None,
    ) -> ProbeResult:
        """Walk the chunk chain until the first miss.

        Returns the concatenated tokens of the matched chunks, the
        fraction of the prompt's bytes they cover, and — when
        ``key_space`` is given — the deepest attached block-key record
        along the matched chain (empty when none is attached)."""
        tokens: List[int] = []
        data = prompt.encode("utf-8")
        size = self.config.block_size
        # Hash the whole chain first, then resolve every chunk in ONE
        # lock round-trip (peek_many) instead of a locked get per chunk.
        hashes = _chunk_hashes(data, model_name, size)
        coverage = 0.0
        keys: Tuple[int, ...] = ()
        blocks = 0
        matched = 0
        chunk_tuples: List[Tuple[int, ...]] = []
        if hashes:
            # peek (no recency) then touch ONLY the consumed prefix:
            # resident chunks beyond the first miss are unreachable
            # from this prompt, and promoting them would push other
            # prompts' live chunks out under LRU pressure (the same
            # invariant the index lookup keeps for its key chains).
            for block in self._cache.peek_many(hashes):
                if block is None:
                    break
                tokens.extend(block)
                chunk_tuples.append(block)
                matched += 1
            if matched:
                self._cache.touch_many(hashes[:matched])
            coverage = matched * size / len(data)
        if key_space is not None and matched:
            # Deepest attached record wins; records are monotone along
            # the chain, so scanning backward finds it on the first hit
            # (one memo read on the warm path, not one per chunk).
            keys_cache = self._keys_cache
            record = None
            for i in range(matched - 1, -1, -1):
                record = keys_cache.get((hashes[i], key_space))
                if record is not None:
                    break
            if record is not None:
                r_blocks, r_keys, n_chunks, anchors = record
                # Accept the record ONLY if every chunk token tuple it
                # was derived from is still the resident object (an
                # overwritten split installs fresh tuples): identity
                # implies the tokens being returned are bit-identical
                # to the ones the keys were hashed from — a stale
                # pairing would silently diverge scores.
                if n_chunks <= matched and n_chunks <= len(
                    anchors
                ) and all(
                    anchor is resident
                    for anchor, resident in zip(
                        anchors, chunk_tuples[:n_chunks]
                    )
                ):
                    blocks = r_blocks
                    keys = (
                        r_keys
                        if len(r_keys) == r_blocks
                        else r_keys[:r_blocks]
                    )
        return ProbeResult(tokens, coverage, keys, blocks)

    def attach_block_keys(
        self,
        prompt: str,
        model_name: str,
        key_space: tuple,
        block_keys: Sequence[int],
        tokens: Sequence[int],
        min_blocks: int = 0,
    ) -> int:
        """Attach a hashed block-key chain to the prompt's chunk chain.

        Called by the indexer after deriving ``block_keys`` from
        ``tokens`` — the token list this store resolved (or indexed)
        for ``prompt``.  Each matched chunk gets a record of how many
        full blocks its token prefix spans, pointing at one shared key
        tuple plus a signature of the exact token prefix the keys were
        hashed from (probe() verifies it before serving the record);
        returns the number of chunk records written.  Walking stops at
        the first chunk whose token entry is missing (evicted
        mid-flight) or whose cumulative token count diverges from
        ``tokens`` (overwritten by a different tokenization): beyond it
        the block alignment is unknown.

        ``min_blocks`` skips record writes for chunks covering no more
        than that many blocks: a multi-turn caller passes the depth its
        probe already resumed from, so only the NEW suffix's chunks pay
        a record write (records below that depth are value-identical —
        a chunk's block count and key prefix never change).
        """
        if not prompt or not block_keys:
            return 0
        shared = tuple(block_keys)
        block_size = key_space[1]
        data = prompt.encode("utf-8")
        size = self.config.block_size
        # Same hash-all-then-batch-read shape as probe(): the chunk
        # token entries resolve in ONE lock round-trip instead of a
        # locked peek per chunk.
        hashes = _chunk_hashes(data, model_name, size)
        if not hashes:
            return 0
        blocks_per_chunk = self._cache.peek_many(hashes)
        cum_tokens = 0
        anchors: List[Tuple[int, ...]] = []
        # (chunk_hash, blocks, n_chunks) records to publish once the
        # shared anchor tuple is final.
        pending: List[Tuple[int, int, int]] = []
        for chunk_hash, block in zip(hashes, blocks_per_chunk):
            if block is None:
                break
            cum_tokens += len(block)
            if cum_tokens > len(tokens) or list(block) != tokens[
                cum_tokens - len(block) : cum_tokens
            ]:
                # The resident chunk entries no longer describe the
                # tokenization our keys came from (overwritten by a
                # different split mid-flight): anchoring them would
                # pair our keys with someone else's tokens.
                break
            anchors.append(block)
            blocks = cum_tokens // block_size
            if blocks > len(shared):
                blocks = len(shared)
            if blocks > min_blocks:
                pending.append((chunk_hash, blocks, len(anchors)))
            if blocks == len(shared):
                # Every remaining chunk would claim the same (capped)
                # record; deeper chunks gain nothing.
                break
        if not pending:
            return 0
        anchors_shared = tuple(anchors)
        for chunk_hash, blocks, n_chunks in pending:
            self._keys_cache.put(
                (chunk_hash, key_space),
                (blocks, shared, n_chunks, anchors_shared),
            )
        return len(pending)

    def find_longest_contained_tokens(
        self, prompt: str, model_name: str = ""
    ) -> Tuple[List[int], float]:
        """Tokens + coverage of the longest cached chunk chain (the
        pre-fast-lane probe surface, kept for compatibility)."""
        result = self.probe(prompt, model_name)
        return result.tokens, result.coverage
