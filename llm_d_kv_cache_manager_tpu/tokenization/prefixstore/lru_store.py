"""Prefix store: amortizes tokenization on the scoring hot path.

Prompts in a KV-aware fleet share long prefixes (system prompts, few-shot
preambles).  The store caches *text-chunk -> tokens* so a new prompt's
shared prefix resolves to tokens without running the tokenizer; only when
coverage falls below the pool's overlap threshold does a full tokenization
run.

Design (capability parity: pkg/tokenization/prefixstore/lru_store.go):
fixed-size text chunks, chained xxhash64 keyed on
``little_endian(prev_hash) || chunk_bytes`` so a chunk's identity encodes
its whole prefix; each block stores the tokens whose end offset falls
inside the chunk; lookups walk the chain until the first miss and report
the covered fraction of the prompt.

This store is purely indexer-internal (no cross-system hash contract), so
it chunks the UTF-8 *bytes* of the prompt and expects tokenizer offsets in
byte units (see ``tokenization.tokenizers.Encoding``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import xxhash

from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache

DEFAULT_CHUNK_BYTES = 256
DEFAULT_MAX_BLOCKS = 500_000


@dataclass
class LRUStoreConfig:
    cache_size: int = DEFAULT_MAX_BLOCKS
    # Chunk size in bytes of UTF-8 prompt text.
    block_size: int = DEFAULT_CHUNK_BYTES


def _chain_hash(prev_hash: int, chunk: bytes) -> int:
    digest = xxhash.xxh64()
    digest.update(prev_hash.to_bytes(8, "little"))
    digest.update(chunk)
    return digest.intdigest()


def _chain_seed(model_name: str) -> int:
    """Root of the chunk chain.

    Tokenizations from different models must never alias — the same text
    tokenized by two vocabularies yields different tokens — so the chain is
    rooted in the model name.
    """
    if not model_name:
        return 0
    return xxhash.xxh64(model_name.encode("utf-8")).intdigest()


class LRUTokenStore:
    def __init__(self, config: LRUStoreConfig | None = None) -> None:
        self.config = config or LRUStoreConfig()
        if self.config.block_size <= 0:
            raise ValueError("block_size must be positive")
        self._cache: LRUCache[int, Tuple[int, ...]] = LRUCache(
            self.config.cache_size
        )

    def add_tokenization(
        self,
        prompt: str,
        tokens: Sequence[int],
        offsets: Sequence[Tuple[int, int]],
        model_name: str = "",
    ) -> None:
        """Index a full tokenization of ``prompt``.

        ``offsets[i]`` is the byte range of ``tokens[i]`` in the UTF-8
        prompt.  A token belongs to the chunk its *end* offset falls in;
        tokens straddling a boundary belong to the later chunk.
        """
        if not prompt or not tokens:
            return
        if len(tokens) != len(offsets):
            raise ValueError("tokens and offsets length mismatch")

        data = prompt.encode("utf-8")
        size = self.config.block_size
        prev_hash = _chain_seed(model_name)
        token_idx = 0
        for start in range(0, len(data) - size + 1, size):
            end = start + size
            prev_hash = _chain_hash(prev_hash, data[start:end])
            block_tokens: List[int] = []
            while token_idx < len(tokens) and offsets[token_idx][1] <= end:
                block_tokens.append(tokens[token_idx])
                token_idx += 1
            self._cache.put(prev_hash, tuple(block_tokens))

    def find_longest_contained_tokens(
        self, prompt: str, model_name: str = ""
    ) -> Tuple[List[int], float]:
        """Walk the chunk chain until the first miss.

        Returns the concatenated tokens of the matched chunks and the
        fraction of the prompt's bytes they cover.
        """
        tokens: List[int] = []
        data = prompt.encode("utf-8")
        size = self.config.block_size
        prev_hash = _chain_seed(model_name)
        coverage = 0.0
        for start in range(0, len(data) - size + 1, size):
            end = start + size
            prev_hash = _chain_hash(prev_hash, data[start:end])
            block = self._cache.get(prev_hash)
            if block is None:
                break
            tokens.extend(block)
            coverage = end / len(data)
        return tokens, coverage
