"""Alternative prefix store: byte trie with per-node token bookkeeping.

Capability parity with the reference's non-default trie store
(pkg/tokenization/prefixstore/trie_store.go): exact-prefix matching at byte
granularity in exchange for more memory and slower walks.  Each node
remembers how many tokens are fully contained in the prompt prefix ending
at that node, plus a reference to a token sequence passing through it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (
    ProbeResult,
)


class _Node:
    __slots__ = ("children", "token_count", "tokens_ref")

    def __init__(self) -> None:
        self.children: Dict[int, "_Node"] = {}
        # Tokens fully contained in the prefix ending here (None = unset).
        self.token_count: int = 0
        # A token sequence whose encoding path passes through this node;
        # its first `token_count` entries are valid at this node.
        self.tokens_ref: Sequence[int] = ()


class TrieTokenStore:
    def __init__(self, max_depth_bytes: int = 4096) -> None:
        # One trie root per model: vocabularies must never alias.
        self._roots: Dict[str, _Node] = {}
        self._max_depth = max_depth_bytes

    def _root_for(self, model_name: str) -> _Node:
        root = self._roots.get(model_name)
        if root is None:
            root = self._roots[model_name] = _Node()
        return root

    def add_tokenization(
        self,
        prompt: str,
        tokens: Sequence[int],
        offsets: Sequence[Tuple[int, int]],
        model_name: str = "",
    ) -> None:
        if not prompt or not tokens:
            return
        if len(tokens) != len(offsets):
            raise ValueError("tokens and offsets length mismatch")
        data = prompt.encode("utf-8")[: self._max_depth]
        ends = [offset[1] for offset in offsets]
        tokens = tuple(tokens)

        node = self._root_for(model_name)
        token_idx = 0
        for depth, byte in enumerate(data, start=1):
            node = node.children.setdefault(byte, _Node())
            while token_idx < len(ends) and ends[token_idx] <= depth:
                token_idx += 1
            if token_idx >= node.token_count:
                node.token_count = token_idx
                node.tokens_ref = tokens

    def find_longest_contained_tokens(
        self, prompt: str, model_name: str = ""
    ) -> Tuple[List[int], float]:
        data = prompt.encode("utf-8")
        node = self._root_for(model_name)
        best: Tuple[Sequence[int], int] = ((), 0)
        depth = 0
        for byte in data:
            child = node.children.get(byte)
            if child is None:
                break
            node = child
            depth += 1
            if node.token_count > best[1]:
                best = (node.tokens_ref, node.token_count)
        coverage = depth / len(data) if data else 0.0
        tokens_ref, count = best
        return list(tokens_ref[:count]), coverage

    def probe(
        self,
        prompt: str,
        model_name: str = "",
        key_space: Optional[tuple] = None,
    ) -> ProbeResult:
        """Interface parity with ``LRUTokenStore.probe``; the trie does
        not memoize block keys, so the record is always empty."""
        tokens, coverage = self.find_longest_contained_tokens(
            prompt, model_name
        )
        return ProbeResult(tokens, coverage, (), 0)

    def attach_block_keys(
        self,
        prompt: str,
        model_name: str,
        key_space: tuple,
        block_keys: Sequence[int],
        tokens: Sequence[int],
        min_blocks: int = 0,
    ) -> int:
        """No-op (no block-key memoization in the trie store)."""
        return 0
