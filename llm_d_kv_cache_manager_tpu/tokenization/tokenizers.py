"""Tokenizer backends.

The reference reaches HF's Rust tokenizers through cgo and a UDS sidecar
(pkg/tokenization/tokenizer.go, services/uds_tokenizer) because its host
language is Go.  Here the host *is* Python, so the Rust tokenizers bind in
directly — one process model, no sidecar tax (SURVEY §7.2).  Backends:

* ``LocalFastTokenizer`` — ``tokenizer.json`` from disk, with the same
  auto-discovery the reference does (direct path, ``<dir>/<model>/``, and
  HF-cache ``models--org--name/snapshots/*`` layouts,
  tokenizer.go:163-257).
* ``TransformersTokenizer`` — ``AutoTokenizer`` (hub or cache).
* ``CompositeTokenizer`` — ordered fallback with error accumulation and
  per-backend latency/token metrics (tokenizer.go:458-529).

All backends return byte-unit offsets (converted from the HF library's
char units) because the prefix store chunks UTF-8 bytes.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tokenization")


@dataclass
class Encoding:
    tokens: List[int]
    # Byte offsets (start, end) of each token in the UTF-8 prompt.
    offsets: List[Tuple[int, int]]


def load_auto_tokenizer(
    model_name: str,
    revision: Optional[str] = None,
    auth_token: Optional[str] = None,
):
    """Cache-first ``AutoTokenizer`` load.

    Tries the local HF cache before touching the hub: in zero-egress
    deployments the hub path burns minutes in connection retries per model
    before failing (observed in verification), and the local path is also
    faster when the model is cached.
    """
    from transformers import AutoTokenizer

    try:
        return AutoTokenizer.from_pretrained(
            model_name,
            revision=revision,
            token=auth_token,
            use_fast=True,
            local_files_only=True,
        )
    except Exception:
        if os.environ.get("HF_HUB_OFFLINE"):
            raise
        return AutoTokenizer.from_pretrained(
            model_name, revision=revision, token=auth_token, use_fast=True
        )


def char_offsets_to_byte_offsets(
    text: str, offsets: Sequence[Tuple[int, int]]
) -> List[Tuple[int, int]]:
    """Convert char-unit offsets (HF convention) to byte units."""
    if len(text) == len(text.encode("utf-8")):
        # Pure ASCII: char offsets already are byte offsets.
        return list(offsets)
    byte_at: List[int] = [0] * (len(text) + 1)
    total = 0
    for i, ch in enumerate(text):
        total += len(ch.encode("utf-8"))
        byte_at[i + 1] = total
    n = len(text)
    return [
        (byte_at[min(start, n)], byte_at[min(end, n)])
        for start, end in offsets
    ]


class Tokenizer(Protocol):
    def encode(
        self, prompt: str, model_name: str, add_special_tokens: bool
    ) -> Encoding:
        ...

    def type(self) -> str:
        ...


class LocalFastTokenizer:
    """Loads ``tokenizer.json`` files from a local directory tree."""

    def __init__(self, tokenizers_dir: str) -> None:
        self.tokenizers_dir = tokenizers_dir
        self._cache: Dict[str, object] = {}

    def type(self) -> str:
        return "local"

    def _discover(self, model_name: str) -> Optional[str]:
        base = self.tokenizers_dir
        candidates = [
            os.path.join(base, model_name, "tokenizer.json"),
            os.path.join(base, model_name.replace("/", "--"), "tokenizer.json"),
        ]
        # HF cache layout: models--org--name/snapshots/<rev>/tokenizer.json
        hub_dir = os.path.join(
            base, "models--" + model_name.replace("/", "--"), "snapshots"
        )
        if os.path.isdir(hub_dir):
            for revision in sorted(os.listdir(hub_dir)):
                candidates.append(
                    os.path.join(hub_dir, revision, "tokenizer.json")
                )
        if model_name.endswith(".json"):
            candidates.append(os.path.join(base, model_name))
        for path in candidates:
            if os.path.isfile(path):
                return path
        return None

    def _load(self, model_name: str):
        cached = self._cache.get(model_name)
        if cached is not None:
            return cached
        path = self._discover(model_name)
        if path is None:
            raise FileNotFoundError(
                f"no tokenizer.json for {model_name!r} under "
                f"{self.tokenizers_dir!r}"
            )
        from tokenizers import Tokenizer as FastTokenizer

        tokenizer = FastTokenizer.from_file(path)
        self._cache[model_name] = tokenizer
        logger.info("loaded local tokenizer for %s from %s", model_name, path)
        return tokenizer

    def encode(
        self, prompt: str, model_name: str, add_special_tokens: bool
    ) -> Encoding:
        tokenizer = self._load(model_name)
        encoding = tokenizer.encode(
            prompt, add_special_tokens=add_special_tokens
        )
        return Encoding(
            tokens=list(encoding.ids),
            offsets=char_offsets_to_byte_offsets(prompt, encoding.offsets),
        )


class TransformersTokenizer:
    """``AutoTokenizer``-based backend (hub download or local cache)."""

    def __init__(self, auth_token: Optional[str] = None) -> None:
        self._auth_token = auth_token or os.environ.get("HF_TOKEN")
        self._cache: Dict[str, object] = {}

    def type(self) -> str:
        return "transformers"

    def _load(self, model_name: str):
        cached = self._cache.get(model_name)
        if cached is not None:
            return cached
        tokenizer = load_auto_tokenizer(
            model_name, auth_token=self._auth_token
        )
        self._cache[model_name] = tokenizer
        return tokenizer

    def encode(
        self, prompt: str, model_name: str, add_special_tokens: bool
    ) -> Encoding:
        tokenizer = self._load(model_name)
        output = tokenizer(
            prompt,
            add_special_tokens=add_special_tokens,
            return_offsets_mapping=True,
        )
        return Encoding(
            tokens=list(output["input_ids"]),
            offsets=char_offsets_to_byte_offsets(
                prompt, output["offset_mapping"]
            ),
        )


class CompositeTokenizer:
    """Ordered fallback across backends, with per-backend metrics."""

    def __init__(self, backends: Sequence[Tokenizer]) -> None:
        if not backends:
            raise ValueError("composite tokenizer needs at least one backend")
        self.backends = list(backends)

    def type(self) -> str:
        return "composite(" + ",".join(b.type() for b in self.backends) + ")"

    def encode(
        self, prompt: str, model_name: str, add_special_tokens: bool
    ) -> Encoding:
        errors: List[str] = []
        for backend in self.backends:
            start = time.perf_counter()
            try:
                encoding = backend.encode(
                    prompt, model_name, add_special_tokens
                )
            except Exception as exc:  # try the next backend
                errors.append(f"{backend.type()}: {exc}")
                continue
            METRICS.tokenization_latency.labels(backend.type()).observe(
                time.perf_counter() - start
            )
            METRICS.tokenization_tokens.labels(backend.type()).inc(
                len(encoding.tokens)
            )
            return encoding
        raise RuntimeError(
            f"all tokenizer backends failed for {model_name!r}: "
            + "; ".join(errors)
        )
