"""UDS gRPC tokenizer client backend.

Counterpart of the reference's Go client
(pkg/tokenization/uds_tokenizer.go:58-182): connects to the tokenizer
sidecar over a Unix-domain socket, 100 MB message caps, keepalive, and a
5-attempt exponential-backoff init.  Implements the ``Tokenizer``
protocol so it slots into ``CompositeTokenizer`` ahead of or behind the
in-process backends.
"""

from __future__ import annotations

import time
from typing import Optional

import grpc

from llm_d_kv_cache_manager_tpu.api import tokenizer_pb2
from llm_d_kv_cache_manager_tpu.api.grpc_services import (
    TokenizationServiceStub,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    Encoding,
    char_offsets_to_byte_offsets,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("tokenization.uds")

MAX_MESSAGE_BYTES = 100 * 1024 * 1024
INIT_RETRIES = 5
INIT_BACKOFF_SECONDS = 0.2


class UdsTokenizer:
    """Tokenizes via the sidecar service (services/uds_tokenizer.py)."""

    def __init__(
        self,
        uds_path: str = "/tmp/kvcache_tokenizer.sock",
        timeout_seconds: float = 30.0,
    ) -> None:
        self.uds_path = uds_path
        self.timeout_seconds = timeout_seconds
        self._channel = grpc.insecure_channel(
            f"unix://{uds_path}",
            options=[
                ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ("grpc.keepalive_time_ms", 30_000),
                ("grpc.keepalive_timeout_ms", 10_000),
            ],
        )
        self._stub = TokenizationServiceStub(self._channel)

    def type(self) -> str:
        return "uds"

    def close(self) -> None:
        self._channel.close()

    def initialize_model(self, model_name: str) -> None:
        """Pre-warm with retry/backoff (uds_tokenizer.go:113-142)."""
        last_error: Optional[Exception] = None
        for attempt in range(INIT_RETRIES):
            try:
                response = self._stub.InitializeTokenizer(
                    tokenizer_pb2.InitializeTokenizerRequest(
                        model_name=model_name
                    ),
                    timeout=self.timeout_seconds,
                )
                if response.success:
                    return
                last_error = RuntimeError(response.error_message)
            except grpc.RpcError as exc:
                last_error = exc
            if attempt < INIT_RETRIES - 1:
                time.sleep(INIT_BACKOFF_SECONDS * (2**attempt))
        raise RuntimeError(
            f"tokenizer init failed for {model_name!r} after "
            f"{INIT_RETRIES} attempts: {last_error}"
        )

    def encode(
        self, prompt: str, model_name: str, add_special_tokens: bool
    ) -> Encoding:
        response = self._stub.Tokenize(
            tokenizer_pb2.TokenizeRequest(
                input=prompt,
                model_name=model_name,
                add_special_tokens=add_special_tokens,
            ),
            timeout=self.timeout_seconds,
        )
        if not response.success:
            raise RuntimeError(
                f"sidecar tokenize failed: {response.error_message}"
            )
        pairs = list(response.offset_pairs)
        offsets = list(zip(pairs[0::2], pairs[1::2]))
        return Encoding(
            tokens=list(response.input_ids),
            offsets=char_offsets_to_byte_offsets(prompt, offsets),
        )
