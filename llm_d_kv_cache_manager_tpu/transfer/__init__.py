"""KV-transfer planning plane: transfer-aware routing, pod-to-pod
block movement, instant-warm scale-out.

The scorer (kvcache/scorer.py) answers "who already holds the longest
prefix"; this package answers "who could *cheaply get it*" — the
planning plane between scoring and the tier/offload machinery:

* :mod:`planner` — :class:`TransferPlanner` prices pod-to-pod block
  movement against recompute using the tiering advisor's measured
  read- and write-side RTT estimators, and tracks plans in a bounded
  TTL registry;
* :mod:`directives` — :class:`TransferExecutor` validates a plan
  against the live index and publishes real ``BlockStored`` /
  ``BlockRemoved`` KVEvents through the ingestion-pool sink, so the
  index, ledger, and cluster journal observe the move through the
  ordinary decode/apply path;
* :mod:`warmup` — instant-warm scale-out: a cold pod registers, the
  planner bulk-plans its share of hot families (ranked by cachestats
  ``reuse_predictions()``), and a budgeted worker drains the queue;
* :mod:`engine` — :class:`TransferEngine`, the composition root wired
  by ``TRANSFER=1`` in the HTTP service and directly in tests/bench.

See docs/transfer.md for the plan lifecycle, the pricing formula, and
the warm-up state machine.
"""

from llm_d_kv_cache_manager_tpu.transfer.directives import TransferExecutor
from llm_d_kv_cache_manager_tpu.transfer.engine import (
    TransferConfig,
    TransferEngine,
)
from llm_d_kv_cache_manager_tpu.transfer.planner import (
    DONE,
    EXECUTING,
    EXPIRED,
    INVALIDATED,
    PLANNED,
    TransferPlan,
    TransferPlanner,
)
from llm_d_kv_cache_manager_tpu.transfer.warmup import (
    HotFamilyCatalog,
    HotFamilyRecord,
    WarmupWorker,
)

__all__ = [
    "DONE",
    "EXECUTING",
    "EXPIRED",
    "INVALIDATED",
    "PLANNED",
    "HotFamilyCatalog",
    "HotFamilyRecord",
    "TransferConfig",
    "TransferEngine",
    "TransferExecutor",
    "TransferPlan",
    "TransferPlanner",
    "WarmupWorker",
]
