"""Transfer execution: the directive channel's write side.

A :class:`TransferPlan` is advice until something moves bytes.  The
executor is that something for the in-repo reference path (tests, the
bench's virtual fleet, the smoke gate): it validates the plan against
the *live* index, then publishes real ``BlockStored``/``BlockRemoved``
KVEvents through the same ingestion-pool sink the demotion worker uses
(:func:`tiering.demotion.pool_event_sink`), so the index, the
cachestats ledger, and the cluster journal all observe the move
through the ordinary decode/apply path — no side door.

Two safety properties the tests pin:

* **No phantom entries.**  Before publishing anything the executor
  re-reads the source pod's residency.  A source that died (or evicted
  the chain) after planning invalidates the plan and publishes
  NOTHING; a partially-evicted chain executes only the surviving
  prefix.
* **Demotion-race safe.**  The source tier recorded at plan time may
  be stale — a demotion worker can move the chain down a rung between
  plan and execute.  The executor re-reads the *current* tier from the
  index at execute time, so a "move" removes from the tier the source
  actually holds, never the tier the plan remembered.

``mode="copy"`` (the default, and all warm-up uses) leaves the source
untouched: pod-to-pod replication.  ``mode="move"`` also removes the
source entries — store-before-remove, same as demotion, so a scorer
racing the transfer never sees an empty window.
"""

from __future__ import annotations

from typing import Optional

from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.tiering.demotion import pool_event_sink
from llm_d_kv_cache_manager_tpu.transfer.planner import (
    DONE,
    EXECUTING,
    INVALIDATED,
    PLANNED,
    TransferPlan,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("transfer.directives")


class TransferExecutor:
    """Execute plans against a kvblock index via a kvevents pool."""

    def __init__(self, index, pool, model_name: str) -> None:
        self.index = index
        self.pool = pool
        self.model_name = model_name
        self._executed = 0
        self._invalidated = 0

    def _surviving_prefix(self, plan: TransferPlan) -> int:
        """How many leading blocks the source still holds, per the
        live index (0 = chain gone or source dead)."""
        found = self.index.lookup(
            plan.block_keys, {plan.source_pod}
        )
        n = 0
        for key in plan.block_keys:
            entries = found.get(key)
            if not entries:
                break
            n += 1
        return n

    def _current_source_tier(self, plan: TransferPlan) -> Optional[str]:
        found = self.index.lookup(
            plan.block_keys[:1], {plan.source_pod}
        )
        for entries in found.values():
            for entry in entries:
                if entry.pod_identifier == plan.source_pod:
                    return entry.device_tier
        return None

    def execute(self, plan: TransferPlan, mode: str = "copy") -> bool:
        """Run one plan; True iff events were published."""
        if plan.state != PLANNED:
            METRICS.transfer_executions.labels(outcome="stale").inc()
            return False
        plan.state = EXECUTING
        surviving = self._surviving_prefix(plan)
        if surviving == 0:
            # Source died (or evicted the chain) after planning: the
            # plan is void and NO events flow — publishing would plant
            # phantom residency at the target for bytes nobody holds.
            plan.state = INVALIDATED
            self._invalidated += 1
            METRICS.transfer_executions.labels(outcome="invalidated").inc()
            logger.warning(
                "plan %d invalidated: %s no longer holds the chain",
                plan.plan_id,
                plan.source_pod,
            )
            return False
        # Re-read the tier NOW — a demotion may have moved the chain
        # since plan time (the transfer-vs-demotion race).
        source_tier = self._current_source_tier(plan) or plan.tier
        hashes = list(plan.engine_hashes[:surviving])
        tokens = list(plan.token_ids[: surviving * plan.block_size])
        stored = BlockStored(
            block_hashes=hashes,
            parent_block_hash=None,
            token_ids=tokens,
            block_size=plan.block_size,
            # The target receives into device memory: transfers warm
            # the fast tier, that is their point.
            medium="hbm",
        )
        pool_event_sink(self.pool, plan.target_pod, self.model_name)(
            [stored]
        )
        if mode == "move":
            pool_event_sink(
                self.pool, plan.source_pod, self.model_name
            )([BlockRemoved(block_hashes=hashes, medium=source_tier)])
        plan.state = DONE
        self._executed += 1
        nbytes = (
            plan.nbytes * surviving // plan.blocks
            if plan.blocks
            else 0
        )
        outcome = "moved" if mode == "move" else "copied"
        if surviving < plan.blocks:
            outcome = f"partial-{outcome}"
        METRICS.transfer_executions.labels(outcome=outcome).inc()
        METRICS.transfer_bytes.inc(nbytes)
        return True

    def stats(self) -> dict:
        return {
            "executed": self._executed,
            "invalidated": self._invalidated,
        }
