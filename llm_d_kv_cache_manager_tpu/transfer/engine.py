"""TransferEngine: one handle over the KV-transfer planning plane.

Composition root mirroring tiering's :class:`PolicyEngine`: the HTTP
service (``TRANSFER=1``), the bench's scale-out regime, the smoke
gate, and tests construct one engine and get:

* ``planner`` — the priced pod-to-pod :class:`TransferPlanner`;
* ``catalog`` — the hot-family holder registry, fed automatically
  from scored traffic through :meth:`plan_for_chain`;
* ``attach_executor(index, pool, model_name)`` — binds the event
  channel (the kvevents ingestion pool) and builds the executor +
  warm-up worker;
* ``plan_for_chain(...)`` — the scoring-path hook: given scorer
  provenance + pod loads, return a transfer directive (or None), and
  note the holder in the catalog either way.  Must never raise into
  scoring, same contract as ``PolicyEngine.observe_scored``.

Every knob is env-resolvable (docs/configuration.md §KV-transfer):
``TRANSFER_LOAD_THRESHOLD``, ``TRANSFER_MIN_BLOCKS``,
``TRANSFER_PRICE_MARGIN``, ``TRANSFER_MAX_PLANS``, ``TRANSFER_TTL_S``,
``TRANSFER_REPLAN_COOLDOWN_S``, ``TRANSFER_WARMUP_FAMILIES``,
``TRANSFER_WARMUP_INTERVAL_S``, ``TRANSFER_WARMUP_MOVES``.

When tiering is also enabled the engines share one
``ComputeOrLoadAdvisor`` (pass it in), so transfer pricing rides the
same measured RTT models the offload plane feeds; standalone, the
engine builds its own from the ``TIERING_*`` advisor knobs.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

from llm_d_kv_cache_manager_tpu.tiering.advisor import ComputeOrLoadAdvisor
from llm_d_kv_cache_manager_tpu.transfer.directives import TransferExecutor
from llm_d_kv_cache_manager_tpu.transfer.planner import TransferPlanner
from llm_d_kv_cache_manager_tpu.transfer.warmup import (
    HotFamilyCatalog,
    WarmupWorker,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("transfer.engine")

DEFAULT_LOAD_THRESHOLD = 4.0
DEFAULT_MIN_BLOCKS = 2
DEFAULT_PRICE_MARGIN = 0.1
DEFAULT_MAX_PLANS = 256
DEFAULT_TTL_S = 30.0
DEFAULT_REPLAN_COOLDOWN_S = 5.0
DEFAULT_WARMUP_FAMILIES = 8
DEFAULT_WARMUP_INTERVAL_S = 1.0
DEFAULT_WARMUP_MOVES = 4


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


@dataclass
class TransferConfig:
    # Queue depth at (or above) which the best holder counts as
    # overloaded and a transfer is considered.
    load_threshold: float = DEFAULT_LOAD_THRESHOLD
    # Smallest matched prefix worth moving.
    min_blocks: int = DEFAULT_MIN_BLOCKS
    # Transfer must beat recompute by this fraction to be planned.
    price_margin: float = DEFAULT_PRICE_MARGIN
    max_plans: int = DEFAULT_MAX_PLANS
    ttl_s: float = DEFAULT_TTL_S
    # A hot chain gets one live plan at a time, and after one lands it
    # is not re-planned to the same target within this window.
    replan_cooldown_s: float = DEFAULT_REPLAN_COOLDOWN_S
    warmup_families: int = DEFAULT_WARMUP_FAMILIES
    warmup_interval_s: float = DEFAULT_WARMUP_INTERVAL_S
    warmup_moves: int = DEFAULT_WARMUP_MOVES

    @classmethod
    def from_env(cls) -> "TransferConfig":
        return cls(
            load_threshold=_env_float(
                "TRANSFER_LOAD_THRESHOLD", DEFAULT_LOAD_THRESHOLD
            ),
            min_blocks=_env_int(
                "TRANSFER_MIN_BLOCKS", DEFAULT_MIN_BLOCKS
            ),
            price_margin=_env_float(
                "TRANSFER_PRICE_MARGIN", DEFAULT_PRICE_MARGIN
            ),
            max_plans=_env_int("TRANSFER_MAX_PLANS", DEFAULT_MAX_PLANS),
            ttl_s=_env_float("TRANSFER_TTL_S", DEFAULT_TTL_S),
            replan_cooldown_s=_env_float(
                "TRANSFER_REPLAN_COOLDOWN_S", DEFAULT_REPLAN_COOLDOWN_S
            ),
            warmup_families=_env_int(
                "TRANSFER_WARMUP_FAMILIES", DEFAULT_WARMUP_FAMILIES
            ),
            warmup_interval_s=_env_float(
                "TRANSFER_WARMUP_INTERVAL_S", DEFAULT_WARMUP_INTERVAL_S
            ),
            warmup_moves=_env_int(
                "TRANSFER_WARMUP_MOVES", DEFAULT_WARMUP_MOVES
            ),
        )


class TransferEngine:
    """Composition root for the transfer subsystem."""

    def __init__(
        self,
        advisor: Optional[ComputeOrLoadAdvisor] = None,
        ledger=None,
        config: Optional[TransferConfig] = None,
    ) -> None:
        self.config = config or TransferConfig.from_env()
        if advisor is None:
            # Standalone: own advisor from the shared TIERING_* knobs
            # (the pricing inputs are the same measured RTT models).
            from llm_d_kv_cache_manager_tpu.tiering.engine import (
                TieringConfig,
            )

            advisor = ComputeOrLoadAdvisor(TieringConfig.from_env().advisor)
        self.advisor = advisor
        self.ledger = ledger
        self.planner = TransferPlanner(
            advisor,
            load_threshold=self.config.load_threshold,
            min_blocks=self.config.min_blocks,
            price_margin=self.config.price_margin,
            max_plans=self.config.max_plans,
            ttl_s=self.config.ttl_s,
            replan_cooldown_s=self.config.replan_cooldown_s,
        )
        self.catalog = HotFamilyCatalog()
        self.executor: Optional[TransferExecutor] = None
        self.warmup: Optional[WarmupWorker] = None

    def bind_ledger(self, ledger) -> None:
        self.ledger = ledger
        if self.warmup is not None:
            self.warmup.ledger = ledger

    def attach_executor(
        self, index, pool, model_name: str, start_warmup: bool = True
    ) -> TransferExecutor:
        """Bind the event channel; builds executor + warm-up worker."""
        self.executor = TransferExecutor(index, pool, model_name)
        self.warmup = WarmupWorker(
            self.catalog,
            self.planner,
            self.executor,
            ledger=self.ledger,
            warmup_families=self.config.warmup_families,
            interval_s=self.config.warmup_interval_s,
            moves_per_cycle=self.config.warmup_moves,
        )
        if start_warmup:
            self.warmup.start()
        return self.executor

    # -- scoring-path hook ----------------------------------------------

    def plan_for_chain(
        self,
        per_pod: Dict[str, dict],
        pod_loads: Optional[Dict[str, float]],
        block_keys: Sequence[int],
        token_ids: Optional[Sequence[int]] = None,
        block_size: int = 16,
    ) -> Optional[dict]:
        """Called by the indexer on the planned/explained scoring path,
        outside every index lock.  Notes the holder in the hot-family
        catalog, runs the planner, returns a directive dict (or None).
        Must never raise into scoring."""
        try:
            self._note_holder(per_pod, block_keys, token_ids, block_size)
            self.planner.expire()
            plan, outcome = self.planner.plan(
                per_pod,
                dict(pod_loads or {}),
                block_keys,
                token_ids=token_ids,
                block_size=block_size,
            )
            if plan is None:
                return {"planned": False, "outcome": outcome}
            return dict(plan.to_directive(), planned=True, outcome=outcome)
        except Exception:  # noqa: BLE001 — planner bugs stay out of scoring
            logger.exception("transfer planning failed")
            return None

    def _note_holder(
        self, per_pod, block_keys, token_ids, block_size
    ) -> None:
        if not block_keys:
            return
        holders = {
            pod: d for pod, d in per_pod.items() if d.get("score", 0) > 0
        }
        if not holders:
            return
        holder = min(
            holders, key=lambda p: (-holders[p].get("score", 0.0), p)
        )
        blocks = int(holders[holder].get("blocks_matched") or 0)
        if blocks <= 0:
            return
        family = self._family(block_keys)
        if family is None:
            return
        from llm_d_kv_cache_manager_tpu.transfer.planner import _pick_tier

        self.catalog.note(
            family,
            holder,
            list(block_keys)[:blocks],
            token_ids=list(token_ids or [])[: blocks * block_size],
            block_size=block_size,
            tier=_pick_tier(holders[holder].get("tiers")),
        )

    def _family(self, block_keys: Sequence[int]) -> Optional[int]:
        if self.ledger is not None:
            try:
                return self.ledger.family_key(
                    list(block_keys), len(block_keys)
                )
            except Exception:  # noqa: BLE001 — fall back to the
                # key-based family id below; the catalog stays usable
                # even if the ledger's keyspace disagrees.
                logger.debug(
                    "ledger family_key failed; using chain head",
                    exc_info=True,
                )
        # No ledger: the chain's first key identifies the family well
        # enough for the catalog (chained hashing commits to prefixes).
        return block_keys[0] if block_keys else None

    # -- warm-up passthroughs -------------------------------------------

    def register_cold_pod(self, pod_identifier: str) -> int:
        if self.warmup is None:
            raise RuntimeError(
                "attach_executor() before register_cold_pod()"
            )
        return self.warmup.register_cold_pod(pod_identifier)

    def run_warmup_cycle(self) -> int:
        if self.warmup is None:
            return 0
        return self.warmup.run_cycle()

    def invalidate_pod(self, pod_identifier: str) -> int:
        return self.planner.invalidate_pod(pod_identifier)

    def close(self) -> None:
        if self.warmup is not None:
            self.warmup.close()

    # -- status (the /debug/transfer payload) ----------------------------

    def status(self) -> dict:
        return {
            "config": {
                "load_threshold": self.config.load_threshold,
                "min_blocks": self.config.min_blocks,
                "price_margin": self.config.price_margin,
                "max_plans": self.config.max_plans,
                "ttl_s": self.config.ttl_s,
                "warmup_families": self.config.warmup_families,
                "warmup_interval_s": self.config.warmup_interval_s,
                "warmup_moves": self.config.warmup_moves,
            },
            "planner": self.planner.stats(),
            "catalog": self.catalog.stats(),
            "advisor": self.advisor.stats(),
            "executor": (
                self.executor.stats() if self.executor else None
            ),
            "warmup": self.warmup.status() if self.warmup else None,
        }
