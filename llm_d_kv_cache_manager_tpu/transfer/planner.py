"""TransferPlanner: price pod-to-pod KV movement against recompute.

The scorer answers "who already holds the longest prefix"; the planner
answers the enterprise follow-up "who could *cheaply get it*".  Given
the scorer's per-pod provenance (``LongestPrefixScorer.explain``
detail: score, blocks matched, tier histogram), per-pod load signals
(queue depths, the same signal ``LOAD_BLEND`` folds into routing), and
the tiering advisor's measured read- AND write-side RTT estimators, it
produces a :class:`TransferPlan`: move the matched block chain from
the overloaded holder to an underloaded target, priced as

    transfer_s  = rtt.estimate(nbytes) + estimate_store_s(nbytes)
    recompute_s = blocks * block_tokens / prefill_tokens_per_s

and only planned when ``transfer_s < recompute_s * (1 - margin)``
(the compute-or-load split, write side included because the target
must *store* what the source streams out).  No RTT observations yet
means no plan — recompute is the only priced option, exactly the
advisor's "no-rtt-observations" posture.

Plans live in a bounded registry with a TTL so a dead scheduler never
leaks them; ``invalidate_pod`` kills every plan touching a departed
pod before the executor can publish phantom index entries.

Decision outcomes (the ``kvtpu_transfer_plans_total`` label values)::

    planned                a plan was produced
    holder-not-overloaded  best holder below TRANSFER_LOAD_THRESHOLD
    no-holder              no pod scored above zero
    no-target              no pod both less loaded than the holder
                           and with real headroom (load below half
                           the threshold) — copying onto a busy pod
                           spreads overload instead of relieving it
    too-few-blocks         matched prefix below TRANSFER_MIN_BLOCKS
    no-block-bytes         bytes-per-block unconfigured (can't price)
    no-rtt-observations    read estimator has no signal -> recompute
    recompute-cheaper      priced, and recompute won
    in-flight              a live plan for this chain already exists
    recently-transferred   this chain landed on this target within
                           TRANSFER_REPLAN_COOLDOWN_S
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("transfer.planner")

# Plan lifecycle states (docs/transfer.md).
PLANNED = "planned"
EXECUTING = "executing"
DONE = "done"
INVALIDATED = "invalidated"
EXPIRED = "expired"

# Deterministic tier preference when a holder spans several tiers: the
# executor reads the *current* tier at execute time anyway, this only
# seeds the directive.
_TIER_ORDER = ("hbm", "host", "shared_storage")

# TransferPlanner._lock is a leaf: metrics and planning math happen
# outside it; only the registry mutates under it.
# kvlint: lock-order: TransferPlanner._lock ascending
lockorder.declare_ascending("TransferPlanner._lock")


@dataclass
class TransferPlan:
    """One priced pod-to-pod block movement."""

    plan_id: int
    source_pod: str
    target_pod: str
    # Request-chain keys (index keys) for the matched prefix.
    block_keys: List[int]
    # Engine-side hashes as the KVEvents will carry them (default: the
    # request keys themselves — the ingestion pool re-derives request
    # keys from token_ids, so any stable engine id works).
    engine_hashes: List[int]
    token_ids: List[int]
    block_size: int
    # Source-side tier the chain was observed on at plan time.
    tier: str
    blocks: int
    nbytes: int
    est_transfer_s: Optional[float]
    est_recompute_s: Optional[float]
    reason: str
    state: str = PLANNED
    created_at: float = 0.0

    def to_dict(self) -> dict:
        return {
            "plan_id": self.plan_id,
            "source_pod": self.source_pod,
            "target_pod": self.target_pod,
            "blocks": self.blocks,
            "nbytes": self.nbytes,
            "tier": self.tier,
            "est_transfer_s": self.est_transfer_s,
            "est_recompute_s": self.est_recompute_s,
            "reason": self.reason,
            "state": self.state,
        }

    def to_directive(self) -> dict:
        """The wire form riding the scoring response: everything a
        scheduler needs to route to ``target_pod`` with a fetch
        instruction, nothing that only the executor needs."""
        return {
            "plan_id": self.plan_id,
            "source_pod": self.source_pod,
            "target_pod": self.target_pod,
            "block_keys": list(self.block_keys),
            "blocks": self.blocks,
            "nbytes": self.nbytes,
            "tier": self.tier,
            "est_transfer_s": self.est_transfer_s,
            "est_recompute_s": self.est_recompute_s,
            "reason": self.reason,
        }


def _pick_tier(tiers: Optional[Dict[str, int]]) -> str:
    """Deterministic dominant tier from an explain histogram."""
    if not tiers:
        return _TIER_ORDER[0]
    best = max(
        tiers.items(),
        key=lambda kv: (
            kv[1],
            # Prefer the faster tier on a count tie (stable order).
            -_TIER_ORDER.index(kv[0]) if kv[0] in _TIER_ORDER else -99,
        ),
    )
    return best[0]


class TransferPlanner:
    """Produce and track :class:`TransferPlan` instances.

    Deterministic by construction: plan ids come from a counter, the
    holder is the max-score pod with a lexicographic tiebreak, the
    target the min-load pod with the same tiebreak, and no wall-clock
    or randomness enters the directive — the plan-determinism test
    pins this.
    """

    def __init__(
        self,
        advisor,
        load_threshold: float = 4.0,
        min_blocks: int = 2,
        price_margin: float = 0.1,
        max_plans: int = 256,
        ttl_s: float = 30.0,
        replan_cooldown_s: float = 5.0,
    ) -> None:
        self.advisor = advisor
        self.load_threshold = load_threshold
        self.min_blocks = min_blocks
        self.price_margin = price_margin
        self.max_plans = max_plans
        self.ttl_s = ttl_s
        self.replan_cooldown_s = replan_cooldown_s
        self._lock = lockorder.tracked(
            threading.Lock(), "TransferPlanner._lock"
        )
        # guarded-by: _lock — insertion-ordered for bounded eviction.
        self._plans: "OrderedDict[int, TransferPlan]" = OrderedDict()
        self._next_id = 1  # guarded-by: _lock
        self._outcomes: Dict[str, int] = {}  # guarded-by: _lock

    # -- pricing ---------------------------------------------------------

    def _prefill_rate(self) -> float:
        cfg_rate = getattr(self.advisor.config, "prefill_tokens_per_s", 0.0)
        if cfg_rate and cfg_rate > 0:
            return cfg_rate
        measured = self.advisor.prefill_tokens_per_s
        return measured if measured else 0.0

    def price(self, blocks: int) -> Tuple[Optional[float], Optional[float]]:
        """(est_transfer_s, est_recompute_s) for a ``blocks`` chain;
        either side is None when its estimator has no signal."""
        bpb = getattr(self.advisor.config, "bytes_per_block", 0)
        transfer_s: Optional[float] = None
        if bpb and bpb > 0:
            nbytes = blocks * bpb
            read_s = self.advisor.rtt.estimate(nbytes)
            if read_s is not None:
                store_s = self.advisor.estimate_store_s(nbytes) or 0.0
                transfer_s = read_s + store_s
        rate = self._prefill_rate()
        recompute_s = (
            blocks * self.advisor.config.block_tokens / rate
            if rate > 0
            else None
        )
        return transfer_s, recompute_s

    # -- the decision ----------------------------------------------------

    def plan(
        self,
        per_pod: Dict[str, dict],
        pod_loads: Dict[str, float],
        block_keys: Sequence[int],
        token_ids: Optional[Sequence[int]] = None,
        block_size: int = 16,
        engine_hashes: Optional[Sequence[int]] = None,
        now: Optional[float] = None,
    ) -> Tuple[Optional[TransferPlan], str]:
        """Decide for one scored request.

        ``per_pod`` is the scorer-explain provenance (``score``,
        ``blocks_matched``, ``tiers`` per pod); ``pod_loads`` maps pod
        to queue depth.  Returns ``(plan, outcome)`` — plan is None for
        every outcome except ``"planned"``.
        """
        if now is None:
            now = time.monotonic()
        outcome = self._decide(per_pod, pod_loads)
        if isinstance(outcome, str):
            self._count(outcome)
            return None, outcome
        holder, target, detail = outcome
        damped = self._damped(list(block_keys), target, now)
        if damped is not None:
            self._count(damped)
            return None, damped
        blocks = int(detail.get("blocks_matched") or 0)
        transfer_s, recompute_s = self.price(blocks)
        bpb = getattr(self.advisor.config, "bytes_per_block", 0)
        if not bpb or bpb <= 0:
            self._count("no-block-bytes")
            return None, "no-block-bytes"
        if transfer_s is None:
            # Zero-RTT edge: no measurements yet -> recompute is the
            # only priced option; never plan on a guess.
            self._count("no-rtt-observations")
            return None, "no-rtt-observations"
        reason = "priced"
        if recompute_s is None:
            # Transfer measurable, recompute unknown: plan, flagged.
            reason = "no-prefill-rate"
        elif transfer_s >= recompute_s * (1.0 - self.price_margin):
            self._count("recompute-cheaper")
            return None, "recompute-cheaper"
        keys = list(block_keys)[:blocks]
        tokens = list(token_ids or [])[: blocks * block_size]
        plan = self._register(
            TransferPlan(
                plan_id=0,  # assigned under the lock
                source_pod=holder,
                target_pod=target,
                block_keys=keys,
                engine_hashes=(
                    list(engine_hashes)[:blocks]
                    if engine_hashes is not None
                    else list(keys)
                ),
                token_ids=tokens,
                block_size=block_size,
                tier=_pick_tier(detail.get("tiers")),
                blocks=blocks,
                nbytes=blocks * bpb,
                est_transfer_s=transfer_s,
                est_recompute_s=recompute_s,
                reason=reason,
            ),
            now=now,
        )
        self._count("planned")
        return plan, "planned"

    def _decide(self, per_pod, pod_loads):
        """Holder/target selection; returns an outcome string or
        ``(holder, target, holder_detail)``."""
        scored = {
            pod: d for pod, d in per_pod.items() if d.get("score", 0) > 0
        }
        if not scored:
            return "no-holder"
        holder = min(
            scored, key=lambda p: (-scored[p].get("score", 0.0), p)
        )
        detail = scored[holder]
        holder_load = float(pod_loads.get(holder, 0.0))
        if holder_load < self.load_threshold:
            return "holder-not-overloaded"
        if int(detail.get("blocks_matched") or 0) < self.min_blocks:
            return "too-few-blocks"
        # A target must have real headroom, not merely be less loaded
        # than the holder: when the whole fleet is saturated, copying a
        # family onto a busy pod evicts that pod's own hot blocks and
        # spreads the overload instead of relieving it.
        headroom = self.load_threshold / 2.0
        candidates = [
            pod
            for pod in set(per_pod) | set(pod_loads)
            if pod != holder
            and float(pod_loads.get(pod, 0.0)) < holder_load
            and float(pod_loads.get(pod, 0.0)) < headroom
        ]
        if not candidates:
            return "no-target"
        target = min(
            candidates, key=lambda p: (float(pod_loads.get(p, 0.0)), p)
        )
        return holder, target, detail

    def _damped(
        self, block_keys: List[int], target: str, now: float
    ) -> Optional[str]:
        """Replan damping: scoring is per-request but a hot chain is
        scored thousands of times a second, and without idempotency
        every call would mint another copy of the same transfer —
        thrashing the fleet's pools with duplicate replicas.  One live
        plan per chain at a time; after it lands, the same chain goes
        to the same target at most once per cooldown window."""
        if not block_keys:
            return None
        head = block_keys[0]
        with self._lock:
            for plan in self._plans.values():
                if not plan.block_keys or plan.block_keys[0] != head:
                    continue
                if plan.state in (PLANNED, EXECUTING):
                    return "in-flight"
                if (
                    plan.state == DONE
                    and plan.target_pod == target
                    and now - plan.created_at < self.replan_cooldown_s
                ):
                    return "recently-transferred"
        return None

    def plan_warmup(
        self,
        source_pod: str,
        target_pod: str,
        block_keys: Sequence[int],
        engine_hashes: Optional[Sequence[int]] = None,
        token_ids: Optional[Sequence[int]] = None,
        block_size: int = 16,
        tier: str = "hbm",
        now: Optional[float] = None,
    ) -> TransferPlan:
        """Bulk pre-placement plan for a cold pod: the decision is
        already made (the warm-up worker ranked the family hot), so no
        load/price gate — pricing is recorded for reporting only."""
        blocks = len(block_keys)
        bpb = getattr(self.advisor.config, "bytes_per_block", 0) or 0
        transfer_s, recompute_s = self.price(blocks)
        plan = self._register(
            TransferPlan(
                plan_id=0,
                source_pod=source_pod,
                target_pod=target_pod,
                block_keys=list(block_keys),
                engine_hashes=(
                    list(engine_hashes)
                    if engine_hashes is not None
                    else list(block_keys)
                ),
                token_ids=list(token_ids or []),
                block_size=block_size,
                tier=tier,
                blocks=blocks,
                nbytes=blocks * bpb,
                est_transfer_s=transfer_s,
                est_recompute_s=recompute_s,
                reason="warmup",
            ),
            now=now,
        )
        self._count("warmup")
        return plan

    # -- registry --------------------------------------------------------

    def _register(
        self, plan: TransferPlan, now: Optional[float] = None
    ) -> TransferPlan:
        if now is None:
            now = time.monotonic()
        plan.created_at = now
        with self._lock:
            plan.plan_id = self._next_id
            self._next_id += 1
            self._plans[plan.plan_id] = plan
            while len(self._plans) > self.max_plans:
                self._plans.popitem(last=False)
        return plan

    def _count(self, outcome: str) -> None:
        with self._lock:
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
        METRICS.transfer_plans.labels(outcome=outcome).inc()

    def get(self, plan_id: int) -> Optional[TransferPlan]:
        with self._lock:
            return self._plans.get(plan_id)

    def mark(self, plan_id: int, state: str) -> None:
        with self._lock:
            plan = self._plans.get(plan_id)
            if plan is not None:
                plan.state = state

    def invalidate_pod(self, pod_identifier: str) -> int:
        """Kill every live plan touching a departed pod (source gone:
        nothing to copy; target gone: nowhere to put it).  Returns the
        number invalidated."""
        n = 0
        with self._lock:
            for plan in self._plans.values():
                if plan.state not in (PLANNED, EXECUTING):
                    continue
                if pod_identifier in (plan.source_pod, plan.target_pod):
                    plan.state = INVALIDATED
                    n += 1
        if n:
            METRICS.transfer_plans.labels(outcome="pod-invalidated").inc(n)
        return n

    def expire(self, now: Optional[float] = None) -> int:
        """TTL sweep: planned-but-never-executed plans go stale."""
        if now is None:
            now = time.monotonic()
        n = 0
        with self._lock:
            for plan in self._plans.values():
                if (
                    plan.state == PLANNED
                    and now - plan.created_at >= self.ttl_s
                ):
                    plan.state = EXPIRED
                    n += 1
        if n:
            METRICS.transfer_plans.labels(outcome="expired").inc(n)
        return n

    def stats(self) -> dict:
        with self._lock:
            by_state: Dict[str, int] = {}
            for plan in self._plans.values():
                by_state[plan.state] = by_state.get(plan.state, 0) + 1
            recent = [
                p.to_dict() for p in list(self._plans.values())[-8:]
            ]
            return {
                "config": {
                    "load_threshold": self.load_threshold,
                    "min_blocks": self.min_blocks,
                    "price_margin": self.price_margin,
                    "max_plans": self.max_plans,
                    "ttl_s": self.ttl_s,
                    "replan_cooldown_s": self.replan_cooldown_s,
                },
                "plans": len(self._plans),
                "by_state": by_state,
                "outcomes": dict(self._outcomes),
                "recent": recent,
            }
