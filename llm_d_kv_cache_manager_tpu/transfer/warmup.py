"""Instant-warm scale-out: pre-place hot families on cold pods.

Today a scale-out event costs cache-warmup minutes: the new pod joins
with an empty KV cache, scores zero on every prefix, and only warms by
taking misses.  The warm-up plane turns that into seconds: when a pod
registers cold, the planner bulk-plans its share of the fleet's hot
prefix families — ranked by the cachestats ledger's
``reuse_predictions()`` (shortest reuse interval first, i.e. hottest)
— and a budgeted worker drains the plan queue a few transfers per
cycle, publishing real KVEvents so the new pod's scores rise through
the ordinary index path.

The :class:`HotFamilyCatalog` is the bridge: the transfer engine notes
every scored chain's holder + block keys as traffic flows (the ledger
knows *which* families are hot, the catalog knows *where* their bytes
live and what the chain is), so ``register_cold_pod`` can turn a
ranked family list into executable plans without re-scoring anything.

State machine, per cold pod (docs/transfer.md)::

    cold --register_cold_pod: rank + bulk-plan--> warming
    warming --run_cycle x N: queue drains--> warm

``kvtpu_transfer_cold_pods`` gauges pods still warming;
``kvtpu_transfer_warmup_moves_total`` counts executed pre-placements.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("transfer.warmup")

DEFAULT_CATALOG_SIZE = 1024

# kvlint: lock-order: HotFamilyCatalog._lock ascending
lockorder.declare_ascending("HotFamilyCatalog._lock")
# kvlint: lock-order: WarmupWorker._lock ascending
lockorder.declare_ascending("WarmupWorker._lock")


@dataclass
class HotFamilyRecord:
    """Where one prefix family's bytes live and what the chain is."""

    family: int
    holder_pod: str
    block_keys: List[int]
    engine_hashes: List[int]
    token_ids: List[int]
    block_size: int
    tier: str = "hbm"
    last_seen: float = 0.0


class HotFamilyCatalog:
    """Bounded family -> holder/chain registry, fed from scored
    traffic by the transfer engine (and directly by tests/bench)."""

    def __init__(self, max_families: int = DEFAULT_CATALOG_SIZE) -> None:
        self.max_families = max_families
        self._lock = lockorder.tracked(
            threading.Lock(), "HotFamilyCatalog._lock"
        )
        # guarded-by: _lock — insertion-ordered for bounded eviction.
        self._records: "OrderedDict[int, HotFamilyRecord]" = OrderedDict()

    def note(
        self,
        family: int,
        holder_pod: str,
        block_keys: Sequence[int],
        engine_hashes: Optional[Sequence[int]] = None,
        token_ids: Optional[Sequence[int]] = None,
        block_size: int = 16,
        tier: str = "hbm",
        now: Optional[float] = None,
    ) -> None:
        """Record (or refresh) a family's holder.  A longer observed
        chain replaces a shorter one; a newer holder replaces an older
        one at equal length (residency drifts with traffic)."""
        if not block_keys:
            return
        if now is None:
            now = time.monotonic()
        record = HotFamilyRecord(
            family=family,
            holder_pod=holder_pod,
            block_keys=list(block_keys),
            engine_hashes=(
                list(engine_hashes)
                if engine_hashes is not None
                else list(block_keys)
            ),
            token_ids=list(token_ids or []),
            block_size=block_size,
            tier=tier,
            last_seen=now,
        )
        with self._lock:
            old = self._records.pop(family, None)
            if old is not None and len(old.block_keys) > len(
                record.block_keys
            ):
                old.last_seen = now
                record = old
            self._records[family] = record
            while len(self._records) > self.max_families:
                self._records.popitem(last=False)

    def get(self, family: int) -> Optional[HotFamilyRecord]:
        with self._lock:
            return self._records.get(family)

    def families(self) -> List[int]:
        with self._lock:
            return list(self._records)

    def stats(self) -> dict:
        with self._lock:
            return {
                "families": len(self._records),
                "max_families": self.max_families,
            }


class WarmupWorker:
    """Budgeted drain of per-pod warm-up plan queues.

    Run either as a daemon thread (``start()``; the HTTP service's
    ``TRANSFER=1`` path) or by pumping :meth:`run_cycle` directly
    (tests, the bench's virtual clock, the smoke gate).
    """

    def __init__(
        self,
        catalog: HotFamilyCatalog,
        planner,
        executor,
        ledger=None,
        warmup_families: int = 8,
        interval_s: float = 1.0,
        moves_per_cycle: int = 4,
    ) -> None:
        self.catalog = catalog
        self.planner = planner
        self.executor = executor
        self.ledger = ledger
        self.warmup_families = warmup_families
        self.interval_s = interval_s
        self.moves_per_cycle = moves_per_cycle
        self._lock = lockorder.tracked(
            threading.Lock(), "WarmupWorker._lock"
        )
        # guarded-by: _lock — (pod, plan) FIFO across all cold pods.
        self._queue: Deque[Tuple[str, object]] = deque()
        self._pending: Dict[str, int] = {}  # guarded-by: _lock
        self._warmed: Dict[str, int] = {}  # guarded-by: _lock
        self._cycles = 0
        self._moves = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- planning --------------------------------------------------------

    def _ranked_families(self) -> List[int]:
        """Hottest-first family ranking: shortest predicted reuse
        interval wins, family id breaks ties (determinism)."""
        if self.ledger is None:
            with_catalog = self.catalog.families()
            return sorted(with_catalog)[: self.warmup_families]
        predictions = self.ledger.reuse_predictions()
        ranked = sorted(predictions, key=lambda p: (p[1], p[0]))
        out: List[int] = []
        for family, _ewma, _last_seen, _requests in ranked:
            if self.catalog.get(family) is not None:
                out.append(family)
            if len(out) >= self.warmup_families:
                break
        return out

    def register_cold_pod(self, pod_identifier: str) -> int:
        """A new pod joined cold: bulk-plan its share of hot families.
        Returns the number of transfers queued."""
        queued = 0
        plans: List[Tuple[str, object]] = []
        for family in self._ranked_families():
            record = self.catalog.get(family)
            if record is None or record.holder_pod == pod_identifier:
                continue
            plan = self.planner.plan_warmup(
                source_pod=record.holder_pod,
                target_pod=pod_identifier,
                block_keys=record.block_keys,
                engine_hashes=record.engine_hashes,
                token_ids=record.token_ids,
                block_size=record.block_size,
                tier=record.tier,
            )
            plans.append((pod_identifier, plan))
            queued += 1
        with self._lock:
            self._queue.extend(plans)
            self._pending[pod_identifier] = (
                self._pending.get(pod_identifier, 0) + queued
            )
            cold = sum(1 for n in self._pending.values() if n > 0)
        METRICS.transfer_cold_pods.set(cold)
        logger.info(
            "cold pod %s: %d warm-up transfers planned",
            pod_identifier,
            queued,
        )
        return queued

    def queued_plans(self) -> List[object]:
        """Snapshot of the not-yet-executed warm-up plans, in drain
        order — the bench's scale-out sim mirrors each executed plan
        into its virtual pods' engine caches."""
        with self._lock:
            return [plan for _pod, plan in self._queue]

    # -- draining --------------------------------------------------------

    def run_cycle(self) -> int:
        """Execute up to ``moves_per_cycle`` queued transfers; the
        testable unit the thread loops over."""
        moved = 0
        for _ in range(self.moves_per_cycle):
            with self._lock:
                if not self._queue:
                    break
                pod, plan = self._queue.popleft()
            ok = False
            try:
                ok = self.executor.execute(plan, mode="copy")
            except Exception:  # noqa: BLE001 — a bad plan must not
                # wedge the drain loop; the plan is already terminal.
                logger.exception("warm-up transfer failed")
            with self._lock:
                self._pending[pod] = max(
                    0, self._pending.get(pod, 1) - 1
                )
                if ok:
                    self._warmed[pod] = self._warmed.get(pod, 0) + 1
                cold = sum(
                    1 for n in self._pending.values() if n > 0
                )
            if ok:
                moved += 1
                METRICS.transfer_warmup_moves.inc()
            METRICS.transfer_cold_pods.set(cold)
        # gil-atomic: stats counter bumped by the one warm-up thread
        self._cycles += 1
        return moved

    # -- thread lifecycle ------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run,
            name="kvtpu-transfer-warmup",
            daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001
                logger.exception("warm-up cycle failed")

    def close(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    def status(self) -> dict:
        with self._lock:
            pending = {
                pod: n for pod, n in self._pending.items() if n > 0
            }
            warmed = dict(self._warmed)
            queued = len(self._queue)
        return {
            "running": self._thread is not None
            and self._thread.is_alive(),
            "interval_s": self.interval_s,
            "moves_per_cycle": self.moves_per_cycle,
            "warmup_families": self.warmup_families,
            "queued": queued,
            "cold_pods": pending,
            "warmed_moves": warmed,
            "cycles": self._cycles,
        }
