from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache  # noqa: F401
