"""Runtime lock-order watchdog: the dynamic half of kvlint KV006.

The static rule (hack/kvlint/kv006_lockorder.py) proves the global
lock-acquisition graph acyclic from the source; this module asserts the
same declared order while the code actually runs, so the two validate
each other — a nesting the static model cannot see (a lock smuggled
through an untyped receiver) still trips the watchdog under the
concurrency storm tests, and a stale declaration trips it immediately.

Debug-gated and ~zero-cost when off: :func:`tracked` returns the lock
it was given unchanged unless the watchdog is enabled
(``KVTPU_LOCK_ORDER_DEBUG=1``, or :func:`enable` from tests), so
production lock acquisition never crosses a wrapper.

Vocabulary (mirrors the ``# kvlint: lock-order:`` comment annotations
the static rule reads — declare both at the same site):

* :func:`declare_order(first, second)` — ``first < second``: any
  thread holding ``second`` must not acquire ``first``.
* :func:`declare_ascending(name)` — multiple instances of ``name``
  are only ever acquired in ascending :func:`tracked` ``rank`` order
  (the striped-shard pattern).

Checks fire on acquire, against a per-thread stack of held locks:

* re-acquiring the *same instance* of a non-reentrant lock
  (guaranteed self-deadlock; RLocks/Conditions re-enter freely);
* same-name nesting without an ``ascending`` declaration;
* same-name nesting with one, but a rank that is missing or not
  strictly greater than every held instance's;
* acquiring ``first`` of a declared pair while ``second`` is held.

Violations raise :class:`LockOrderViolation` (an ``AssertionError``
subclass, so storm tests fail loudly instead of deadlocking flakily).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Set, Tuple

__all__ = [
    "LockOrderViolation",
    "declare_ascending",
    "declare_order",
    "enable",
    "enabled",
    "held",
    "reset_declarations",
    "tracked",
]


class LockOrderViolation(AssertionError):
    """A lock was acquired against the declared global order."""


_enabled = os.environ.get("KVTPU_LOCK_ORDER_DEBUG", "") in (
    "1",
    "true",
    "yes",
)
# first < second pairs and ascending-instance lock names.  Module-level
# registries mutated only at import/declaration time (single-threaded),
# read on every tracked acquire.
_ordered_pairs: Set[Tuple[str, str]] = set()
_ascending: Set[str] = set()

_state = threading.local()


def enabled() -> bool:
    return _enabled


def enable(flag: bool = True) -> bool:
    """Toggle the watchdog (tests); returns the previous state.

    Only locks created by :func:`tracked` *after* enabling are checked
    — construct the structures under test after calling this.
    """
    global _enabled
    previous = _enabled
    _enabled = flag
    return previous


def declare_order(first: str, second: str) -> None:
    """Declare ``first < second``: ``first`` is always acquired before
    ``second``; holding ``second`` forbids acquiring ``first``."""
    _ordered_pairs.add((first, second))


def declare_ascending(name: str) -> None:
    """Declare that instances of ``name`` nest only in ascending
    ``rank`` order (e.g. shard stripes by shard index)."""
    _ascending.add(name)


def reset_declarations() -> None:
    """Drop every declaration (test isolation)."""
    _ordered_pairs.clear()
    _ascending.clear()


def held() -> List[Tuple[str, Optional[int]]]:
    """The current thread's held tracked locks, outermost first."""
    return [
        (name, rank) for name, rank, _ in getattr(_state, "stack", ())
    ]


def _check(
    name: str, rank: Optional[int], ident: int, reentrant: bool
) -> None:
    stack = getattr(_state, "stack", [])
    if any(held_ident == ident for _, _, held_ident in stack):
        # Re-acquiring an instance this thread already holds: an RLock
        # (or Condition) re-enters without blocking — no hazard, no
        # order to check; a plain Lock is a guaranteed self-deadlock.
        if reentrant:
            return
        raise LockOrderViolation(
            f"'{name}' re-acquired by the thread already holding it — "
            "a non-reentrant lock self-deadlocks here"
        )
    for held_name, held_rank, _ in stack:
        if held_name == name:
            if name not in _ascending:
                raise LockOrderViolation(
                    f"'{name}' acquired while another instance of it is "
                    "held, with no '# kvlint: lock-order: "
                    f"{name} ascending' declaration"
                )
            if rank is None or held_rank is None or rank <= held_rank:
                raise LockOrderViolation(
                    f"'{name}' instances must be acquired in ascending "
                    f"rank order: holding rank {held_rank!r}, acquiring "
                    f"rank {rank!r}"
                )
        elif (name, held_name) in _ordered_pairs:
            raise LockOrderViolation(
                f"'{name}' acquired while holding '{held_name}', "
                f"contradicting the declared order "
                f"'{name} < {held_name}'"
            )


class TrackedLock:
    """Order-asserting proxy over a ``threading`` lock primitive.

    Proxies ``acquire``/``release`` and the context-manager protocol;
    anything else (``locked``, ``notify`` for Conditions) falls through
    via ``__getattr__``.
    """

    __slots__ = ("_lock", "_name", "_rank", "_reentrant")

    def __init__(self, lock, name: str, rank: Optional[int]) -> None:
        self._lock = lock
        self._name = name
        self._rank = rank
        self._reentrant = type(lock).__name__ in ("RLock", "Condition")

    @property
    def name(self) -> str:
        return self._name

    @property
    def rank(self) -> Optional[int]:
        return self._rank

    def acquire(self, *args, **kwargs):
        _check(self._name, self._rank, id(self), self._reentrant)
        acquired = self._lock.acquire(*args, **kwargs)
        if acquired:
            stack = getattr(_state, "stack", None)
            if stack is None:
                stack = _state.stack = []
            stack.append((self._name, self._rank, id(self)))
        return acquired

    def release(self) -> None:
        self._lock.release()
        stack = getattr(_state, "stack", [])
        # Remove the innermost matching hold (locks release LIFO in
        # `with` blocks; out-of-order manual release still unwinds the
        # right entry; reentrant holds pop one level per release).
        ident = id(self)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][2] == ident:
                del stack[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    def __getattr__(self, attr):
        return getattr(self._lock, attr)


def tracked(lock, name: str, rank: Optional[int] = None):
    """Wrap ``lock`` for order checking — identity when the watchdog
    is off, so the production fast path never pays for it.

    ``name`` should match the static model's lock identity
    (``Class._attr``); ``rank`` disambiguates instances under an
    ``ascending`` declaration (e.g. the shard index).
    """
    if not _enabled:
        return lock
    return TrackedLock(lock, name, rank)
