"""Runtime lock-order watchdog: the dynamic half of kvlint KV006.

The static rule (hack/kvlint/kv006_lockorder.py) proves the global
lock-acquisition graph acyclic from the source; this module asserts the
same declared order while the code actually runs, so the two validate
each other — a nesting the static model cannot see (a lock smuggled
through an untyped receiver) still trips the watchdog under the
concurrency storm tests, and a stale declaration trips it immediately.

Debug-gated and ~zero-cost when off: :func:`tracked` returns the lock
it was given unchanged unless the watchdog is enabled
(``KVTPU_LOCK_ORDER_DEBUG=1``, or :func:`enable` from tests), so
production lock acquisition never crosses a wrapper.

Vocabulary (mirrors the ``# kvlint: lock-order:`` comment annotations
the static rule reads — declare both at the same site):

* :func:`declare_order(first, second)` — ``first < second``: any
  thread holding ``second`` must not acquire ``first``.
* :func:`declare_ascending(name)` — multiple instances of ``name``
  are only ever acquired in ascending :func:`tracked` ``rank`` order
  (the striped-shard pattern).

Checks fire on acquire, against a per-thread stack of held locks:

* re-acquiring the *same instance* of a non-reentrant lock
  (guaranteed self-deadlock; RLocks/Conditions re-enter freely);
* same-name nesting without an ``ascending`` declaration;
* same-name nesting with one, but a rank that is missing or not
  strictly greater than every held instance's;
* acquiring ``first`` of a declared pair while ``second`` is held.

Violations raise :class:`LockOrderViolation` (an ``AssertionError``
subclass, so storm tests fail loudly instead of deadlocking flakily).

Contention telemetry (docs/observability.md "Lock contention"): the
second, production-grade mode of :func:`tracked`.  Unlike the
watchdog it needs no debug flag — setting ``LOCK_CONTENTION_SAMPLE=N``
arms it in any build: every Nth acquire of a tracked lock runs a
non-blocking probe first; a probe that succeeds costs nothing beyond
the probe itself (the uncontended fast path stays ~free), a probe
that fails is a *contended* acquire whose wait is timed and folded
into a per-lock-name stat (count, EWMA, max, total) plus the
``kvtpu_lock_wait_seconds{lock}`` / ``kvtpu_lock_contention_total
{lock}`` metric families.  ``LOCK_CONTENTION_SAMPLE`` unset or ``0``
keeps today's behavior bit-identically: :func:`tracked` returns the
raw lock object.  The watchdog supersedes timing when both are armed
(it is a debug tool; timing is for production).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "GuardRecordingLock",
    "LockOrderViolation",
    "contention_sample",
    "contention_stats",
    "declare_ascending",
    "declare_order",
    "enable",
    "enabled",
    "guard_recording",
    "held",
    "holder_of",
    "holds",
    "raw_lock",
    "reset_contention_stats",
    "reset_declarations",
    "set_contention_sample",
    "set_fuzz_hook",
    "set_guard_recording",
    "tracked",
]


class LockOrderViolation(AssertionError):
    """A lock was acquired against the declared global order."""


_enabled = os.environ.get("KVTPU_LOCK_ORDER_DEBUG", "") in (
    "1",
    "true",
    "yes",
)
# first < second pairs and ascending-instance lock names.  Module-level
# registries mutated only at import/declaration time (single-threaded),
# read on every tracked acquire.
_ordered_pairs: Set[Tuple[str, str]] = set()
_ascending: Set[str] = set()

_state = threading.local()


def enabled() -> bool:
    return _enabled


def enable(flag: bool = True) -> bool:
    """Toggle the watchdog (tests); returns the previous state.

    Only locks created by :func:`tracked` *after* enabling are checked
    — construct the structures under test after calling this.
    """
    global _enabled
    previous = _enabled
    _enabled = flag
    return previous


def declare_order(first: str, second: str) -> None:
    """Declare ``first < second``: ``first`` is always acquired before
    ``second``; holding ``second`` forbids acquiring ``first``."""
    _ordered_pairs.add((first, second))


def declare_ascending(name: str) -> None:
    """Declare that instances of ``name`` nest only in ascending
    ``rank`` order (e.g. shard stripes by shard index)."""
    _ascending.add(name)


def reset_declarations() -> None:
    """Drop every declaration (test isolation)."""
    _ordered_pairs.clear()
    _ascending.clear()


def held() -> List[Tuple[str, Optional[int]]]:
    """The current thread's held tracked locks, outermost first."""
    return [
        (name, rank) for name, rank, _ in getattr(_state, "stack", ())
    ]


def _check(
    name: str, rank: Optional[int], ident: int, reentrant: bool
) -> None:
    stack = getattr(_state, "stack", [])
    if any(held_ident == ident for _, _, held_ident in stack):
        # Re-acquiring an instance this thread already holds: an RLock
        # (or Condition) re-enters without blocking — no hazard, no
        # order to check; a plain Lock is a guaranteed self-deadlock.
        if reentrant:
            return
        raise LockOrderViolation(
            f"'{name}' re-acquired by the thread already holding it — "
            "a non-reentrant lock self-deadlocks here"
        )
    for held_name, held_rank, _ in stack:
        if held_name == name:
            if name not in _ascending:
                raise LockOrderViolation(
                    f"'{name}' acquired while another instance of it is "
                    "held, with no '# kvlint: lock-order: "
                    f"{name} ascending' declaration"
                )
            if rank is None or held_rank is None or rank <= held_rank:
                raise LockOrderViolation(
                    f"'{name}' instances must be acquired in ascending "
                    f"rank order: holding rank {held_rank!r}, acquiring "
                    f"rank {rank!r}"
                )
        elif (name, held_name) in _ordered_pairs:
            raise LockOrderViolation(
                f"'{name}' acquired while holding '{held_name}', "
                f"contradicting the declared order "
                f"'{name} < {held_name}'"
            )


# ------------------------ held-lock registry ---------------------------
#
# The raceguard plane (utils/raceguard.py, KVTPU_RACEGUARD=1) needs one
# question answered on every guarded attribute access: "does the
# CURRENT thread hold this specific lock instance?"  The watchdog's
# per-thread stack answers by *name*; guarded-by enforcement needs
# *instance* identity, so this registry tracks raw-lock ids — fed by
# every wrapper (TrackedLock, ContentionTimedLock, GuardRecordingLock)
# when recording is armed, so raceguard composes with whichever mode a
# storm runs under.  Off (the default) it is a single module-global
# bool test on wrapper acquires and nothing at all on raw locks.

_guard_recording = False

# The preemption fuzzer's injection point (hack/racefuzz.py): called as
# ``hook(kind, name)`` at every recording-lock acquire and — via
# raceguard's descriptors — at every guarded read/write boundary.
# Lives here (not in raceguard) so wrappers need no circular import.
_fuzz_hook = None

# raw-lock id -> ident of the thread currently holding it; plain dict
# with single-key ops so the registry itself cannot deadlock anything.
_holder_by_lock: Dict[int, int] = {}


def guard_recording() -> bool:
    return _guard_recording


def set_guard_recording(flag: bool) -> bool:
    """Arm/disarm held-lock recording (raceguard, racefuzz, tests);
    returns the previous state."""
    global _guard_recording
    previous = _guard_recording
    _guard_recording = flag
    return previous


def set_fuzz_hook(hook):
    """Install the preemption-fuzz yield hook; returns the previous
    one.  ``hook(kind, name)`` fires at guarded-access and
    lock-acquire boundaries while recording is armed."""
    global _fuzz_hook
    previous = _fuzz_hook
    _fuzz_hook = hook
    return previous


def raw_lock(lock):
    """Unwrap OUR proxy layers only — never foreign internals (a
    ``Condition`` owns a ``_lock`` attribute that must stay inside
    it), so wrapper and checker agree on one lock identity."""
    while isinstance(
        lock, (TrackedLock, ContentionTimedLock, GuardRecordingLock)
    ):
        lock = lock._lock
    return lock


def _record_acquire(raw) -> None:
    stack = getattr(_state, "guard_held", None)
    if stack is None:
        stack = _state.guard_held = []
    stack.append(id(raw))
    # gil-atomic: single-key put; one holder per lock at a time
    _holder_by_lock[id(raw)] = threading.get_ident()


def _record_release(raw) -> None:
    stack = getattr(_state, "guard_held", None)
    ident = id(raw)
    if stack:
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == ident:
                del stack[i]
                break
        if ident not in stack:
            # Delete only our own entry: recording runs before the
            # actual release, so no racer can have re-claimed it yet.
            if _holder_by_lock.get(ident) == threading.get_ident():
                # gil-atomic: single-key del by the current holder
                _holder_by_lock.pop(ident, None)


def holds(lock) -> bool:
    """True when the CURRENT thread holds ``lock`` (any wrapping)."""
    stack = getattr(_state, "guard_held", None)
    return bool(stack) and id(raw_lock(lock)) in stack


def holder_of(lock):
    """Thread ident of the current holder, or None — the raceguard
    violation report uses it to attach the *other* thread's stack."""
    return _holder_by_lock.get(id(raw_lock(lock)))


class GuardRecordingLock:
    """Minimal held-lock-recording proxy for raw locks.

    When raceguard arms on a tree where neither the watchdog nor
    contention timing wrapped a class's lock (``tracked`` returned the
    raw primitive), instances get this wrapper at ``__init__`` time so
    their acquires still feed the registry — and the fuzz hook, which
    fires BEFORE the inner acquire: that gap between two acquisitions
    of a check-then-act is exactly where a seeded yield flushes the
    race out.
    """

    __slots__ = ("_lock", "_name")

    def __init__(self, lock, name: str = "") -> None:
        self._lock = lock
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, *args, **kwargs):
        hook = _fuzz_hook
        if hook is not None:
            hook("lock-acquire", self._name)
        acquired = self._lock.acquire(*args, **kwargs)
        if acquired and _guard_recording:
            _record_acquire(self._lock)
        return acquired

    def release(self) -> None:
        if _guard_recording:
            _record_release(self._lock)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    def __getattr__(self, attr):
        return getattr(self._lock, attr)


class TrackedLock:
    """Order-asserting proxy over a ``threading`` lock primitive.

    Proxies ``acquire``/``release`` and the context-manager protocol;
    anything else (``locked``, ``notify`` for Conditions) falls through
    via ``__getattr__``.
    """

    __slots__ = ("_lock", "_name", "_rank", "_reentrant")

    def __init__(self, lock, name: str, rank: Optional[int]) -> None:
        self._lock = lock
        self._name = name
        self._rank = rank
        self._reentrant = type(lock).__name__ in ("RLock", "Condition")

    @property
    def name(self) -> str:
        return self._name

    @property
    def rank(self) -> Optional[int]:
        return self._rank

    def acquire(self, *args, **kwargs):
        _check(self._name, self._rank, id(self), self._reentrant)
        hook = _fuzz_hook
        if hook is not None:
            hook("lock-acquire", self._name)
        acquired = self._lock.acquire(*args, **kwargs)
        if acquired:
            stack = getattr(_state, "stack", None)
            if stack is None:
                stack = _state.stack = []
            stack.append((self._name, self._rank, id(self)))
            if _guard_recording:
                _record_acquire(self._lock)
        return acquired

    def release(self) -> None:
        if _guard_recording:
            _record_release(self._lock)
        self._lock.release()
        stack = getattr(_state, "stack", [])
        # Remove the innermost matching hold (locks release LIFO in
        # `with` blocks; out-of-order manual release still unwinds the
        # right entry; reentrant holds pop one level per release).
        ident = id(self)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][2] == ident:
                del stack[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    def __getattr__(self, attr):
        return getattr(self._lock, attr)


# ------------------------ contention telemetry ------------------------


def _env_sample() -> int:
    raw = os.environ.get("LOCK_CONTENTION_SAMPLE", "")
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


# 0 = off (tracked() returns the raw lock); N>0 = probe every Nth
# acquire of locks constructed after arming.  Mutated only by
# set_contention_sample() (tests/smokes) — read at lock construction.
_contention_sample = _env_sample()

_EWMA_ALPHA = 0.2

_contention_lock = threading.Lock()
_contention: Dict[str, "_ContentionStat"] = {}  # guarded-by: _contention_lock


class _ContentionStat:
    """Aggregate for one lock *name* (all instances fold together).

    ``sampled`` is bumped lock-free from the probe fast path — a plain
    int increment is GIL-coherent enough for a statistic, and putting
    a global lock on every Nth acquire of every tracked lock would
    manufacture exactly the contention this mode exists to find.  The
    contended-path fields are updated under ``_contention_lock``
    (that path just finished *waiting*; a lock op is noise there).
    """

    __slots__ = (
        "name",
        "sampled",
        "contended",
        "wait_total_s",
        "wait_max_s",
        "wait_ewma_s",
        "_wait_hist",
        "_contended_counter",
    )

    def __init__(self, name: str) -> None:
        self.name = name
        self.sampled = 0  # lock-free statistic (see class docstring)
        # The wait fields are updated/read under the MODULE-level
        # _contention_lock (record_contended/view) — KV001's
        # guarded-by annotation only resolves instance locks, so the
        # discipline is documented here instead.
        self.contended = 0
        self.wait_total_s = 0.0
        self.wait_max_s = 0.0
        self.wait_ewma_s = 0.0
        # Lazy import: lockorder must stay importable (and ~free) in
        # contexts that never arm timing and never touch prometheus.
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        self._wait_hist = METRICS.lock_wait.labels(lock=name)
        self._contended_counter = METRICS.lock_contention.labels(
            lock=name
        )

    def record_contended(self, wait_s: float) -> None:
        with _contention_lock:
            self.contended += 1
            self.wait_total_s += wait_s
            if wait_s > self.wait_max_s:
                self.wait_max_s = wait_s
            if self.wait_ewma_s == 0.0:
                self.wait_ewma_s = wait_s
            else:
                self.wait_ewma_s += _EWMA_ALPHA * (
                    wait_s - self.wait_ewma_s
                )
        self._contended_counter.inc()
        self._wait_hist.observe(wait_s)

    def view(self) -> dict:
        with _contention_lock:
            contended = self.contended
            view = {
                "sampled": self.sampled,
                "contended": contended,
                "wait_total_ms": round(self.wait_total_s * 1e3, 3),
                "wait_max_us": round(self.wait_max_s * 1e6, 1),
                "wait_ewma_us": round(self.wait_ewma_s * 1e6, 1),
            }
        sampled = view["sampled"]
        view["contention_ratio"] = (
            round(contended / sampled, 4) if sampled else 0.0
        )
        return view


def _stat_for(name: str) -> _ContentionStat:
    stat = _contention.get(name)
    if stat is not None:
        return stat
    # Construct OUTSIDE _contention_lock: the prometheus labels()
    # call takes the registry lock, and nesting a foreign lock under
    # ours is exactly the shape KV006 exists to forbid.  A racing
    # constructor loses to setdefault and its stat is garbage.
    stat = _ContentionStat(name)
    with _contention_lock:
        return _contention.setdefault(name, stat)


def contention_sample() -> int:
    """The armed sampling interval (0 = timing off)."""
    return _contention_sample


def set_contention_sample(sample: int) -> int:
    """Arm/disarm contention timing (tests, smokes); returns the
    previous interval.  Like :func:`enable`, only locks created by
    :func:`tracked` *after* the call pick the new mode up."""
    global _contention_sample
    previous = _contention_sample
    _contention_sample = max(0, int(sample))
    return previous


def contention_stats() -> Dict[str, dict]:
    """Per-lock-name contention view (the ``/debug/profile?kind=locks``
    payload): sampled/contended counts, contention ratio, wait EWMA /
    max / total."""
    with _contention_lock:
        stats = list(_contention.values())
    return {stat.name: stat.view() for stat in stats}


def reset_contention_stats() -> None:
    """Drop every accumulated stat (test/bench isolation).  Locks
    already constructed keep feeding their (now orphaned) stat
    objects; re-create structures after resetting, same as
    :func:`enable`."""
    with _contention_lock:
        _contention.clear()


class ContentionTimedLock:
    """Contention-timing proxy over a ``threading`` lock primitive.

    Every ``sample``-th acquire runs a non-blocking probe; only a
    failed probe (a genuinely contended acquire) pays for timestamps
    and stat recording.  Everything else proxies straight through,
    and non-acquire surface (``locked``, Condition ``wait``/``notify``)
    falls through via ``__getattr__`` exactly like ``TrackedLock``.
    """

    __slots__ = ("_lock", "_stat", "_sample", "_tick")

    def __init__(self, lock, stat: _ContentionStat, sample: int) -> None:
        self._lock = lock
        self._stat = stat
        self._sample = sample
        self._tick = 0

    @property
    def name(self) -> str:
        return self._stat.name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # gil-atomic: per-instance sampling tick; a lost ++ only
        # shifts which acquire gets probed
        self._tick += 1
        if self._tick % self._sample:
            acquired = self._lock.acquire(blocking, timeout)
            if acquired and _guard_recording:
                _record_acquire(self._lock)
            return acquired
        stat = self._stat
        # gil-atomic: lock-free statistic (see _ContentionStat)
        stat.sampled += 1
        if self._lock.acquire(False):
            if _guard_recording:
                _record_acquire(self._lock)
            return True
        if not blocking:
            # The probe WAS the caller's non-blocking attempt; a
            # failed one still proves contention.
            stat.record_contended(0.0)
            return False
        start = time.perf_counter()
        acquired = self._lock.acquire(blocking, timeout)
        stat.record_contended(time.perf_counter() - start)
        if acquired and _guard_recording:
            _record_acquire(self._lock)
        return acquired

    def release(self) -> None:
        if _guard_recording:
            _record_release(self._lock)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    def __getattr__(self, attr):
        return getattr(self._lock, attr)


def tracked(lock, name: str, rank: Optional[int] = None):
    """Wrap ``lock`` for order checking or contention timing —
    identity when both modes are off, so the production fast path
    never pays for it.

    ``name`` should match the static model's lock identity
    (``Class._attr``); ``rank`` disambiguates instances under an
    ``ascending`` declaration (e.g. the shard index).
    """
    if _enabled:
        return TrackedLock(lock, name, rank)
    sample = _contention_sample
    if sample > 0:
        return ContentionTimedLock(lock, _stat_for(name), sample)
    return lock
