"""Leveled logging shim.

The reference uses logr verbosity levels DEBUG=4 / TRACE=5
(pkg/utils/logging/levels.go:17-20).  We map them onto stdlib logging with a
TRACE level below DEBUG so per-stage trace logs along the hot paths stay
cheap and filterable.
"""

from __future__ import annotations

import logging
import os

TRACE = 5
logging.addLevelName(TRACE, "TRACE")


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(f"kvtpu.{name}")
    if not logging.getLogger("kvtpu").handlers:
        _configure_root()
    return logger


def _configure_root() -> None:
    root = logging.getLogger("kvtpu")
    handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    root.addHandler(handler)
    level_name = os.environ.get("KVTPU_LOG_LEVEL", "INFO").strip().upper()
    try:
        root.setLevel(TRACE if level_name == "TRACE" else level_name)
    except ValueError:
        root.setLevel(logging.INFO)
        root.warning(
            "invalid KVTPU_LOG_LEVEL %r, falling back to INFO", level_name
        )
    root.propagate = False


def trace(logger: logging.Logger, msg: str, *args) -> None:
    if logger.isEnabledFor(TRACE):
        logger.log(TRACE, msg, *args)
