"""Thread-safe LRU cache used by the index and prefix-store backends.

Capability parity with the hashicorp/golang-lru caches the reference builds
on (reference: pkg/kvcache/kvblock/in_memory.go:24,
pkg/tokenization/prefixstore/lru_store.go:26) — but implemented on
``OrderedDict`` with a single lock, which is the idiomatic CPython shape.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import (
    Callable,
    Generic,
    Hashable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
    TypeVar,
)

from llm_d_kv_cache_manager_tpu.utils import lockorder

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

_MISSING = object()


class LRUCache(Generic[K, V]):
    """A bounded mapping that evicts the least-recently-used entry.

    ``get`` and ``put`` both refresh recency.  All operations are O(1) and
    thread-safe.  An optional ``on_evict`` callback observes capacity
    evictions (not explicit removals).
    """

    def __init__(
        self,
        capacity: int,
        on_evict: Optional[Callable[[K, V], None]] = None,
        *,
        lock_rank: Optional[int] = None,
    ) -> None:
        if capacity <= 0:
            raise ValueError(f"LRU capacity must be positive, got {capacity}")
        self._capacity = capacity
        self._data: "OrderedDict[K, V]" = OrderedDict()  # guarded-by: _lock
        # ``lock_rank`` orders instances under the lock-order watchdog
        # (utils/lockorder.py): striped owners pass their stripe index
        # so same-name nesting asserts ascending acquisition.  With the
        # watchdog off, tracked() returns the bare Lock unchanged.
        self._lock = lockorder.tracked(
            threading.Lock(), "LRUCache._lock", lock_rank
        )
        self._on_evict = on_evict

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key: K) -> bool:
        with self._lock:
            return key in self._data

    @property
    def capacity(self) -> int:
        return self._capacity

    def get(self, key: K, default: Optional[V] = None) -> Optional[V]:
        with self._lock:
            value = self._data.get(key, _MISSING)
            if value is _MISSING:
                return default
            self._data.move_to_end(key)
            return value  # type: ignore[return-value]

    def peek(self, key: K, default: Optional[V] = None) -> Optional[V]:
        """Read without refreshing recency."""
        with self._lock:
            value = self._data.get(key, _MISSING)
            return default if value is _MISSING else value  # type: ignore[return-value]

    def peek_many(
        self, keys: Sequence[K], default: Optional[V] = None
    ) -> List[Optional[V]]:
        """Batched ``peek``: ONE lock acquisition for the whole key
        list (a per-key call costs a lock round-trip each — on the
        scoring hot path a 500-block prompt chain paid 500 of them).
        No recency refresh: callers that consume only a prefix of the
        chain follow up with :meth:`touch_many` on what they used.
        ``default`` marks missing keys — pass a sentinel when the
        cache legitimately stores ``None`` values (``peek``'s own
        contract, kept for the batched form)."""
        out: List[Optional[V]] = []
        with self._lock:
            data = self._data
            for key in keys:
                value = data.get(key, _MISSING)
                out.append(default if value is _MISSING else value)
        return out

    def touch_many(self, keys: Sequence[K]) -> None:
        """Batched recency refresh for keys the caller actually
        consumed (missing keys are ignored)."""
        with self._lock:
            data = self._data
            for key in keys:
                if key in data:
                    data.move_to_end(key)

    def put(self, key: K, value: V) -> None:
        evicted: Optional[Tuple[K, V]] = None
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
            self._data[key] = value
            if len(self._data) > self._capacity:
                evicted = self._data.popitem(last=False)
        if evicted is not None and self._on_evict is not None:
            self._on_evict(*evicted)

    def put_many(self, items: Sequence[Tuple[K, V]]) -> None:
        """Batched ``put``: ONE lock acquisition for the whole item
        list (the kvevents write path inserts a key pair per block —
        a 100-block store event paid 100 lock round-trips)."""
        evicted: List[Tuple[K, V]] = []
        with self._lock:
            data = self._data
            capacity = self._capacity
            for key, value in items:
                if key in data:
                    data.move_to_end(key)
                data[key] = value
                if len(data) > capacity:
                    evicted.append(data.popitem(last=False))
        if evicted and self._on_evict is not None:
            for key, value in evicted:
                self._on_evict(key, value)

    def get_or_create_many(
        self, keys: Sequence[K], factory: Callable[[], V]
    ) -> List[V]:
        """Batched ``get``-or-``put_if_absent``: one lock round-trip
        returns the resident (or freshly created) value per key, with
        recency refreshed — the grouped-per-shard admission primitive
        of the kvevents batched apply path.  ``factory`` runs under
        the lock, so it must be cheap and side-effect-free."""
        out: List[V] = []
        evicted: List[Tuple[K, V]] = []
        with self._lock:
            data = self._data
            capacity = self._capacity
            for key in keys:
                resident = data.get(key, _MISSING)
                if resident is _MISSING:
                    resident = factory()
                    data[key] = resident
                    if len(data) > capacity:
                        evicted.append(data.popitem(last=False))
                else:
                    data.move_to_end(key)
                out.append(resident)  # type: ignore[arg-type]
        if evicted and self._on_evict is not None:
            for key, value in evicted:
                self._on_evict(key, value)
        return out

    def put_if_absent(self, key: K, value: V) -> V:
        """Insert ``value`` unless ``key`` exists; return the resident value.

        The atomic check-and-set the reference approximates with
        double-checked locking (in_memory.go:183-197) is a single locked
        operation here.
        """
        evicted: Optional[Tuple[K, V]] = None
        with self._lock:
            resident = self._data.get(key, _MISSING)
            if resident is not _MISSING:
                self._data.move_to_end(key)
                result = resident
            else:
                self._data[key] = value
                result = value
                if len(self._data) > self._capacity:
                    evicted = self._data.popitem(last=False)
        if evicted is not None and self._on_evict is not None:
            self._on_evict(*evicted)
        return result  # type: ignore[return-value]

    def remove(self, key: K) -> bool:
        with self._lock:
            return self._data.pop(key, _MISSING) is not _MISSING

    def keys(self) -> list:
        """Snapshot of keys, least-recently-used first."""
        with self._lock:
            return list(self._data.keys())

    def items(self) -> Iterator[Tuple[K, V]]:
        with self._lock:
            snapshot = list(self._data.items())
        return iter(snapshot)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
