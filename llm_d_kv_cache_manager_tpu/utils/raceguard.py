"""Guarded-by runtime enforcement: kvlint's static model, executed.

kvlint KV001 proves from source that every ``# guarded-by:`` attribute
is touched only under its declared lock — within the access shapes the
AST can see.  Aliases, foreign-object accesses, dynamic dispatch and
plain annotation lies are invisible to it.  This module closes the
loop at runtime: ``hack/kvlint --emit-manifest`` exports phase 1's
class→{guarded attrs, lock attr, caller-locked methods} model to
``hack/kvlint/raceguard_manifest.json`` (checked in,
staleness-pinned), and when ``KVTPU_RACEGUARD=1`` :func:`install`
imports every manifest class and replaces each guarded attribute with
a data descriptor that asserts *the current thread holds the declared
lock instance* on every read and write.

Composition (utils/lockorder.py): enforcement needs to know which lock
instances the current thread holds, which is the held-lock registry —
fed by ``TrackedLock`` (watchdog), ``ContentionTimedLock`` (telemetry)
and ``GuardRecordingLock`` (the minimal wrapper instances get at
``__init__`` time when neither debug mode armed their lock).  A storm
can therefore run watchdog + raceguard together and each wrapper
records exactly once.

Zero-cost when unarmed, same contract as the watchdog: with
``KVTPU_RACEGUARD`` unset nothing is instrumented — class dicts keep
their raw slots/attributes, ``tracked`` locks stay raw, attribute
access is native (pinned by a tier-1 test).

Violations raise :class:`RaceGuardViolation` (an ``AssertionError``
subclass, so storms fail loudly) carrying BOTH thread stacks: the
offending accessor's and — via ``sys._current_frames`` and the
registry's holder map — the stack of the thread currently holding the
lock, which is the pair a race report needs.

Known soundness gaps (documented, deliberate): an object is exempt
while its ``__init__`` runs in the constructing thread (not shared
yet); a subclass ``__init__`` continuing after the instrumented base
``__init__`` returned re-enters enforcement; a ``Condition.wait``
still counts as holding for the waiting thread while it is blocked
(it cannot access anything meanwhile).
"""

from __future__ import annotations

import importlib
import json
import os
import sys
import threading
import traceback
from typing import Dict, List, Optional

from llm_d_kv_cache_manager_tpu.utils import lockorder

__all__ = [
    "RaceGuardViolation",
    "armed_from_env",
    "guard_class",
    "install",
    "install_from_env",
    "installed",
    "uninstall",
]

MANIFEST_ENV = "KVTPU_RACEGUARD_MANIFEST"


class RaceGuardViolation(AssertionError):
    """A guarded attribute was accessed without its declared lock."""


def armed_from_env() -> bool:
    return os.environ.get("KVTPU_RACEGUARD", "") in ("1", "true", "yes")


_local = threading.local()

# class -> {attr -> original class-dict entry (or _MISSING)}, plus the
# original __init__, for uninstall(); mutated only by install/uninstall
# (single-threaded test/boot paths).
_instrumented: Dict[type, dict] = {}

_MISSING = object()


def installed() -> bool:
    return bool(_instrumented)


def _other_thread_stack(ident: Optional[int]) -> str:
    if ident is None:
        return "  (no thread currently holds the lock)"
    if ident == threading.get_ident():
        return "  (the holder IS the current thread)"
    frame = sys._current_frames().get(ident)
    if frame is None:
        return f"  (holder thread {ident} already exited)"
    name = str(ident)
    for thread in threading.enumerate():
        if thread.ident == ident:
            name = f"{thread.name} ({ident})"
            break
    stack = "".join(traceback.format_stack(frame))
    return f"  holder thread {name}:\n{stack}"


class GuardedAttribute:
    """Data descriptor enforcing ``# guarded-by:`` on one attribute.

    Storage is delegated to the original slot descriptor when the
    class used ``__slots__``, to the instance ``__dict__`` otherwise
    (a data descriptor shadows the instance dict, so the raw value
    stays invisible to normal lookup).
    """

    __slots__ = ("attr", "lock_attr", "owner_name", "slot")

    def __init__(
        self,
        attr: str,
        lock_attr: str,
        owner_name: str,
        slot=None,
    ) -> None:
        self.attr = attr
        self.lock_attr = lock_attr
        self.owner_name = owner_name
        self.slot = slot

    # -- enforcement ----------------------------------------------------

    def _check(self, obj, mode: str) -> None:
        initializing = getattr(_local, "initializing", None)
        if initializing and id(obj) in initializing:
            return  # under construction in this thread: not shared yet
        hook = lockorder._fuzz_hook
        if hook is not None:
            hook(f"guard-{mode}", f"{self.owner_name}.{self.attr}")
        lock = getattr(obj, self.lock_attr, None)
        if lock is None:
            return  # lock not built yet (partially-initialized object)
        if lockorder.holds(lock):
            return
        mine = "".join(traceback.format_stack())
        other = _other_thread_stack(lockorder.holder_of(lock))
        raise RaceGuardViolation(
            f"raceguard: {mode} of '{self.owner_name}.{self.attr}' "
            f"without holding 'self.{self.lock_attr}' "
            f"(declared `# guarded-by: {self.lock_attr}`)\n"
            f"  accessing thread {threading.current_thread().name} "
            f"({threading.get_ident()}):\n{mine}\n{other}"
        )

    # -- storage --------------------------------------------------------

    def __get__(self, obj, owner=None):
        if obj is None:
            return self
        self._check(obj, "read")
        if self.slot is not None:
            return self.slot.__get__(obj, owner)
        try:
            return obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(self.attr) from None

    def __set__(self, obj, value) -> None:
        self._check(obj, "write")
        if self.slot is not None:
            self.slot.__set__(obj, value)
        else:
            obj.__dict__[self.attr] = value

    def __delete__(self, obj) -> None:
        self._check(obj, "write")
        if self.slot is not None:
            self.slot.__delete__(obj)
        else:
            try:
                del obj.__dict__[self.attr]
            except KeyError:
                raise AttributeError(self.attr) from None


def _wrap_instance_locks(obj, lock_attrs) -> None:
    """Post-``__init__``: ensure every lock attr feeds the held-lock
    registry.  Locks already wrapped by the watchdog or contention
    timing record on their own; raw primitives get the minimal
    recording wrapper.  Identity is the RAW lock, so double wrapping
    elsewhere could never split an instance's identity."""
    for attr in lock_attrs:
        try:
            lock = object.__getattribute__(obj, attr)
        except AttributeError:
            continue
        if lock is None or isinstance(
            lock,
            (
                lockorder.TrackedLock,
                lockorder.ContentionTimedLock,
                lockorder.GuardRecordingLock,
            ),
        ):
            continue
        if not hasattr(lock, "acquire"):
            continue
        wrapped = lockorder.GuardRecordingLock(
            lock, f"{type(obj).__name__}.{attr}"
        )
        setattr(obj, attr, wrapped)


def _wrap_init(cls, lock_attrs) -> object:
    orig_init = cls.__init__

    def raceguard_init(self, *args, **kwargs):
        initializing = getattr(_local, "initializing", None)
        if initializing is None:
            initializing = _local.initializing = set()
        fresh = id(self) not in initializing
        if fresh:
            initializing.add(id(self))
        try:
            orig_init(self, *args, **kwargs)
        finally:
            if fresh:
                initializing.discard(id(self))
        if fresh:
            _wrap_instance_locks(self, lock_attrs)

    raceguard_init.__name__ = getattr(orig_init, "__name__", "__init__")
    raceguard_init.__qualname__ = getattr(
        orig_init, "__qualname__", f"{cls.__name__}.__init__"
    )
    raceguard_init.__raceguard_wrapped__ = True
    cls.__init__ = raceguard_init
    return orig_init


def guard_class(
    cls: type,
    guarded: Dict[str, str],
    locks: Optional[List[str]] = None,
) -> type:
    """Instrument one class (the manifest path calls this for every
    entry; tests call it directly to plant violations).  ``guarded``
    maps attr -> lock attr; ``locks`` lists lock attrs to wrap at
    ``__init__`` time (defaults to the distinct guard locks)."""
    if cls in _instrumented:
        return cls
    lock_attrs = sorted(set(locks or ()) | set(guarded.values()))
    saved: dict = {"__init__": cls.__init__, "attrs": {}}
    for attr, lock_attr in sorted(guarded.items()):
        original = cls.__dict__.get(attr, _MISSING)
        saved["attrs"][attr] = original
        slot = original if _is_slot_descriptor(original) else None
        setattr(
            cls,
            attr,
            GuardedAttribute(attr, lock_attr, cls.__name__, slot),
        )
    _wrap_init(cls, lock_attrs)
    _instrumented[cls] = saved
    return cls


def _is_slot_descriptor(obj) -> bool:
    return type(obj).__name__ == "member_descriptor"


# ---------------------------- manifest ---------------------------------


def _default_manifest_path() -> str:
    override = os.environ.get("KVTPU_RACEGUARD_MANIFEST")
    if override:
        return override
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(
        os.path.dirname(package_dir),
        "hack",
        "kvlint",
        "raceguard_manifest.json",
    )


def load_manifest(path: Optional[str] = None) -> dict:
    manifest_path = path or _default_manifest_path()
    with open(manifest_path, encoding="utf-8") as handle:
        return json.load(handle)


def _resolve(key: str) -> type:
    module_name, _, qualname = key.partition(":")
    module = importlib.import_module(module_name)
    obj = module
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def install(path: Optional[str] = None) -> int:
    """Instrument every manifest class; returns the class count.
    Import or resolution failures raise — silently skipping a class
    would silently skip its enforcement."""
    manifest = load_manifest(path)
    lock_attrs_by_cls: Dict[type, List[str]] = {}
    count = 0
    for key, entry in manifest.get("classes", {}).items():
        cls = _resolve(key)
        locks = sorted(
            set(entry.get("locks", ()))
            | set(entry.get("guarded", {}).values())
        )
        guard_class(
            cls,
            guarded=dict(entry.get("guarded", {})),
            locks=locks,
        )
        lock_attrs_by_cls[cls] = locks
        count += 1
    _sweep_existing_instances(lock_attrs_by_cls)
    return count


def _sweep_existing_instances(
    lock_attrs_by_cls: Dict[type, List[str]],
) -> None:
    """Module-level singletons (``TRACER``, ``PROFILER``, …) are built
    while :func:`install` is still importing their modules — before
    their ``__init__`` was wrapped — so their locks never entered the
    held-lock registry and every guarded access would look unlocked.
    One gc pass wraps the locks of instances that already exist; every
    later construction goes through the wrapped ``__init__``."""
    import gc

    classes = tuple(lock_attrs_by_cls)
    if not classes:
        return
    for obj in gc.get_objects():
        if isinstance(obj, classes):
            for cls in type(obj).__mro__:
                attrs = lock_attrs_by_cls.get(cls)
                if attrs:
                    _wrap_instance_locks(obj, attrs)


def install_from_env() -> bool:
    """Boot hook (package ``__init__``): install iff
    ``KVTPU_RACEGUARD=1``; False (and zero work) otherwise."""
    if not armed_from_env():
        return False
    if not installed():
        install()
        lockorder.set_guard_recording(True)
    return True


def uninstall() -> None:
    """Restore every instrumented class (test isolation)."""
    for cls, saved in list(_instrumented.items()):
        cls.__init__ = saved["__init__"]
        for attr, original in saved["attrs"].items():
            if original is _MISSING:
                try:
                    delattr(cls, attr)
                except AttributeError:
                    pass
            else:
                setattr(cls, attr, original)
        del _instrumented[cls]
