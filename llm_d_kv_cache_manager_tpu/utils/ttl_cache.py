"""TTL cache with eviction callbacks.

Small, dependency-free equivalent of the ``ttlcache`` library the
reference's scheduler plugin uses for subscriber lifecycle
(examples/kv_cache_aware_scorer/kvcache_aware_scorer.go:126-140): every
``set`` refreshes the key's deadline, expired keys fire ``on_evict``,
and an optional background thread sweeps periodically.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Generic, Optional, TypeVar

from llm_d_kv_cache_manager_tpu.utils import lockorder

K = TypeVar("K")
V = TypeVar("V")

# Both static KV006 and the runtime watchdog assert this: the callback
# serializer wraps the entry lock (set/_fire_eviction), never the
# other way around.
# kvlint: lock-order: TTLCache._cb_lock < TTLCache._lock
lockorder.declare_order("TTLCache._cb_lock", "TTLCache._lock")


class TTLCache(Generic[K, V]):
    def __init__(
        self,
        ttl_seconds: float,
        on_evict: Optional[Callable[[K, V], None]] = None,
    ) -> None:
        self.ttl_seconds = ttl_seconds
        self._on_evict = on_evict
        # key -> (value, deadline)
        self._entries: Dict[K, tuple] = {}  # guarded-by: _lock
        self._lock = lockorder.tracked(
            threading.Lock(), "TTLCache._lock"
        )
        # Serializes set() against expiry callbacks so a re-insert can
        # never interleave between the is-it-still-absent check and the
        # on_evict call (which would tear down the fresh state).  RLock
        # so an on_evict callback may itself call set().
        self._cb_lock = lockorder.tracked(
            threading.RLock(), "TTLCache._cb_lock"
        )
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def set(self, key: K, value: V, ttl_seconds: Optional[float] = None):
        """Insert or refresh; refreshing resets the deadline."""
        deadline = time.monotonic() + (ttl_seconds or self.ttl_seconds)
        with self._cb_lock:
            with self._lock:
                self._entries[key] = (value, deadline)

    def get(self, key: K) -> Optional[V]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            value, deadline = entry
            if deadline < time.monotonic():
                del self._entries[key]
            else:
                return value
        self._fire_eviction(key, value)
        return None

    def delete(self, key: K) -> bool:
        """Remove without firing ``on_evict`` (explicit removal, not
        expiry — mirrors ttlcache's EvictionReason distinction)."""
        with self._lock:
            return self._entries.pop(key, None) is not None

    def keys(self) -> list:
        with self._lock:
            return list(self._entries)

    def sweep(self) -> int:
        """Evict every expired key now; returns the eviction count.

        A raising ``on_evict`` must not abort the sweep (the remaining
        expired keys were already removed from the map — skipping their
        callbacks would leak whatever the callback tears down) nor kill
        the background sweeper thread.
        """
        now = time.monotonic()
        expired = []
        with self._lock:
            for key, (value, deadline) in list(self._entries.items()):
                if deadline < now:
                    del self._entries[key]
                    expired.append((key, value))
        for key, value in expired:
            try:
                self._fire_eviction(key, value)
            except Exception:  # noqa: BLE001 - callback bugs stay local
                from llm_d_kv_cache_manager_tpu.utils.logging import (
                    get_logger,
                )

                get_logger("utils.ttl_cache").exception(
                    "on_evict callback failed for %r", key
                )
        return len(expired)

    def _fire_eviction(self, key: K, value: V) -> None:
        """Run ``on_evict`` outside the entry lock, skipping it if the
        key was re-inserted between removal and now — otherwise a
        concurrent ``set`` has its fresh state torn down by the stale
        eviction.  Holding ``_cb_lock`` across check+callback makes the
        skip airtight: ``set`` cannot land in between."""
        if self._on_evict is None:
            return
        with self._cb_lock:
            with self._lock:
                if key in self._entries:
                    return
            self._on_evict(key, value)

    def start_sweeper(self, interval_seconds: Optional[float] = None) -> None:
        """Spawn the periodic cleaner (idempotent)."""
        if self._sweeper is not None:
            return
        interval = interval_seconds or self.ttl_seconds

        def loop() -> None:
            while not self._stop.wait(interval):
                self.sweep()

        # gil-atomic: lifecycle ref; start/close are control-plane
        self._sweeper = threading.Thread(
            target=loop, name="kvtpu-ttl-sweeper", daemon=True
        )
        self._sweeper.start()

    def stop_sweeper(self) -> None:
        self._stop.set()
        if self._sweeper is not None:
            self._sweeper.join(timeout=5)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._sweeper = None
        self._stop.clear()

    def close(self) -> None:
        """Canonical shutdown: stop the sweeper thread (idempotent).

        Callers that only ever used :meth:`stop_sweeper` keep working;
        owners tearing a subsystem down get the conventional name (and
        KV008's reachable-closer check keys on it).
        """
        self.stop_sweeper()
