"""Shared eviction-policy invocation guard.

The cost-aware index and the host-tier cache both hand a pluggable
eviction policy (tiering/eviction.py) an LRU-ordered ``(key,
byte_cost)`` sample and need the same safety contract around the
call: the policy's answer is bounds-checked, and ANY policy failure
falls back to the LRU-first victim — eviction must never wedge a
cache.  One implementation here so the two backends cannot drift
(each still builds its own sample; only the invocation semantics are
shared).
"""

from __future__ import annotations

from typing import Sequence, Tuple


def sample_limit(policy) -> int:
    """How many LRU-ordered candidates the policy wants ranked."""
    return max(1, getattr(policy, "sample", 1))


def guarded_select(policy, sample: Sequence[Tuple[int, int]], logger) -> int:
    """Index into ``sample`` of the victim the policy chose; 0 (the
    LRU-first candidate) on any policy failure or out-of-range
    answer.  Runs under the caller's lock — the policy contract says
    it takes no locks of its own."""
    try:
        index = policy.select_victim(sample)
        if not 0 <= index < len(sample):
            raise IndexError(index)
        return index
    except Exception:  # noqa: BLE001 — eviction must never wedge
        logger.exception("eviction policy failed; using LRU victim")
        return 0
