"""Test harness configuration.

Tests run on CPU with a virtual 8-device platform so multi-chip sharding
compiles and executes without TPU hardware.  These env vars must be set
before JAX is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
