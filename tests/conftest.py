"""Test harness configuration.

Tests run on CPU with a virtual 8-device platform so multi-chip sharding
compiles and executes without TPU hardware.  These env vars must be set
before JAX is imported anywhere in the test process.
"""

import os

# Force CPU even when the environment preselects a TPU platform
# (e.g. a sitecustomize registering JAX_PLATFORMS=axon): tests validate
# multi-chip sharding on the virtual 8-device mesh; the real chip is
# reserved for bench.py.  The config.update path wins over an
# already-registered backend as long as no computation has run yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The env var alone is NOT enough here: the axon sitecustomize calls
# jax.config at interpreter start, and config beats env at backend
# init.  Importing jax to update config is the only reliable override.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
