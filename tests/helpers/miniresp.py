"""In-process fake Redis speaking enough RESP2 for the RedisIndex backend.

Test-only stand-in following the reference's miniredis pattern
(pkg/kvcache/kvblock/redis_test.go:29-45): no real server required.
Supports HSET / HKEYS / HDEL / HLEN / SET / GET / DEL / PING / FLUSHALL /
AUTH / SELECT, the index's atomic-prune EVAL script, and optional
``requirepass`` / TLS so the authenticated and encrypted handshakes can be
exercised end-to-end.
"""

from __future__ import annotations

import socketserver
import ssl
import threading
from typing import Dict, Optional


class _State:
    def __init__(self) -> None:
        self.strings: Dict[bytes, bytes] = {}
        self.hashes: Dict[bytes, Dict[bytes, bytes]] = {}
        self.lock = threading.Lock()


def _bulk(data) -> bytes:
    if data is None:
        return b"$-1\r\n"
    if isinstance(data, str):
        data = data.encode()
    return b"$%d\r\n%s\r\n" % (len(data), data)


def _normalize_script(script: bytes) -> bytes:
    return b" ".join(script.split())


# The only script the index runs: atomic prune of the engine->request
# mapping (semantics of the reference's redis.go:147-154 Lua script).
_PRUNE_FINGERPRINT = _normalize_script(
    b"local hashLen = redis.call('HLEN', KEYS[1]) "
    b"if hashLen == 0 then redis.call('DEL', KEYS[2]) return 1 end "
    b"return 0"
)


class _Handler(socketserver.StreamRequestHandler):
    # Real Redis disables Nagle on accepted sockets; without this, each
    # small per-command reply stalls ~40ms on the peer's delayed ACK.
    disable_nagle_algorithm = True

    def handle(self) -> None:
        state: _State = self.server.state  # type: ignore[attr-defined]
        self._authed = self.server.password is None  # type: ignore[attr-defined]
        while True:
            try:
                command = self._read_command()
            except (ConnectionError, ValueError, OSError):
                return
            if command is None:
                return
            self.wfile.write(self._dispatch(state, command))

    def _read_command(self):
        line = self.rfile.readline()
        if not line:
            return None
        if not line.startswith(b"*"):
            raise ValueError("inline commands unsupported")
        argc = int(line[1:])
        args = []
        for _ in range(argc):
            header = self.rfile.readline()
            if not header.startswith(b"$"):
                raise ValueError("expected bulk string")
            length = int(header[1:])
            args.append(self.rfile.read(length + 2)[:-2])
        return args

    def _auth(self, args) -> bytes:
        password = self.server.password  # type: ignore[attr-defined]
        if password is None:
            return b"-ERR Client sent AUTH, but no password is set\r\n"
        if len(args) == 2:
            user, given = b"default", args[1]
        elif len(args) == 3:
            user, given = args[1], args[2]
        else:
            return b"-ERR wrong number of arguments for 'auth' command\r\n"
        if user != b"default" or given != password.encode():
            return b"-WRONGPASS invalid username-password pair\r\n"
        self._authed = True
        return b"+OK\r\n"

    def _dispatch(self, state: _State, args) -> bytes:
        cmd = args[0].upper()
        if cmd == b"AUTH":
            return self._auth(args)
        if not self._authed:
            return b"-NOAUTH Authentication required.\r\n"
        with state.lock:
            if cmd == b"PING":
                return b"+PONG\r\n"
            if cmd == b"SELECT":
                # Single logical database; accept the index for handshake
                # compatibility.
                return b"+OK\r\n"
            if cmd == b"SET":
                state.strings[args[1]] = args[2]
                return b"+OK\r\n"
            if cmd == b"GET":
                return _bulk(state.strings.get(args[1]))
            if cmd == b"DEL":
                removed = 0
                for key in args[1:]:
                    removed += int(state.strings.pop(key, None) is not None)
                    removed += int(state.hashes.pop(key, None) is not None)
                return b":%d\r\n" % removed
            if cmd == b"HSET":
                bucket = state.hashes.setdefault(args[1], {})
                added = 0
                for i in range(2, len(args), 2):
                    added += int(args[i] not in bucket)
                    bucket[args[i]] = args[i + 1]
                return b":%d\r\n" % added
            if cmd == b"HKEYS":
                bucket = state.hashes.get(args[1], {})
                out = b"*%d\r\n" % len(bucket)
                for field in bucket:
                    out += _bulk(field)
                return out
            if cmd == b"HDEL":
                bucket = state.hashes.get(args[1], {})
                removed = 0
                for field in args[2:]:
                    removed += int(bucket.pop(field, None) is not None)
                if not bucket:
                    state.hashes.pop(args[1], None)
                return b":%d\r\n" % removed
            if cmd == b"HLEN":
                return b":%d\r\n" % len(state.hashes.get(args[1], {}))
            if cmd == b"SCAN":
                # One-shot cursor: every key in a single page (real
                # Redis pages; clients must loop until cursor "0"
                # either way, which the index's purge_pod does).
                keys = list(state.hashes) + list(state.strings)
                out = b"*2\r\n" + _bulk(b"0")
                out += b"*%d\r\n" % len(keys)
                for key in keys:
                    out += _bulk(key)
                return out
            if cmd == b"EVAL":
                return self._eval(state, args)
            if cmd == b"FLUSHALL":
                state.strings.clear()
                state.hashes.clear()
                return b"+OK\r\n"
        return b"-ERR unknown command '%s'\r\n" % cmd

    def _eval(self, state: _State, args) -> bytes:
        """Execute the index's prune script atomically (caller holds the
        state lock, which is what makes it atomic here)."""
        if len(args) < 3:
            return b"-ERR wrong number of arguments for 'eval' command\r\n"
        if _normalize_script(args[1]) != _PRUNE_FINGERPRINT:
            return b"-ERR unsupported script for miniresp\r\n"
        if args[2] != b"2" or len(args) != 5:
            return b"-ERR prune script expects exactly 2 keys\r\n"
        request_key, engine_key = args[3], args[4]
        if len(state.hashes.get(request_key, {})) == 0:
            state.strings.pop(engine_key, None)
            state.hashes.pop(request_key, None)
            return b":1\r\n"
        return b":0\r\n"


class MiniRespServer:
    def __init__(
        self,
        password: Optional[str] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
    ) -> None:
        self._server = socketserver.ThreadingTCPServer(
            ("127.0.0.1", 0), _Handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self._server.state = _State()  # type: ignore[attr-defined]
        self._server.password = password  # type: ignore[attr-defined]
        if ssl_context is not None:
            self._server.socket = ssl_context.wrap_socket(
                self._server.socket, server_side=True
            )
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def state(self) -> _State:
        return self._server.state  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._server.server_address
        return f"{host}:{port}"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
