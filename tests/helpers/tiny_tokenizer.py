"""Builds a tiny word-level fast tokenizer entirely in-process.

No network, no checked-in fixture files: the vocabulary is derived from the
test corpus at call time and saved as a standard ``tokenizer.json`` that
both the `tokenizers` and `transformers` loaders understand.
"""

from __future__ import annotations

import os

CORPUS = (
    "the quick brown fox jumps over the lazy dog . "
    "pack my box with five dozen liquor jugs . "
    "how vexingly quick daft zebras jump . "
    "system : you are a helpful assistant . user says hello world"
)

CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|> {{ message['content'] }} "
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>{% endif %}"
)


def build_fast_tokenizer():
    from tokenizers import Tokenizer, models, pre_tokenizers

    vocab = {"<unk>": 0, "<s>": 1, "</s>": 2}
    for word in CORPUS.split():
        if word not in vocab:
            vocab[word] = len(vocab)
    # Chat-template markers used by CHAT_TEMPLATE.
    for marker in ("<|system|>", "<|user|>", "<|assistant|>"):
        vocab[marker] = len(vocab)
    tokenizer = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tokenizer.pre_tokenizer = pre_tokenizers.Whitespace()
    return tokenizer


def save_tokenizer_json(directory: str, model_name: str = "test-model") -> str:
    """Save under ``<dir>/<model>/tokenizer.json``; returns the dir."""
    model_dir = os.path.join(directory, model_name)
    os.makedirs(model_dir, exist_ok=True)
    build_fast_tokenizer().save(os.path.join(model_dir, "tokenizer.json"))
    return directory


def build_transformers_tokenizer(chat_template: str = CHAT_TEMPLATE):
    from transformers import PreTrainedTokenizerFast

    wrapped = PreTrainedTokenizerFast(
        tokenizer_object=build_fast_tokenizer(),
        unk_token="<unk>",
        bos_token="<s>",
        eos_token="</s>",
    )
    wrapped.chat_template = chat_template
    return wrapped
