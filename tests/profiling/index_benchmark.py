"""Index microbenchmarks: Add/Lookup across backends.

Counterpart of the reference's profiling harness
(tests/profiling/kv_cache_index/index_benchmark_test.go:97-197):
fixed-seed key sets, per-backend Add and Lookup timings, the in-process
RESP server standing in for Redis (their miniredis pattern).

    python tests/profiling/index_benchmark.py [--keys 10000] [--pods 8]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "..")
)

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (  # noqa: E402
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (  # noqa: E402
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (  # noqa: E402
    CostAwareIndexConfig,
    InMemoryIndexConfig,
    PodEntry,
    RedisIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (  # noqa: E402
    RedisIndex,
)
from tests.helpers.miniresp import MiniRespServer  # noqa: E402

SEED = 42
LOOKUP_CHAIN = 64  # keys per lookup (a ~1k-token prompt at block=16)


def bench_backend(name: str, index, n_keys: int, n_pods: int) -> dict:
    rng = random.Random(SEED)
    keys = [rng.getrandbits(64) for _ in range(n_keys)]
    entries = [
        [PodEntry(f"pod-{i % n_pods}", "hbm")] for i in range(n_keys)
    ]

    start = time.perf_counter()
    for i, key in enumerate(keys):
        index.add([key], [key], entries[i])
    add_seconds = time.perf_counter() - start

    lookups = 0
    start = time.perf_counter()
    for offset in range(0, n_keys - LOOKUP_CHAIN, LOOKUP_CHAIN):
        index.lookup(keys[offset:offset + LOOKUP_CHAIN], None)
        lookups += 1
    lookup_seconds = time.perf_counter() - start

    return {
        "backend": name,
        "add_us_per_key": 1e6 * add_seconds / n_keys,
        "lookup_us_per_chain": 1e6 * lookup_seconds / max(lookups, 1),
        "chain_len": LOOKUP_CHAIN,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--keys", type=int, default=10_000)
    parser.add_argument("--pods", type=int, default=8)
    args = parser.parse_args()

    resp = MiniRespServer()
    backends = [
        ("in_memory", InMemoryIndex(InMemoryIndexConfig(size=args.keys * 2))),
        (
            "cost_aware",
            CostAwareMemoryIndex(
                CostAwareIndexConfig(max_cost_bytes=2 << 30)
            ),
        ),
        ("redis(miniresp)", RedisIndex(RedisIndexConfig(address=resp.address))),
    ]
    try:
        for name, index in backends:
            print(
                json.dumps(bench_backend(name, index, args.keys, args.pods))
            )
    finally:
        resp.close()


if __name__ == "__main__":
    main()
