"""Offload-engine throughput benchmark (VERDICT r3 #4).

The NUMA-pinned C++ engine exists to be fast; this measures it.
Counterpart of the reference connector's throughput calc
(kv_connectors/llmd_fs_backend/tests/test_fs_backend.py), minus CUDA:
here the moved bytes are host-RAM KV group buffers, the same shape the
TPU connector stages (offload/worker.py one-gather-one-DMA groups).

Measures, per engine (native C++ vs Python fallback):

* store GB/s — N group files written via async jobs, wait-harvested;
* load GB/s — same files read back into preallocated buffers;
* store GB/s with ``skip_existing`` dedupe hitting resident files.

And the tier latency ladder the manager's scorer weights encode:

* host-tier hit  — HostTierCache.get (DRAM, no syscall);
* file read      — engine load of one group from the filesystem.

Emits one JSON line; run from repo root:

    python tests/profiling/offload_benchmark.py [--files 64] [--mb 4]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import shutil
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(
    0, str(pathlib.Path(__file__).resolve().parent.parent.parent)
)

from llm_d_kv_cache_manager_tpu.native import get_library  # noqa: E402
from llm_d_kv_cache_manager_tpu.native.engine import JobStatus, OffloadEngine
from llm_d_kv_cache_manager_tpu.offload.host_tier import HostTierCache


def run_jobs(engine, direction, paths, buffers, skip_existing=True):
    """Submit one job per file, wait for all; returns elapsed seconds."""
    t0 = time.perf_counter()
    for job_id, (path, buffer) in enumerate(zip(paths, buffers)):
        if direction == "store":
            engine.store(job_id, [path], [buffer], skip_existing)
        else:
            engine.load(job_id, [path], [buffer])
    for job_id in range(len(paths)):
        status = engine.wait(job_id)
        assert status == JobStatus.SUCCEEDED, f"job {job_id}: {status}"
    return time.perf_counter() - t0


def bench_engine(native: bool, root: str, n_files: int, file_mb: int,
                 threads: int) -> dict:
    """GB/s for one engine flavor over its own directory."""
    if native and get_library() is None:
        return {"skipped": "native library unavailable"}
    engine = OffloadEngine(n_threads=threads)
    if native != engine.is_native:
        engine.close()
        return {"skipped": f"wanted native={native}"}
    try:
        rng = np.random.default_rng(0)
        buffers = [
            rng.integers(0, 255, size=file_mb << 20, dtype=np.uint8)
            for _ in range(n_files)
        ]
        paths = [f"{root}/{i:03d}/blk_{i}.bin" for i in range(n_files)]
        total_gb = n_files * file_mb / 1024

        store_s = run_jobs(engine, "store", paths, buffers,
                           skip_existing=False)
        dedupe_s = run_jobs(engine, "store", paths, buffers,
                            skip_existing=True)
        read_bufs = [np.empty_like(b) for b in buffers]
        load_s = run_jobs(engine, "load", paths, read_bufs)
        assert all(
            np.array_equal(a, b) for a, b in zip(buffers, read_bufs)
        ), "loaded bytes differ from stored bytes"
        return {
            "threads": threads,
            "files": n_files,
            "file_mb": file_mb,
            "store_gb_s": round(total_gb / store_s, 3),
            "load_gb_s": round(total_gb / load_s, 3),
            "dedupe_store_gb_s": round(total_gb / dedupe_s, 3),
        }
    finally:
        engine.close()


def bench_tier_latency(root: str, file_mb: int, reps: int = 50) -> dict:
    """Host-tier-hit vs file-read latency for ONE group fetch."""
    group = np.random.default_rng(1).integers(
        0, 255, size=file_mb << 20, dtype=np.uint8
    )
    tier = HostTierCache(max_bytes=group.nbytes * 2)
    tier.put(0xF00D, group)

    hit_us = []
    for _ in range(reps):
        t0 = time.perf_counter()
        got = tier.get(0xF00D)
        hit_us.append((time.perf_counter() - t0) * 1e6)
        assert got is not None

    engine = OffloadEngine(n_threads=1)
    path = f"{root}/tier_probe.bin"
    engine.store(0, [path], [group], skip_existing=False)
    assert engine.wait(0) == JobStatus.SUCCEEDED
    out = np.empty_like(group)
    file_us = []
    for job_id in range(1, reps + 1):
        t0 = time.perf_counter()
        engine.load(job_id, [path], [out])
        assert engine.wait(job_id) == JobStatus.SUCCEEDED
        file_us.append((time.perf_counter() - t0) * 1e6)
    engine.close()
    return {
        "group_mb": file_mb,
        "host_tier_hit_us_p50": round(statistics.median(hit_us), 2),
        "file_read_us_p50": round(statistics.median(file_us), 2),
        "file_vs_host_ratio": round(
            statistics.median(file_us) / max(statistics.median(hit_us), 1e-3),
            1,
        ),
        "engine": "native" if get_library() is not None else "python",
    }


def _quiesce() -> None:
    """Flush dirty pages and (best-effort) drop the page cache so each
    sweep config starts from the same I/O state — without this, store
    GB/s swings ~20x run-to-run as earlier configs' writeback stalls
    land on later ones."""
    os.sync()
    try:
        with open("/proc/sys/vm/drop_caches", "w") as handle:
            handle.write("3\n")
    except OSError:
        pass  # unprivileged: medians still bound the noise


def bench_engine_median(
    native: bool, root: str, n_files: int, file_mb: int, threads: int,
    reps: int = 3,
) -> dict:
    """Median-of-``reps`` bench_engine, quiesced between runs: store
    GB/s on this VM is bimodal (~0.2 vs ~3.5 — writeback throttling
    randomly taxes a run), so single-shot rows are dice rolls."""
    rows = []
    for rep in range(reps):
        _quiesce()
        row = bench_engine(
            native, f"{root}/r{rep}", n_files, file_mb, threads
        )
        if "skipped" in row:
            return row
        rows.append(row)
    out = dict(rows[0])
    for field in ("store_gb_s", "load_gb_s", "dedupe_store_gb_s"):
        values = [r[field] for r in rows]
        out[field] = statistics.median(values)
        out[field + "_all"] = values
    out["reps"] = reps
    return out


def thread_sweep(
    root: str, n_files: int, file_mb: int, counts: list, reps: int = 3
) -> list:
    """Median-of-``reps`` native store/load GB/s per thread count,
    quiesced between runs.  The interesting axis is store: I/O threads
    overlap blocking writes even on a single core."""
    rows = []
    for threads in counts:
        row = bench_engine_median(
            True, f"{root}/sweep-{threads}", n_files, file_mb, threads,
            reps,
        )
        if "skipped" in row:
            return [row]
        rows.append(row)
    return rows


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--files", type=int, default=64)
    parser.add_argument("--mb", type=int, default=4)
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument(
        "--thread-sweep",
        default="",
        help="comma-separated thread counts; adds a native store/load "
        "GB/s row per count (the thread pool's raison d'etre, "
        "measured — I/O threads overlap blocking writes even on one "
        "core)",
    )
    args = parser.parse_args()

    root = tempfile.mkdtemp(prefix="kvtpu-offload-bench-")
    try:
        result = {
            "bench": "offload_throughput",
            "native": bench_engine_median(
                True, f"{root}/native", args.files, args.mb, args.threads
            ),
            "python_fallback": {},
            "tier_latency": bench_tier_latency(f"{root}/tier", args.mb),
        }
        if args.thread_sweep:
            result["native_thread_sweep"] = thread_sweep(
                root, args.files, args.mb,
                [int(n) for n in args.thread_sweep.split(",")],
            )
        # Force the Python fallback (loader honors this env knob).
        os.environ["KVTPU_DISABLE_NATIVE"] = "1"
        try:
            result["python_fallback"] = bench_engine_median(
                False, f"{root}/python", args.files, args.mb, args.threads
            )
        finally:
            del os.environ["KVTPU_DISABLE_NATIVE"]
        native = result["native"]
        fallback = result["python_fallback"]
        if "store_gb_s" in native and "store_gb_s" in fallback:
            result["native_vs_python"] = {
                "store": round(
                    native["store_gb_s"] / fallback["store_gb_s"], 2
                ),
                "load": round(
                    native["load_gb_s"] / fallback["load_gb_s"], 2
                ),
            }
        print(json.dumps(result))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
