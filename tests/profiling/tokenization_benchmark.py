"""Tokenization-path microbenchmarks.

Counterpart of the reference's `make bench` Go benchmarks (chat
templating + tokenization, Makefile:214-219 there): measures the three
costs on the scoring hot path — full tokenization, the prefix-store
fast path that usually replaces it, and chat-template rendering.

Run from the repo root:

    python tests/profiling/tokenization_benchmark.py [--chars 40000]

One JSON line with per-op latencies and the fast-path speedup.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import statistics
import sys
import time

sys.path.insert(
    0, str(pathlib.Path(__file__).resolve().parent.parent.parent)
)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from helpers.tiny_tokenizer import (  # noqa: E402
    build_transformers_tokenizer,
)
from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (  # noqa: E402,E501
    ApplyChatTemplateRequest,
    ChatTemplatingProcessor,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (  # noqa: E402,E501
    LRUStoreConfig,
    LRUTokenStore,
)

MODEL = "bench-model"


def timed(fn, reps=30):
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        samples.append((time.perf_counter() - t0) * 1e3)
    return round(statistics.median(samples), 3)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--chars", type=int, default=40_000)
    args = parser.parse_args()

    tokenizer = build_transformers_tokenizer()
    sentence = "the quick brown fox jumps over the lazy dog . "
    prompt = sentence * (args.chars // len(sentence))

    def full_tokenize():
        return tokenizer(
            prompt, add_special_tokens=True, return_offsets_mapping=True
        )

    encoding = full_tokenize()
    tokens = list(encoding["input_ids"])
    offsets = list(encoding["offset_mapping"])

    store = LRUTokenStore(LRUStoreConfig())
    store.add_tokenization(prompt, tokens, offsets, MODEL)

    def fast_path():
        return store.find_longest_contained_tokens(prompt, MODEL)

    cached_tokens, ratio = fast_path()

    chat = ChatTemplatingProcessor()
    chat.register_tokenizer(MODEL, tokenizer)
    render_req = ApplyChatTemplateRequest(
        conversation=[
            {"role": "system", "content": sentence * 40},
            {"role": "user", "content": sentence * 4},
        ]
    )

    full_ms = timed(full_tokenize)
    fast_ms = timed(fast_path)
    render_ms = timed(
        lambda: chat.apply_chat_template(MODEL, render_req)
    )
    print(
        json.dumps(
            {
                "bench": "tokenization",
                "prompt_chars": len(prompt),
                "prompt_tokens": len(tokens),
                "full_tokenize_ms": full_ms,
                "prefix_store_lookup_ms": fast_ms,
                "fast_path_speedup": round(full_ms / max(fast_ms, 1e-6), 1),
                "prefix_store_coverage": round(ratio, 4),
                "chat_render_ms": render_ms,
            }
        )
    )


if __name__ == "__main__":
    main()
