"""Guards on bench.py's fleet-simulation semantics.

The headline number's meaning rests on these behaviors; a silent change
to any of them would alter what the benchmark measures without failing
anything.  All run on CPU with no device arrays (SimPod with_kv=False).
"""

import random

import pytest

import bench
from bench import (
    EstimatedScorer,
    FleetRouter,
    SimPod,
    block_hash_chain,
    poisson_arrivals,
    run_fleet_virtual,
    warmup_indexes,
)

BS = bench.BLOCK_SIZE


def prefix_tokens(n_blocks, seed=1):
    rng = random.Random(seed)
    return [rng.randrange(1, 1000) for _ in range(n_blocks * BS)]


class TestBlockHashChain:
    def test_deterministic_and_chained(self):
        tokens = prefix_tokens(4)
        a = block_hash_chain(tokens)
        b = block_hash_chain(tokens)
        assert a == b and len(a) == 4
        # A change in block 0 reflows every later hash (chaining).
        mutated = [tokens[0] + 1] + tokens[1:]
        c = block_hash_chain(mutated)
        assert all(x != y for x, y in zip(a, c))

    def test_partial_block_dropped(self):
        tokens = prefix_tokens(2) + [5]  # one dangling token
        assert len(block_hash_chain(tokens)) == 2


class TestSimPodAllocator:
    def test_wrap_evicts_and_reports(self):
        pod = SimPod("p", with_kv=False, pool_blocks=4)
        hashes = [10, 11, 12, 13]
        ids, evicted = pod.alloc(4)
        assert evicted == []
        for h, bid in zip(hashes, ids):
            pod.cached[h] = bid
            pod._block_owner[bid] = h
        # Wrapping reuses block 0 and 1: their hashes must be evicted.
        _, evicted = pod.alloc(2)
        assert set(evicted) == {10, 11}
        assert 10 not in pod.cached and 12 in pod.cached

    def test_cached_prefix_stops_at_first_miss(self):
        pod = SimPod("p", with_kv=False, pool_blocks=8)
        pod.cached = {1: 0, 2: 1, 4: 3}
        assert pod.cached_prefix_blocks([1, 2, 3, 4]) == [0, 1]


class TestEstimatedScorer:
    def test_longest_prefix_wins(self):
        scorer = EstimatedScorer()
        scorer.record("a", [1, 2])
        scorer.record("b", [1, 2, 3])
        assert scorer.pick(["a", "b"], [1, 2, 3, 4]) == "b"

    def test_unknown_prefix_returns_none(self):
        scorer = EstimatedScorer()
        scorer.record("a", [1])
        assert scorer.pick(["a"], [99]) is None

    def test_lru_cap(self):
        scorer = EstimatedScorer(capacity_per_pod=2)
        scorer.record("a", [1, 2, 3])  # 1 falls off
        assert scorer.pick(["a"], [1]) is None
        assert scorer.pick(["a"], [2]) == "a"


class TestFleetRouterSemantics:
    def _router(self, strategy, **kwargs):
        return FleetRouter(strategy, with_kv=False, **kwargs)

    def test_round_robin_cycles(self):
        fleet = self._router("round_robin")
        try:
            n = bench.NUM_PODS
            pods = [
                fleet.route("", [1])[0].name for _ in range(2 * n)
            ]
            assert pods[:n] == sorted(set(pods))
            assert pods[:n] == pods[n:]
        finally:
            fleet.shutdown()

    def test_load_routes_to_least_backlogged(self):
        fleet = self._router("load")
        try:
            for name in fleet.pod_free_at:
                fleet.pod_free_at[name] = 5.0
            fleet.pod_free_at["pod-2"] = 1.0
            assert fleet.route("", [1])[0].name == "pod-2"
        finally:
            fleet.shutdown()

    def test_account_register_commit_roundtrip(self):
        """A committed full-prefix request must hit on re-arrival, and
        the register-only-new-blocks invariant must hold: a hit commit
        never re-registers prefix hashes."""
        fleet = self._router("round_robin")
        try:
            pod = fleet.pods[0]
            n_pre = bench.PREFIX_TOKENS // BS
            tokens = prefix_tokens(n_pre + 2)
            hashes = block_hash_chain(tokens)
            hit, first_new, block_ids, evicted = fleet.account(pod, hashes)
            assert not hit and first_new == 0
            fleet.commit(pod, tokens, hashes, first_new, block_ids, evicted)
            hit2, first_new2, block_ids2, _ = fleet.account(pod, hashes)
            assert hit2 and first_new2 == n_pre
            assert block_ids2[:n_pre] == block_ids[:n_pre]
        finally:
            fleet.shutdown()

    def test_precise_learns_through_real_indexer(self):
        fleet = self._router("precise")
        try:
            pod = fleet.pods[2]
            n_pre = bench.PREFIX_TOKENS // BS
            tokens = prefix_tokens(n_pre + 1, seed=7)
            text = " ".join(f"t{t}" for t in tokens)
            hashes = block_hash_chain(tokens)
            _, first_new, block_ids, evicted = fleet.account(pod, hashes)
            fleet.commit(pod, tokens, hashes, first_new, block_ids, evicted)
            chosen, routing_s = fleet.route(text, hashes)
            assert chosen.name == pod.name
            assert routing_s > 0  # real measured indexer wall time
        finally:
            fleet.shutdown()

    def test_zero_score_fallback_is_sticky_affinity(self):
        fleet = self._router("precise")
        try:
            hashes = block_hash_chain(prefix_tokens(4, seed=9))
            first, _ = fleet.route("t1", hashes)
            # Nothing indexed: record routing history, then the same
            # prefix must go back to the same pod (no rr scatter).
            fleet.estimated.record(first.name, hashes)
            again, _ = fleet.route("t1", hashes)
            assert again.name == first.name
        finally:
            fleet.shutdown()


class TestVirtualClock:
    def test_queueing_builds_ttft(self):
        """Round-robin over NUM_PODS pods with simultaneous arrivals:
        the wrap-around request queues behind the busy pod AND hits its
        cached prefix (TTFT = wait + t_hit)."""
        n_pre = bench.PREFIX_TOKENS // BS
        tokens = prefix_tokens(n_pre + 1)
        n = bench.NUM_PODS + 1
        requests = [(0, "", tokens)] * n
        hashes_list = [block_hash_chain(tokens)] * n
        ttfts, hit_rate, depth, _ = run_fleet_virtual(
            "round_robin",
            requests,
            hashes_list,
            arrivals=[0.0] * n,
            t_miss=1.0,
            t_hit=0.1,
            seed=0,
        )
        assert ttfts[: bench.NUM_PODS] == pytest.approx(
            [1.0] * bench.NUM_PODS
        )
        assert ttfts[-1] == pytest.approx(1.0 + 0.1)
        assert depth > 0

    def test_restart_wipes_history_not_index(self):
        n_pre = bench.PREFIX_TOKENS // BS
        tokens = prefix_tokens(n_pre + 1, seed=3)
        text = " ".join(f"t{t}" for t in tokens)
        requests = [(0, text, tokens)] * 4
        hashes_list = [block_hash_chain(tokens)] * 4
        arrivals = [0.0, 10.0, 20.0, 30.0]
        # Precise: indexed state survives the reset -> 3 of 4 hit.
        ttfts, hit_rate, _, _ = run_fleet_virtual(
            "precise", requests, hashes_list, arrivals,
            t_miss=1.0, t_hit=0.1, seed=0, reset_history_at=2,
        )
        assert hit_rate == pytest.approx(0.75)
        # Estimated: history reset at 2 -> request 2 falls to rr and
        # can land on a cold pod; hit rate <= precise's.
        _, est_hit, _, _ = run_fleet_virtual(
            "estimated", requests, hashes_list, arrivals,
            t_miss=1.0, t_hit=0.1, seed=0, reset_history_at=2,
        )
        assert est_hit <= hit_rate


class TestHarness:
    def test_warmup_indexes_marks_first_arrivals(self):
        requests = [(1, "", []), (0, "", []), (1, "", []), (0, "", [])]
        assert warmup_indexes(requests) == {0, 1}

    def test_poisson_deterministic_per_seed(self):
        a = poisson_arrivals(10.0, 5, seed=3)
        b = poisson_arrivals(10.0, 5, seed=3)
        c = poisson_arrivals(10.0, 5, seed=4)
        assert a == b != c
        assert all(x < y for x, y in zip(a, a[1:]))


class TestDriverContract:
    """The driver runs `python bench.py` under an unknown timeout and
    captures only the LAST ~2 KB of stdout; these guards pin the
    degrade-don't-die behavior AND the tail-survivable emit contract
    end to end in a real subprocess (tiny geometry, CPU): probe-status
    line first and second-to-last, compact headline (< 1.5 KB) as the
    final line, full detail in the results file."""

    @staticmethod
    def _run(extra_env):
        """Returns (full_results_doc, compact_headline, stdout_lines,
        stderr) after asserting the emit contract's line layout."""
        import json
        import os
        import subprocess
        import sys
        import tempfile

        env = {
            k: v
            for k, v in os.environ.items()
            # Ambient knobs (an exported KVTPU_BENCH_BUDGET_S, say)
            # must not leak in and flip the truncation asserts.
            if not k.startswith("KVTPU_BENCH_")
        }
        results_path = os.path.join(
            tempfile.mkdtemp(prefix="kvtpu-bench-test-"),
            "results.json",
        )
        env.update(
            KVTPU_BENCH_PLATFORM="cpu",
            KVTPU_BENCH_TINY="1",
            KVTPU_BENCH_RESULTS_PATH=results_path,
            JAX_PLATFORMS="cpu",
        )
        env.update(extra_env)
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "bench.py"],
            cwd=here,
            env=env,
            capture_output=True,
            text=True,
            timeout=500,
        )
        assert proc.returncode == 0, proc.stderr[-1500:]
        lines = [
            line for line in proc.stdout.splitlines() if line.strip()
        ]
        # Probe diagnosis survives clipping from EITHER end: first
        # line, and again immediately before the final headline.
        for probe_line in (lines[0], lines[-2]):
            probe = json.loads(probe_line)["probe_status"]
            assert probe["outcome"] in ("ok", "error")
            assert probe["duration_s"] >= 0
        # The final line is the compact headline and must survive the
        # driver's ~2 KB tail capture with margin.
        assert len(lines[-1].encode()) < 1536, len(lines[-1])
        compact = json.loads(lines[-1])
        assert compact["results"] == results_path
        with open(results_path) as handle:
            full = json.load(handle)
        # The compact line mirrors the full artifact's headline.
        assert compact["value"] == full["value"]
        return full, compact, lines, proc.stderr

    def test_full_tiny_run_emits_all_layers(self):
        # Malformed knobs ride along: they must fall back to defaults
        # (so this stays a FULL run) with a stderr note — asserting the
        # env-fallback contract without paying a third subprocess run.
        result, compact, lines, stderr = self._run(
            {
                "KVTPU_BENCH_BUDGET_S": "half-an-hour",
                "KVTPU_BENCH_DEVICE_TIMEOUT_S": "900s",
            }
        )
        detail = result["detail"]
        assert result["value"] > 0
        assert not detail["headline_seeds_truncated"]
        assert not detail["matrix_truncated"]
        assert not detail["decode_truncated"]
        assert len(detail["matrix"]) == 32  # 5x5 ladder + 5 churn + 2 restart
        assert detail["service_times"] == "measured"
        assert detail["routing_precise_us"]["p99"] > 0
        assert detail["micro"]["index_lookup_us_per_chain"] > 0
        assert "[bench +" in stderr  # phase progress lines
        assert detail["budget_s"] == 1500.0
        assert "ignoring malformed" in stderr
        # Persistence regime (acceptance): a warm-recovered index must
        # route at least as well as a cold restart, and the comparison
        # must ride the compact headline so the driver sees it.
        restart = compact["indexer_restart"]
        assert restart == detail["indexer_restart"]
        assert restart["warm_hit_rate"] >= restart["cold_hit_rate"]
        assert restart["recovered_block_keys"] > 0

    def test_tight_budget_degrades_not_dies(self):
        result, compact, _, _ = self._run({"KVTPU_BENCH_BUDGET_S": "1"})
        detail = result["detail"]
        # Headline still present and real; optional layers flagged.
        assert result["value"] > 0
        assert compact["value"] > 0
        assert len(detail["headline_seeds"]) >= 1
        assert detail["decode_truncated"]
        assert detail["matrix_truncated"]
        assert detail["decode_tok_s_per_seq"] is None

    def test_device_failure_emits_cpu_detail_not_empty_artifact(self):
        """The r4 failure mode: a wedged chip produced value 0.0 and
        NOTHING else.  On device-init failure the bench must still emit
        every device-independent layer — matrix (all regimes, from
        calibrated service times), scoring-RPC percentiles, and the
        index/tokenization microbenches — alongside the explicit error
        and a zeroed headline."""
        import json

        result, compact, lines, stderr = self._run(
            {
                "KVTPU_BENCH_FORCE_DEVICE_ERROR": "wedge-simulation",
            }
        )
        assert result["value"] == 0.0
        assert result["vs_baseline"] == 0.0
        assert "wedge-simulation" in result["error"]
        # The compact headline carries the error; the probe lines
        # carry the diagnosis (outcome + error class) at both ends.
        assert "wedge-simulation" in compact["error"]
        probe = json.loads(lines[0])["probe_status"]
        assert probe["outcome"] == "error"
        assert probe["error_class"]
        detail = result["detail"]
        assert detail["device"] == "cpu"
        assert detail["service_times"] == "calibrated"
        assert not detail["matrix_truncated"]
        assert len(detail["matrix"]) == 32  # 5x5 ladder + 5 churn + 2 restart
        assert detail["routing_precise_us"]["p99"] > 0
        assert detail["micro"]["index_lookup_us_per_chain"] > 0
        assert detail["micro"]["hash_chain_tok_s"] > 0
        # The persistence regime is device-free: it must run (and hold
        # warm >= cold) even with the chip unreachable.
        restart = detail["indexer_restart"]
        assert restart["warm_hit_rate"] >= restart["cold_hit_rate"]
        assert "CPU-detail fallback" in stderr
