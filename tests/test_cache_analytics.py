"""Cache-efficiency analytics: ledger, windows, auditor, debug surface.

Covers the tentpole's acceptance properties:

* window frames rotate exactly at their boundaries (injected clock)
  and serialize to canonical CBOR that round-trips;
* the reuse inter-arrival EWMA tracks bursty arrivals;
* divergence math on synthetic phantom / missing / wrong-tier
  inventories, incl. parent-chain resolution through engine hashes;
* ledger ≡ explain: the hot path's attribution (matched blocks, tier
  split) equals the explain surface's, and the score-memo replay
  records exactly what the elided walk would have;
* traced requests carry per-pod blocks_matched/break_index span attrs
  that match explain (the /debug/traces cross-link satellite);
* scores are bit-identical with analytics on vs off;
* the /debug/cachestats endpoint end to end (totals, drill-down,
  audit log) and the /healthz analytics block;
* bounded memory: the family table LRU-evicts at max_families;
* concurrent records against snapshots never lose counts.
"""

from __future__ import annotations

import json
import random
import tempfile
import threading
import urllib.request

import pytest

from llm_d_kv_cache_manager_tpu.analytics.auditor import (
    AuditorConfig,
    IndexAuditor,
)
from llm_d_kv_cache_manager_tpu.analytics.ledger import (
    CacheStatsLedger,
    LedgerConfig,
)
from llm_d_kv_cache_manager_tpu.analytics.windows import (
    Frame,
    WindowRing,
)
from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    decode_canonical,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.resync import (
    CallableInventorySource,
    InventoryBlock,
    PodInventory,
)
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER, use_trace
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding

MODEL = "analytics-model"
BLOCK_SIZE = 4


class WordTokenizer:
    """Deterministic whitespace tokenizer: 'tN' -> N."""

    def type(self) -> str:
        return "word"

    def encode(self, prompt, model_name, add_special_tokens=True):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]))
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens, offsets)


def make_indexer(
    fast=True, ledger=None, cache_stats=None, memo=True
) -> Indexer:
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=1, model_name=MODEL
            ),
            read_path_fast_lane=fast,
            score_memo_size=None if memo else 0,
            cache_stats=cache_stats,
        ),
        tokenizer=WordTokenizer(),
        cache_stats_ledger=ledger,
    )
    indexer.run()
    return indexer


def prompt_of(tokens) -> str:
    return " ".join(f"t{t}" for t in tokens)


def seed_chain(indexer, tokens, pod, tier, blocks=None):
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        0, tokens, MODEL
    )
    if blocks is not None:
        keys = keys[:blocks]
    indexer.kv_block_index.add(keys, keys, [PodEntry(pod, tier)])
    return keys


# ----------------------------- windows ---------------------------------


class TestWindows:
    def test_frame_rotation_at_boundaries(self):
        ring = WindowRing(span_s=5.0, frames=3)  # 15s window
        ring.record(0.0, "hit", 4, 4)
        ring.record(4.999, "miss", 0, 4)  # same frame
        ring.record(5.0, "hit", 4, 4)  # next frame, exactly on edge
        ring.record(10.0, "partial", 2, 4)
        assert ring.totals(10.0)["requests"] == 4
        # 15.0 pushes the window floor past slot 0: its 2 records drop.
        totals = ring.totals(15.0)
        assert totals["requests"] == 2
        assert totals["hits"] == 1 and totals["partials"] == 1
        # Far future: everything rotated out; ring stays bounded.
        empty = ring.totals(1000.0)
        assert empty["requests"] == 0 and empty["hit_rate"] is None
        assert len(ring.live_frames(1000.0)) == 0

    def test_ring_never_exceeds_frame_count(self):
        ring = WindowRing(span_s=1.0, frames=4)
        for second in range(100):
            ring.record(float(second), "hit", 1, 1)
        assert len(ring.live_frames(99.0)) <= 4

    def test_cbor_frames_round_trip(self):
        ring = WindowRing(span_s=5.0, frames=2)
        ring.record(1.0, "hit", 3, 4, {"hbm": 2, "host": 1})
        ring.record(6.0, "miss", 0, 4)
        version, span_ms, frames, payload = decode_canonical(
            ring.to_cbor(6.0)
        )
        assert version == 1 and span_ms == 5000 and frames == 2
        assert len(payload) == 2
        slot, requests, hits, partials, misses, matched, total, tiers = (
            payload[0]
        )
        assert (requests, hits, misses) == (1, 1, 0)
        assert tiers == [["hbm", 2], ["host", 1]]
        # Canonical: equal counts encode to equal bytes.
        assert ring.to_cbor(6.0) == ring.to_cbor(6.0)

    def test_frame_merge_absorbs_counts(self):
        a = Frame(7)
        a.record("hit", 4, 4, {"hbm": 4})
        b = Frame(7)
        b.record("partial", 2, 4, {"host": 2})
        b.merge(a)
        assert b.requests == 2 and b.hits == 1 and b.partials == 1
        assert b.tiers == {"host": 2, "hbm": 4}


# ----------------------------- ledger ----------------------------------


class TestLedger:
    def test_classification_thresholds(self):
        ledger = CacheStatsLedger(LedgerConfig(hit_ratio=1.0))
        assert ledger.classify(10, 10) == "hit"
        assert ledger.classify(9, 10) == "partial"
        assert ledger.classify(0, 10) == "miss"
        ratio = CacheStatsLedger(LedgerConfig(hit_ratio=0.5))
        assert ratio.classify(5, 10) == "hit"
        assert ratio.classify(4, 10) == "partial"
        absolute = CacheStatsLedger(LedgerConfig(hit_blocks=512))
        assert absolute.classify(512, 528) == "hit"
        assert absolute.classify(511, 528) == "partial"

    def test_ewma_under_bursty_arrivals(self):
        ledger = CacheStatsLedger(LedgerConfig())
        family = 0xF00
        # A burst of 1s-spaced arrivals...
        now = 100.0
        for _ in range(8):
            ledger.record(family, MODEL, 4, 4, None, now=now)
            now += 1.0
        ewma_burst = ledger.predicted_interarrival_s(family)
        assert 0.9 <= ewma_burst <= 1.1
        # ...then a long gap pulls the EWMA up, but smoothed (alpha
        # 0.3: one 61s gap from ~1s lands at ~0.3*61 + 0.7*1).
        now += 60.0
        ledger.record(family, MODEL, 4, 4, None, now=now)
        ewma_after_gap = ledger.predicted_interarrival_s(family)
        assert 15.0 <= ewma_after_gap <= 25.0
        # Resumed fast arrivals decay it back down.
        for _ in range(12):
            now += 0.5
            ledger.record(family, MODEL, 4, 4, None, now=now)
        assert ledger.predicted_interarrival_s(family) < 2.0

    def test_reuse_distance_histogram_and_flush_parity(self):
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        before = {}
        for metric in METRICS.cachestats_reuse_distance.collect():
            for sample in metric.samples:
                before[
                    (sample.name, tuple(sorted(sample.labels.items())))
                ] = sample.value
        ledger = CacheStatsLedger(LedgerConfig())
        # Distances: family B seen after 1 other request (distance 2),
        # then repeats at distance 2 each; family A repeats at 2 too.
        for _ in range(10):
            ledger.record(0xA, MODEL, 4, 4, None, now=1.0)
            ledger.record(0xB, MODEL, 4, 4, None, now=1.0)
        ledger.flush_metrics()
        snapshot = ledger.snapshot(now=1.0)
        assert snapshot["reuse_distance"] == {"le_2": 18}
        after = {}
        total = 0.0
        for metric in METRICS.cachestats_reuse_distance.collect():
            for sample in metric.samples:
                key = (
                    sample.name,
                    tuple(sorted(sample.labels.items())),
                )
                delta = sample.value - before.get(key, 0.0)
                if sample.name.endswith("_count"):
                    total = delta
                if sample.name.endswith("_bucket") and delta:
                    after[dict(sample.labels)["le"]] = delta
        assert total == 18.0
        # All 18 observations landed in the le=2 bucket (cumulative
        # buckets: every bound >= 2 carries them).
        assert after.get("2.0") == 18.0

    def test_family_table_bounded_with_lru_eviction(self):
        ledger = CacheStatsLedger(
            LedgerConfig(max_families=8, stripes=1)
        )
        for family in range(16):
            ledger.record(family, MODEL, 4, 4, None, now=1.0)
        assert ledger.families_tracked() == 8
        # Touch family 8 (move-to-end), then insert a new one: the
        # evicted family must be 9 (LRU), not 8.
        ledger.record(8, MODEL, 4, 4, None, now=2.0)
        ledger.record(999, MODEL, 4, 4, None, now=3.0)
        assert ledger.family_detail(8) is not None
        assert ledger.family_detail(9) is None
        snapshot = ledger.snapshot(now=3.0)
        assert snapshot["totals"]["families_evicted"] >= 9

    def test_sample_rate_zero_records_nothing(self):
        ledger = CacheStatsLedger(LedgerConfig(sample_rate=0.0))
        assert not ledger.should_sample()

    def test_tier_sample_gate(self):
        ledger = CacheStatsLedger(LedgerConfig(tier_sample=4))
        due = [ledger.tier_detail_due() for _ in range(8)]
        assert due.count(True) == 2
        always = CacheStatsLedger(LedgerConfig(tier_sample=1))
        assert all(always.tier_detail_due() for _ in range(5))

    def test_concurrent_records_lose_nothing(self):
        ledger = CacheStatsLedger(LedgerConfig(max_families=1024))
        n_threads, per_thread = 8, 500
        barrier = threading.Barrier(n_threads)

        def worker(tid):
            barrier.wait()
            for i in range(per_thread):
                ledger.record(
                    (tid << 16) | (i % 32), MODEL, 8, 8 if i % 2 else 0,
                    {"hbm": 8} if i % 2 else None,
                )
                if i % 100 == 0:
                    ledger.snapshot()

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        totals = ledger.snapshot()["totals"]
        assert totals["recorded"] == n_threads * per_thread
        assert totals["hits"] + totals["partials"] + totals["misses"] == (
            n_threads * per_thread
        )


# ------------------------ ledger ≡ read path ---------------------------


class TestLedgerReadPathConsistency:
    @pytest.fixture()
    def rng(self):
        return random.Random(1234)

    def _run_pair(self, indexer, prompt, pods):
        """Score via the hot path, then explain; return (ledger record
        deltas, explain detail)."""
        ledger = indexer.cache_stats
        before = ledger.snapshot()["totals"]
        scores = indexer.get_pod_scores(prompt, MODEL, pods)
        after = ledger.snapshot()["totals"]
        explain_scores, detail = indexer.get_pod_scores_explained(
            prompt, MODEL, pods
        )
        assert scores == explain_scores
        delta_matched = after["blocks_matched"] - before["blocks_matched"]
        delta_tiers = {
            tier: after["tiers"].get(tier, 0)
            - before["tiers"].get(tier, 0)
            for tier in set(after["tiers"]) | set(before["tiers"])
        }
        delta_tiers = {k: v for k, v in delta_tiers.items() if v}
        return delta_matched, delta_tiers, detail

    def test_ledger_matches_explain_property(self, rng):
        """Randomized: residency prefixes of random lengths on random
        tiers — the hot path's recorded matched blocks and tier split
        must equal explain's best pod."""
        ledger = CacheStatsLedger(
            LedgerConfig(sample_rate=1.0, tier_sample=1)
        )
        indexer = make_indexer(ledger=ledger, memo=False)
        try:
            for trial in range(12):
                tokens = [
                    rng.randrange(1, 500) for _ in range(BLOCK_SIZE * 12)
                ]
                tier = rng.choice(["hbm", "host", "shared_storage"])
                blocks = rng.randrange(0, 13)
                if blocks:
                    seed_chain(
                        indexer, tokens, f"pod-{trial}", tier, blocks
                    )
                matched, tiers, detail = self._run_pair(
                    indexer, prompt_of(tokens), None
                )
                per_pod = detail["pods"]
                best = (
                    max(
                        d["blocks_matched"] for d in per_pod.values()
                    )
                    if per_pod
                    else 0
                )
                assert matched == best == blocks
                if blocks:
                    assert tiers == {tier: blocks}, (trial, tiers)
        finally:
            indexer.shutdown()

    def test_memo_replay_records_like_the_walk(self):
        """Exact-repeat requests served from the score memo must feed
        the ledger the same attribution the walk did."""
        ledger = CacheStatsLedger(
            LedgerConfig(sample_rate=1.0, tier_sample=1)
        )
        indexer = make_indexer(ledger=ledger, memo=True)
        try:
            tokens = [7 + i for i in range(BLOCK_SIZE * 8)]
            seed_chain(indexer, tokens, "pod-m", "host", 5)
            prompt = prompt_of(tokens)
            first = indexer.get_pod_scores(prompt, MODEL, ["pod-m"])
            t0 = ledger.snapshot()["totals"]
            for _ in range(3):  # memo hits
                assert (
                    indexer.get_pod_scores(prompt, MODEL, ["pod-m"])
                    == first
                )
            t1 = ledger.snapshot()["totals"]
            assert t1["recorded"] - t0["recorded"] == 3
            assert t1["blocks_matched"] - t0["blocks_matched"] == 15
            assert t1["tiers"]["host"] - t0["tiers"].get("host", 0) == 15
            # One family throughout, with reuse arrivals tracked.
            top = ledger.top_families()
            assert len(top) == 1 and top[0]["requests"] == 4
            assert top[0]["ewma_interarrival_s"] is not None
        finally:
            indexer.shutdown()

    def test_scores_identical_analytics_on_vs_off(self, rng):
        on_ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        on = make_indexer(ledger=on_ledger)
        off = make_indexer(cache_stats=False)
        try:
            assert off.cache_stats is None
            tokens = [
                rng.randrange(1, 300) for _ in range(BLOCK_SIZE * 16)
            ]
            for target in (on, off):
                seed_chain(target, tokens, "pod-x", "hbm", 9)
                seed_chain(target, tokens, "pod-y", "host", 4)
            for _ in range(3):
                prompt = prompt_of(tokens)
                assert on.get_pod_scores(
                    prompt, MODEL, ["pod-x", "pod-y"]
                ) == off.get_pod_scores(prompt, MODEL, ["pod-x", "pod-y"])
        finally:
            on.shutdown()
            off.shutdown()

    def test_straight_lane_records_too(self):
        ledger = CacheStatsLedger(
            LedgerConfig(sample_rate=1.0, tier_sample=1)
        )
        indexer = make_indexer(fast=False, ledger=ledger)
        try:
            tokens = [11 + i for i in range(BLOCK_SIZE * 6)]
            seed_chain(indexer, tokens, "pod-s", "hbm", 6)
            indexer.get_pod_scores(prompt_of(tokens), MODEL, ["pod-s"])
            totals = ledger.snapshot()["totals"]
            assert totals["recorded"] == 1
            assert totals["hits"] == 1
            assert totals["tiers"] == {"hbm": 6}
        finally:
            indexer.shutdown()


class TestReviewRegressions:
    """Pins for the review-pass fixes."""

    def test_family_stable_across_early_exit_and_lanes(self):
        """A dead 2-block memoized prefix must not fragment the family
        id: the fast lane's early exit leaves keys_done short of
        family_blocks, and the family must still be the one the full
        chain defines (same id the straight lane computes)."""
        ledger = CacheStatsLedger(
            LedgerConfig(sample_rate=1.0, family_blocks=4)
        )
        indexer = make_indexer(ledger=ledger)
        try:
            base_tokens = [100 + i for i in range(BLOCK_SIZE * 2)]
            long_tokens = base_tokens + [
                300 + i for i in range(BLOCK_SIZE * 6)
            ]
            # Score the 2-block prompt first: the prefix store memoizes
            # exactly 2 chain keys, so the longer prompt's walk starts
            # from a 2-key memo chunk and dies there (cold index).
            indexer.get_pod_scores(prompt_of(base_tokens), MODEL, None)
            indexer.get_pod_scores(prompt_of(long_tokens), MODEL, None)
            full_keys = indexer.token_processor.tokens_to_kv_block_keys(
                0, long_tokens, MODEL
            )
            expected = f"{full_keys[3]:016x}"
            families = {f["family"] for f in ledger.top_families()}
            assert expected in families, (expected, families)
            # The memo replay uses the same id: repeat and re-check the
            # family's request count moved (not a new fragment).
            indexer.get_pod_scores(prompt_of(long_tokens), MODEL, None)
            detail = ledger.family_detail(full_keys[3])
            assert detail is not None and detail["requests"] == 2
        finally:
            indexer.shutdown()

    def test_explain_path_records_hot_path_tier_split(self):
        """?explain=1 requests must feed the ledger the same per-block
        best-resident-tier split the walk records — not the best pod's
        own tiers."""
        ledger = CacheStatsLedger(
            LedgerConfig(sample_rate=1.0, tier_sample=1)
        )
        indexer = make_indexer(ledger=ledger, memo=False)
        try:
            tokens = [40 + i for i in range(BLOCK_SIZE * 5)]
            # pod-a: 5 blocks on host; pod-b: first 3 on hbm.  Best
            # tier per block: hbm,hbm,hbm,host,host.
            seed_chain(indexer, tokens, "pod-a", "host", 5)
            seed_chain(indexer, tokens, "pod-b", "hbm", 3)
            prompt = prompt_of(tokens)
            before = ledger.snapshot()["totals"]["tiers"]
            indexer.get_pod_scores(prompt, MODEL, None)
            mid = ledger.snapshot()["totals"]["tiers"]
            walk_split = {
                tier: mid.get(tier, 0) - before.get(tier, 0)
                for tier in set(mid) | set(before)
            }
            walk_split = {k: v for k, v in walk_split.items() if v}
            assert walk_split == {"hbm": 3, "host": 2}
            indexer.get_pod_scores_explained(prompt, MODEL, None)
            after = ledger.snapshot()["totals"]["tiers"]
            explain_split = {
                tier: after.get(tier, 0) - mid.get(tier, 0)
                for tier in set(after) | set(mid)
            }
            explain_split = {
                k: v for k, v in explain_split.items() if v
            }
            assert explain_split == walk_split
        finally:
            indexer.shutdown()

    def test_auditor_prunes_departed_pods(self):
        index = TestAuditor()._index()
        pod = SyntheticPod(index, "p0", 10)
        auditor = IndexAuditor(
            index,
            processor(),
            CallableInventorySource(lambda p: pod.inventory(drop_last=2)),
            AuditorConfig(interval_s=0.0),
        )
        auditor.run_cycle()
        assert auditor.status()["divergent_pods"] == {"p0": 0.2}
        index.purge_pod("p0")
        auditor.run_cycle()
        status = auditor.status()
        assert status["divergent_pods"] == {}
        assert status["pods_tracked"] == 0

    def test_healthz_survives_analytics_failure(self):
        indexer = make_indexer(
            ledger=CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        )
        server = serve(indexer, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            def boom():
                raise RuntimeError("analytics bug")

            indexer.cache_stats.stats_summary = boom
            status, health = http_json(base, "/healthz")
            assert status == 200
            assert health["status"] == "ok"
            assert health["analytics"] == {"error": "unavailable"}
        finally:
            server.shutdown()
            indexer.shutdown()

    def test_env_sample_rate_out_of_range_warns_and_defaults(self, monkeypatch):
        monkeypatch.setenv("CACHESTATS_SAMPLE_RATE", "2.5")
        config = LedgerConfig.from_env()
        assert config.sample_rate == 1.0
        # The Indexer construction path must survive the typo'd knob.
        CacheStatsLedger(config)

    def test_multi_tier_inventory_not_order_dependent(self):
        """A pod holding one block on two tiers must audit identically
        regardless of inventory block ordering (tier sets, not
        last-write-wins strings)."""
        index = TestAuditor()._index()
        pod = SyntheticPod(index, "p0", 6, tier="hbm")

        def two_tier_inventory(order):
            blocks = [
                InventoryBlock(
                    block_hashes=list(pod.engine_hashes),
                    token_ids=list(pod.tokens),
                    block_size=BLOCK_SIZE,
                    medium=tier,
                )
                for tier in order
            ]
            return PodInventory(
                pod_identifier="p0", model_name=MODEL, blocks=blocks
            )

        for order in (["hbm", "host"], ["host", "hbm"]):
            auditor = IndexAuditor(
                index,
                processor(),
                CallableInventorySource(
                    lambda p, o=order: two_tier_inventory(o)
                ),
                AuditorConfig(interval_s=0.0),
            )
            report = auditor.audit_pod("p0")
            assert report.outcome == "clean", (order, report.to_dict())

    def test_ledger_close_returns_families_to_gauge(self):
        from llm_d_kv_cache_manager_tpu.metrics.collector import (
            METRICS,
            gauge_value,
        )

        before = gauge_value(METRICS.cachestats_families)
        indexer = make_indexer()  # constructs and owns its ledger
        try:
            ledger = indexer.cache_stats
            for i in range(6):
                ledger.record(0x7000 + i, MODEL, 4, 4, None, now=1.0)
            assert gauge_value(METRICS.cachestats_families) == before + 6
        finally:
            indexer.shutdown()
        assert gauge_value(METRICS.cachestats_families) == before
        ledger.close()  # idempotent
        assert gauge_value(METRICS.cachestats_families) == before

    def test_injected_ledger_survives_indexer_shutdown(self):
        ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        indexer = make_indexer(ledger=ledger)
        indexer.shutdown()
        # Caller-owned: still records after the indexer is gone.
        ledger.record(0x1, MODEL, 4, 4, None, now=1.0)
        assert ledger.snapshot()["totals"]["recorded"] == 1

    def test_families_gauge_aggregates_across_ledgers(self):
        from llm_d_kv_cache_manager_tpu.metrics.collector import (
            METRICS,
            gauge_value,
        )

        before = gauge_value(METRICS.cachestats_families)
        a = CacheStatsLedger(LedgerConfig(max_families=64))
        b = CacheStatsLedger(LedgerConfig(max_families=64))
        for i in range(5):
            a.record(0x1000 + i, MODEL, 4, 4, None, now=1.0)
        for i in range(3):
            b.record(0x2000 + i, MODEL, 4, 4, None, now=1.0)
        assert gauge_value(METRICS.cachestats_families) == before + 8
        # Repeats are not new families; eviction nets insert to zero.
        a.record(0x1000, MODEL, 4, 4, None, now=2.0)
        assert gauge_value(METRICS.cachestats_families) == before + 8


# ------------------------- trace provenance ----------------------------


class TestTraceProvenance:
    def _traced_score(self, indexer, prompt, pods):
        trace = TRACER.start_trace("test.score", force=True)
        with use_trace(trace):
            scores = indexer.get_pod_scores(prompt, MODEL, pods)
        trace.finish()
        provenance = None
        for span in trace.to_dict()["spans"]:
            if span["name"] == "score":
                provenance = span["attributes"].get("provenance")
        return scores, provenance

    @pytest.mark.parametrize("fast", [True, False])
    def test_span_provenance_matches_explain(self, fast):
        """The /debug/traces cross-link: a traced scoring request's
        score span carries per-pod blocks_matched/break_index equal to
        explain's (both lanes, incl. the fast lane's early-exit
        truncation where the break is the first un-looked-up block)."""
        indexer = make_indexer(fast=fast, cache_stats=False)
        try:
            tokens = [3 + i for i in range(BLOCK_SIZE * 10)]
            seed_chain(indexer, tokens, "pod-a", "hbm", 7)
            seed_chain(indexer, tokens, "pod-b", "host", 3)
            prompt = prompt_of(tokens)
            scores, provenance = self._traced_score(
                indexer, prompt, ["pod-a", "pod-b"]
            )
            _, detail = indexer.get_pod_scores_explained(
                prompt, MODEL, ["pod-a", "pod-b"]
            )
            assert provenance is not None
            expected = {
                pod: {
                    "blocks_matched": d["blocks_matched"],
                    "break_index": d["break_index"],
                }
                for pod, d in detail["pods"].items()
            }
            assert provenance == expected
            assert provenance["pod-a"]["break_index"] == 7
            assert provenance["pod-b"]["break_index"] == 3
        finally:
            indexer.shutdown()

    def test_survivor_has_null_break_index(self):
        indexer = make_indexer(cache_stats=False)
        try:
            tokens = [5 + i for i in range(BLOCK_SIZE * 6)]
            seed_chain(indexer, tokens, "pod-full", "hbm")  # whole chain
            _, provenance = self._traced_score(
                indexer, prompt_of(tokens), ["pod-full"]
            )
            assert provenance["pod-full"] == {
                "blocks_matched": 6,
                "break_index": None,
            }
        finally:
            indexer.shutdown()


# ------------------------------ auditor --------------------------------


def processor():
    return ChunkedTokenDatabase(
        TokenProcessorConfig(block_size=BLOCK_SIZE)
    )


class SyntheticPod:
    """Builds an index + matching inventory for one pod."""

    def __init__(self, index, pod, n_blocks, tier="hbm", seed=0):
        rng = random.Random(seed)
        self.pod = pod
        self.tier = tier
        self.proc = processor()
        self.tokens = [
            rng.randrange(1, 1000) for _ in range(n_blocks * BLOCK_SIZE)
        ]
        self.request_keys = self.proc.tokens_to_kv_block_keys(
            0, self.tokens, MODEL
        )
        # Engine hashes differ from request keys (distinct hash scheme).
        self.engine_hashes = [k ^ 0xDEAD for k in self.request_keys]
        index.add(
            self.engine_hashes,
            self.request_keys,
            [PodEntry(pod, tier)],
        )

    def inventory(self, drop_last=0, tier=None):
        keep = len(self.engine_hashes) - drop_last
        return PodInventory(
            pod_identifier=self.pod,
            model_name=MODEL,
            blocks=[
                InventoryBlock(
                    block_hashes=self.engine_hashes[:keep],
                    token_ids=self.tokens[: keep * BLOCK_SIZE],
                    block_size=BLOCK_SIZE,
                    medium=tier or self.tier,
                )
            ],
        )


class TestAuditor:
    def _auditor(self, index, fetch, **config):
        return IndexAuditor(
            index,
            processor(),
            CallableInventorySource(fetch),
            AuditorConfig(interval_s=0.0, **config),
        )

    def _index(self):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )

        return InMemoryIndex()

    def test_clean_pod_is_clean(self):
        index = self._index()
        pod = SyntheticPod(index, "p0", 20)
        auditor = self._auditor(index, lambda p: pod.inventory())
        report = auditor.audit_pod("p0")
        assert report.outcome == "clean"
        assert report.divergence_ratio == 0.0
        assert report.index_claims == 20
        assert report.inventory_blocks == 20

    def test_phantom_blocks_detected(self):
        index = self._index()
        pod = SyntheticPod(index, "p0", 40)
        auditor = self._auditor(
            index, lambda p: pod.inventory(drop_last=4)
        )
        report = auditor.audit_pod("p0")
        assert report.outcome == "divergent"
        assert report.phantom == 4 and report.missing == 0
        assert report.divergence_ratio == pytest.approx(4 / 40)
        assert len(report.phantom_sample) == 4

    def test_missing_blocks_detected(self):
        index = self._index()
        pod = SyntheticPod(index, "p0", 30)
        # The index "lost" the last 6 blocks: purge and re-add a prefix.
        index.purge_pod("p0")
        index.add(
            pod.engine_hashes[:24],
            pod.request_keys[:24],
            [PodEntry("p0", "hbm")],
        )
        auditor = self._auditor(index, lambda p: pod.inventory())
        report = auditor.audit_pod("p0")
        assert report.outcome == "divergent"
        assert report.missing == 6 and report.phantom == 0
        assert report.divergence_ratio == pytest.approx(6 / 30)

    def test_wrong_tier_detected(self):
        index = self._index()
        pod = SyntheticPod(index, "p0", 10, tier="hbm")
        auditor = self._auditor(
            index, lambda p: pod.inventory(tier="host")
        )
        report = auditor.audit_pod("p0")
        assert report.outcome == "divergent"
        assert report.wrong_tier == 10
        assert report.divergence_ratio == pytest.approx(1.0)

    def test_parent_chains_resolved_through_engine_hashes(self):
        """Inventory blocks chained off parents (as engines publish
        them) must resolve to the same request keys the event path
        computed — no false divergence from the split."""
        index = self._index()
        proc = processor()
        rng = random.Random(7)
        tokens = [rng.randrange(1, 1000) for _ in range(BLOCK_SIZE * 12)]
        request_keys = proc.tokens_to_kv_block_keys(0, tokens, MODEL)
        engine_hashes = [k ^ 0xBEEF for k in request_keys]
        index.add(engine_hashes, request_keys, [PodEntry("p0", "hbm")])
        split = 5
        blocks = [
            InventoryBlock(
                block_hashes=engine_hashes[:split],
                token_ids=tokens[: split * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                medium="hbm",
            ),
            InventoryBlock(
                block_hashes=engine_hashes[split:],
                token_ids=tokens[split * BLOCK_SIZE:],
                block_size=BLOCK_SIZE,
                parent_block_hash=engine_hashes[split - 1],
                medium="hbm",
            ),
        ]
        auditor = self._auditor(
            index,
            lambda p: PodInventory(
                pod_identifier=p, model_name=MODEL, blocks=blocks
            ),
        )
        report = auditor.audit_pod("p0")
        assert report.outcome == "clean", report.to_dict()
        assert report.unresolvable == 0

    def test_failed_fetch_keeps_pod_unscored(self):
        index = self._index()
        SyntheticPod(index, "p0", 5)
        auditor = self._auditor(index, lambda p: None)
        report = auditor.audit_pod("p0")
        assert report.outcome == "failed"
        assert "p0" not in auditor.status()["divergent_pods"]

    def test_run_cycle_audits_all_pods_and_logs(self):
        index = self._index()
        p0 = SyntheticPod(index, "p0", 10, seed=1)
        p1 = SyntheticPod(index, "p1", 10, seed=2)
        inventories = {
            "p0": lambda: p0.inventory(),
            "p1": lambda: p1.inventory(drop_last=2),
        }
        auditor = self._auditor(
            index, lambda p: inventories[p]()
        )
        reports = {r.pod: r for r in auditor.run_cycle()}
        assert reports["p0"].outcome == "clean"
        assert reports["p1"].outcome == "divergent"
        status = auditor.status()
        assert status["cycles"] == 1 and status["audits"] == 2
        assert status["divergent_pods"] == {"p1": 0.2}
        assert [r["pod"] for r in auditor.divergent()] == ["p1"]
        assert len(auditor.recent()) == 2

    def test_pods_per_cycle_round_robins(self):
        index = self._index()
        for i in range(4):
            SyntheticPod(index, f"p{i}", 4, seed=i)
        seen = []
        auditor = self._auditor(
            index,
            lambda p: None,  # outcome failed; selection is the point
            pods_per_cycle=2,
        )
        for _ in range(2):
            seen.extend(r.pod for r in auditor.run_cycle())
        assert sorted(seen) == ["p0", "p1", "p2", "p3"]

    def test_audit_log_bounded(self):
        index = self._index()
        pod = SyntheticPod(index, "p0", 4)
        auditor = IndexAuditor(
            index,
            processor(),
            CallableInventorySource(lambda p: pod.inventory()),
            AuditorConfig(interval_s=0.0, log_keep=5),
        )
        for _ in range(20):
            auditor.audit_pod("p0")
        assert len(auditor.recent(100)) == 5

    def test_background_worker_runs_cycles(self):
        import time as _time

        index = self._index()
        pod = SyntheticPod(index, "p0", 4)
        auditor = IndexAuditor(
            index,
            processor(),
            CallableInventorySource(lambda p: pod.inventory()),
            AuditorConfig(interval_s=0.05),
        )
        auditor.start()
        try:
            deadline = _time.time() + 10
            while (
                auditor.status()["cycles"] < 2
                and _time.time() < deadline
            ):
                _time.sleep(0.02)
            assert auditor.status()["cycles"] >= 2
        finally:
            auditor.close()
        assert not auditor.status()["running"]


# ------------------------- debug surface e2e ---------------------------


@pytest.fixture()
def analytics_service():
    from tests.helpers.tiny_tokenizer import save_tokenizer_json
    from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
        LocalFastTokenizer,
    )

    ledger = CacheStatsLedger(
        LedgerConfig(sample_rate=1.0, tier_sample=1)
    )
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
        cache_stats_ledger=ledger,
    )
    indexer.run()
    pod = None
    source_state = {}

    def fetch(pod_id):
        fn = source_state.get(pod_id)
        return fn() if fn else None

    auditor = IndexAuditor(
        indexer.kv_block_index,
        indexer.token_processor,
        CallableInventorySource(fetch),
        AuditorConfig(interval_s=0.0),
    )
    server = serve(indexer, host="127.0.0.1", port=0, auditor=auditor)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    yield base, indexer, auditor, source_state
    del pod
    server.shutdown()
    indexer.shutdown()


def http_json(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as response:
        return response.status, json.load(response)


def http_score(base, prompt):
    request = urllib.request.Request(
        base + "/score_completions",
        data=json.dumps({"prompt": prompt, "model": MODEL}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=10) as response:
        return json.load(response)


class TestDebugCachestatsEndpoint:
    def test_endpoint_and_healthz(self, analytics_service):
        base, indexer, auditor, _ = analytics_service
        prompt = "the quick brown fox jumps over the lazy dog . " * 4
        tokens = indexer.tokenization_pool.tokenize(prompt, MODEL, None)
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            0, tokens, MODEL
        )
        indexer.kv_block_index.add(
            keys, keys, [PodEntry("pod-1", "hbm")]
        )
        for _ in range(2):
            http_score(base, prompt)
        status, stats = http_json(base, "/debug/cachestats")
        assert status == 200
        assert stats["totals"]["recorded"] == 2
        assert stats["totals"]["hits"] >= 1
        assert stats["windows"]["1m"]["requests"] == 2
        family_id = stats["top_families"][0]["family"]
        status, detail = http_json(
            base, f"/debug/cachestats?family={family_id}"
        )
        assert status == 200 and detail["family"] == family_id
        # Unknown family -> 404; bad hex -> 400.
        with pytest.raises(urllib.error.HTTPError) as err:
            http_json(base, "/debug/cachestats?family=00000000000000ff")
        assert err.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as err:
            http_json(base, "/debug/cachestats?family=zzz")
        assert err.value.code == 400
        status, health = http_json(base, "/healthz")
        assert health["analytics"]["cachestats"]["recorded"] == 2
        assert "audit" in health["analytics"]

    def test_disabled_ledger_404s(self):
        indexer = make_indexer(cache_stats=False)
        server = serve(indexer, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                http_json(base, "/debug/cachestats")
            assert err.value.code == 404
            # healthz answers without an analytics block.
            _, health = http_json(base, "/healthz")
            assert "analytics" not in health
        finally:
            server.shutdown()
            indexer.shutdown()
