"""Incident capture & deterministic replay plane (ISSUE 15).

Pins the acceptance contract end to end:

* the input flight recorder's rings (bounds, truncation marking, the
  canonical-CBOR artifact round trip, the f64 codec);
* the pool and indexer taps (post-shed dispositions, displaced
  double-records, resync exclusion, lane-independent score records);
* replay determinism — a randomized mixed workload (kvevents storm +
  multi-turn scoring with the fast lane and score memo on) replayed
  through a FRESH stack reproduces recorded scores and final index
  state exactly, in both single-index and 3-replica LocalCluster
  modes;
* replay-to-divergence — mutated captures report a first divergence;
* the config-fingerprint gate (mismatched knobs refuse with names);
* SLO transition listeners + the incident bundler (atomic bundles,
  rate limit, retention, failing sources);
* CAPTURE=0 inertness (no recorder, no ring, no thread).
"""

import copy
import json
import os
import random
import struct
import threading
import time

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
    ResyncJob,
)
from llm_d_kv_cache_manager_tpu.obs.capture import (
    CaptureConfig,
    IncidentManager,
    InputCaptureRecorder,
    canonical_state,
    capture_enabled_env,
    config_fingerprint,
    decode_f64,
    diff_knobs,
    encode_f64,
    fingerprint_status,
    load_artifact,
    set_build_info_metric,
)
from llm_d_kv_cache_manager_tpu.obs.replay import (
    CaptureMismatchError,
    load_capture,
    render_prompt,
    replay_capture,
)
from llm_d_kv_cache_manager_tpu.obs.slo import SloEngine, SloSpec
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding

MODEL = "cap-model"
BLOCK = 4


class WordTokenizer:
    def type(self):
        return "test-word"

    def encode(self, prompt, model_name, add_special_tokens):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            if word.startswith("t"):
                tokens.append(int(word[1:]))
                offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def make_recorder(**cfg):
    cfg.setdefault("window_s", 3600.0)
    cfg.setdefault("max_bytes", 8 << 20)
    return InputCaptureRecorder(
        CaptureConfig(**cfg),
        meta={"block_size": BLOCK, "hash_seed": "", "model": MODEL},
    )


def make_stack(capture):
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK
            ),
            cache_stats=False,
        ),
        tokenizer=WordTokenizer(),
        capture_recorder=capture,
    )
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
        capture=capture,
    )
    pool.start()
    return indexer, pool


def stored_payload(hashes, tokens, parent=None, medium="hbm"):
    return EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(hashes),
                parent_block_hash=parent,
                token_ids=list(tokens),
                block_size=BLOCK,
                medium=medium,
            )
        ],
    ).encode()


def kvevent_records(recorder):
    """Decoded kvevents records from a dump (no state section)."""
    art = load_artifact(recorder.dump_bytes())
    return [r for r in art["records"] if r[0] == 0]


def score_records(recorder):
    art = load_artifact(recorder.dump_bytes())
    return [r for r in art["records"] if r[0] == 1]


class TestF64Codec:
    @pytest.mark.parametrize(
        "value",
        [0.0, 1.0, 0.8, -3.75, 1e-300, 1.7976931348623157e308,
         0.1 + 0.2, float("inf")],
    )
    def test_round_trip_bit_exact(self, value):
        raw = encode_f64(value)
        assert len(raw) == 8
        assert struct.pack(">d", decode_f64(raw)) == raw
        assert decode_f64(raw) == value


class TestFingerprint:
    def test_stable_and_knob_sensitive(self, monkeypatch):
        before = config_fingerprint()
        assert before == config_fingerprint()
        monkeypatch.setenv("BLOCK_SIZE", "128")
        after = config_fingerprint()
        assert after != before
        diffs = diff_knobs([["BLOCK_SIZE", ""]])
        assert any("BLOCK_SIZE" in d for d in diffs)

    def test_status_and_metric(self):
        status = fingerprint_status()
        assert status["fingerprint"] == config_fingerprint()
        assert status["version"]
        fingerprint = set_build_info_metric()
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        text = METRICS.exposition().decode()
        assert "kvtpu_build_info" in text
        assert fingerprint in text


class TestCaptureRecorder:
    def test_record_and_status(self):
        recorder = make_recorder()
        recorder.record_kvevents("p", "t", MODEL, 1, 0, b"xyz", "admitted")
        recorder.record_score(MODEL, [1, 2, 3, 4], ("p",), {"p": 1.0})
        status = recorder.status()
        assert status["sources"]["kvevents"]["records"] == 1
        assert status["sources"]["scores"]["records"] == 1
        assert status["records"] == 2
        assert not status["sources"]["kvevents"]["truncated"]
        assert status["fingerprint"] == config_fingerprint()

    def test_byte_bound_drops_oldest_and_marks_truncated(self):
        recorder = make_recorder(max_bytes=2048)
        for i in range(64):
            recorder.record_kvevents(
                "p", "t", MODEL, i + 1, 0, b"x" * 200, "admitted"
            )
        status = recorder.status()["sources"]["kvevents"]
        assert status["dropped"] > 0
        assert status["truncated"]
        assert status["bytes"] <= 1024  # per-source half budget
        records = kvevent_records(recorder)
        # Oldest went first: the retained stream is a suffix.
        seqs = [r[6] for r in records]
        assert seqs == sorted(seqs) and seqs[0] > 1

    def test_window_prunes_old_records(self):
        recorder = make_recorder(window_s=0.05)
        recorder.record_kvevents("p", "t", MODEL, 1, 0, b"a", "admitted")
        time.sleep(0.08)
        recorder.record_kvevents("p", "t", MODEL, 2, 0, b"b", "admitted")
        records = kvevent_records(recorder)
        assert [r[6] for r in records] == [2]
        assert recorder.status()["sources"]["kvevents"]["truncated"]

    def test_dump_round_trip(self):
        recorder = make_recorder()
        recorder.record_kvevents("p", "kv@p@m", MODEL, 7, 2, b"pp", "admitted")
        recorder.record_score(
            MODEL, (5, 6, 7, 8), None, {"a": 0.8, "b": 1.0}
        )
        art = load_artifact(recorder.dump_bytes())
        assert art["fingerprint"] == config_fingerprint()
        assert art["meta"]["block_size"] == str(BLOCK)
        assert art["truncated"] == []
        kv = [r for r in art["records"] if r[0] == 0][0]
        assert kv[3:] == ["p", "kv@p@m", MODEL, 7, 2, b"pp", "admitted"]
        score = [r for r in art["records"] if r[0] == 1][0]
        assert score[4] == [5, 6, 7, 8]
        assert score[5] is None
        assert {p: decode_f64(v) for p, v in score[6]} == {
            "a": 0.8,
            "b": 1.0,
        }
        # Global seq totally orders the merged stream.
        assert kv[1] < score[1]

    def test_dump_to_file_atomic(self, tmp_path):
        recorder = make_recorder()
        recorder.record_score(MODEL, [1, 2, 3, 4], None, {})
        path = str(tmp_path / "c.cbor")
        size = recorder.dump(path)
        assert os.path.getsize(path) == size
        assert not os.path.exists(path + ".tmp")

    def test_clear(self):
        recorder = make_recorder()
        recorder.record_score(MODEL, [1], None, {})
        recorder.clear()
        assert recorder.status()["sources"]["scores"]["records"] == 0

    def test_capture_env_gate(self, monkeypatch):
        for raw, expect in (
            ("0", False),
            ("false", False),
            ("off", False),
            ("no", False),
            ("1", True),
            ("yes", True),
        ):
            monkeypatch.setenv("CAPTURE", raw)
            assert capture_enabled_env() is expect
        monkeypatch.delenv("CAPTURE")
        assert capture_enabled_env() is True


class TestPoolCaptureTap:
    def test_admitted_stream_recorded(self):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            for i in range(3):
                tokens = [100 * i + j + 1 for j in range(BLOCK)]
                pool.add_task(
                    Message(
                        topic=f"kv@p@{MODEL}",
                        payload=stored_payload([1000 + i], tokens),
                        pod_identifier="p",
                        model_name=MODEL,
                        seq=i + 1,
                    )
                )
            pool.drain()
        finally:
            pool.shutdown()
            indexer.shutdown()
        records = kvevent_records(recorder)
        assert [(r[3], r[6], r[9]) for r in records] == [
            ("p", 1, "admitted"),
            ("p", 2, "admitted"),
            ("p", 3, "admitted"),
        ]
        assert all(r[8] is not None for r in records)

    def test_poison_pill_recorded_with_payload(self):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            pool.add_task(
                Message(
                    topic=f"kv@p@{MODEL}",
                    payload=b"\x01garbage",
                    pod_identifier="p",
                    model_name=MODEL,
                    seq=1,
                )
            )
            pool.drain()
        finally:
            pool.shutdown()
            indexer.shutdown()
        records = kvevent_records(recorder)
        # A poison pill IS admitted ingress: replay re-drives it and
        # it drops identically in the fresh pool.
        assert records[0][8] == b"\x01garbage"
        assert records[0][9] == "admitted"

    def test_shed_dispositions_and_displacement(self):
        recorder = make_recorder()
        # Unstarted single-shard pool: shed decisions are
        # deterministic (no concurrent drain).
        pool = Pool(
            None,
            None,
            PoolConfig(
                concurrency=1, max_queue_depth=4, pod_budget=2
            ),
            capture=recorder,
        )

        def msg(pod, seq):
            return Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=b"x",
                pod_identifier=pod,
                model_name=MODEL,
                seq=seq,
            )

        # Burst 1: pod a fills its budget, then over-budget sheds its
        # own oldest (same-batch: single shed record, payload kept).
        pool.add_tasks([msg("a", 1), msg("a", 2), msg("a", 3)])
        # Burst 2: pod b overflows the shard; the longest lane (a)
        # pays — a's seq 2 was admitted in burst 1, so its
        # displacement lands as a payload-free second record.
        pool.add_tasks([msg("b", 1), msg("b", 2), msg("b", 3)])
        records = kvevent_records(recorder)
        by_disposition = {}
        for r in records:
            by_disposition.setdefault(r[9], []).append((r[3], r[6], r[8]))
        assert ("a", 1, b"x") in by_disposition["pod_budget"]
        displaced = [
            r for r in records if r[9] != "admitted" and r[8] is None
        ]
        assert displaced and displaced[0][3] == "a"
        # Replay reconciliation drops exactly the displaced admits.
        from llm_d_kv_cache_manager_tpu.obs.replay import (
            _cancel_displaced,
        )

        art = load_artifact(recorder.dump_bytes())
        cancelled, n = _cancel_displaced(art["records"])
        assert n == len(displaced)

    def test_resync_commands_not_recorded(self):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            done = threading.Event()
            job = ResyncJob(
                pod_identifier="p",
                model_name=MODEL,
                events=[],
                on_done=lambda *a: done.set(),
            )
            pool.enqueue_resync(job)
            pool.drain()
            assert done.wait(5)
        finally:
            pool.shutdown()
            indexer.shutdown()
        assert kvevent_records(recorder) == []

    def test_set_capture_late_attach(self):
        indexer, pool = make_stack(None)
        recorder = make_recorder()
        try:
            pool.set_capture(recorder)
            pool.add_task(
                Message(
                    topic=f"kv@p@{MODEL}",
                    payload=stored_payload([1], [1, 2, 3, 4]),
                    pod_identifier="p",
                    model_name=MODEL,
                    seq=1,
                )
            )
            pool.drain()
        finally:
            pool.shutdown()
            indexer.shutdown()
        assert len(kvevent_records(recorder)) == 1


class TestIndexerCaptureTap:
    def test_all_lanes_record_identically(self):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            tokens = [i + 1 for i in range(BLOCK * 6)]
            pool.add_task(
                Message(
                    topic=f"kv@p@{MODEL}",
                    payload=stored_payload(
                        [2000 + b for b in range(6)], tokens
                    ),
                    pod_identifier="p",
                    model_name=MODEL,
                    seq=1,
                )
            )
            pool.drain()
            prompt = render_prompt(tokens)
            walk = indexer.get_pod_scores(prompt, MODEL, ["p"])
            memo_hit = indexer.get_pod_scores(prompt, MODEL, ["p"])
            explained, _ = indexer.get_pod_scores_explained(
                prompt, MODEL, ["p"]
            )
            assert walk == memo_hit == explained
        finally:
            pool.shutdown()
            indexer.shutdown()
        records = score_records(recorder)
        assert len(records) == 3
        first = records[0]
        for record in records[1:]:
            assert record[4] == first[4]  # same served tokens
            assert record[6] == first[6]  # same scores
        assert first[5] == ["p"]

    def test_empty_prompt_recorded(self):
        recorder = make_recorder()
        indexer, _pool = make_stack(recorder)
        try:
            assert indexer.get_pod_scores("t1", MODEL) == {}
        finally:
            _pool.shutdown()
            indexer.shutdown()
        records = score_records(recorder)
        assert len(records) == 1 and records[0][6] == []


def drive_mixed_workload(indexer, pool, seed=11, pods=3, prompts=8):
    """Randomized mixed workload: per-pod contiguous event streams
    interleaved with multi-turn scoring (memo hits included).  Event
    bursts drain before scores — the visibility order the capture's
    global seq records.

    Shape chosen for cross-pod commutativity (the replay contract):
    the SHARED conversation prefix is add-only (pod-entry sets and
    engine mappings commute), while removals ride each pod's PRIVATE
    chain (disjoint token/engine space, single owner → per-pod lane
    order IS total order).  Cross-pod removals of a shared request
    key would make the engine-map cleanup order scheduling-dependent
    in the live run itself — no replay could pin that."""
    rng = random.Random(seed)
    seqs = {}
    turns = []
    convo = []

    def send(pod, payload):
        seqs[pod] = seqs.get(pod, 0) + 1
        pool.add_task(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=payload,
                pod_identifier=pod,
                model_name=MODEL,
                seq=seqs[pod],
            )
        )

    for p in range(prompts):
        convo.extend(
            rng.randrange(1, 30000) for _ in range(BLOCK * 4)
        )
        turns.append(list(convo))
        for pod_i in range(pods):
            if rng.random() < 0.3:
                continue
            pod = f"pod-{pod_i}"
            claimed = rng.randrange(1, len(convo) // BLOCK + 1)
            send(
                pod,
                stored_payload(
                    [
                        90_000 + p * 500 + pod_i * 100 + b
                        for b in range(claimed)
                    ],
                    convo[: claimed * BLOCK],
                ),
            )
            if rng.random() < 0.4:
                # Pod-private add + removal (disjoint token space).
                private_hash = 800_000 + pod_i * 1000 + p
                private_tokens = [
                    40_000 + pod_i * 5000 + p * BLOCK + j + 1
                    for j in range(BLOCK)
                ]
                send(
                    pod,
                    stored_payload([private_hash], private_tokens),
                )
                if rng.random() < 0.5:
                    send(
                        pod,
                        EventBatch(
                            ts=0.0,
                            events=[
                                BlockRemoved(
                                    block_hashes=[private_hash]
                                )
                            ],
                        ).encode(),
                    )
        pool.drain()
        prompt = render_prompt(turns[-1])
        pod_filter = (
            [f"pod-{i}" for i in range(pods)]
            if rng.random() < 0.5
            else None
        )
        for _ in range(rng.randrange(1, 3)):
            indexer.get_pod_scores(prompt, MODEL, pod_filter)


class TestReplayDeterminism:
    @pytest.mark.parametrize("mode", ["single", "cluster"])
    def test_mixed_workload_replays_exactly(self, mode):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            drive_mixed_workload(indexer, pool)
            pool.drain()
            blob = recorder.dump_bytes(index=indexer.kv_block_index)
        finally:
            pool.shutdown()
            indexer.shutdown()
        art = load_capture(blob)
        report = replay_capture(art, mode=mode)
        assert report.ok, report.to_dict()
        assert report.events_applied > 0
        assert report.scores_compared > 0
        assert report.state_compared
        assert report.truncated_sources == []

    def test_replay_is_idempotent(self):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            drive_mixed_workload(indexer, pool, seed=23, prompts=4)
            blob = recorder.dump_bytes(index=indexer.kv_block_index)
        finally:
            pool.shutdown()
            indexer.shutdown()
        art = load_capture(blob)
        assert replay_capture(art).ok
        assert replay_capture(art).ok  # artifact unchanged by replay


class TestReplayDivergence:
    def _capture(self, seed=31):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            drive_mixed_workload(indexer, pool, seed=seed, prompts=4)
            blob = recorder.dump_bytes(index=indexer.kv_block_index)
        finally:
            pool.shutdown()
            indexer.shutdown()
        return load_capture(blob)

    def test_mutated_score_diverges_at_record(self):
        art = self._capture()
        mutated = copy.deepcopy(art)
        target = None
        for record in mutated["records"]:
            if record[0] == 1 and record[6]:
                raw = bytearray(record[6][0][1])
                raw[-1] ^= 0x01
                record[6][0][1] = bytes(raw)
                target = record[1]
                break
        assert target is not None
        report = replay_capture(mutated)
        assert not report.ok
        assert report.divergence["kind"] == "score"
        assert report.divergence["at_seq"] == target
        assert "recorded" in report.divergence["detail"]

    def test_dropped_event_record_diverges(self):
        art = self._capture()
        mutated = copy.deepcopy(art)
        victims = [
            i
            for i, r in enumerate(mutated["records"])
            if r[0] == 0 and r[6] > 1
        ]
        del mutated["records"][victims[0]]
        report = replay_capture(mutated)
        assert not report.ok
        assert report.divergence["kind"] in (
            "seq_classification",
            "score",
        )

    def test_mutated_state_diverges(self):
        art = self._capture()
        mutated = copy.deepcopy(art)
        assert mutated["state"] is not None
        mutated["state"][0][0][1][0][1] = "not-a-tier"
        report = replay_capture(mutated)
        assert not report.ok
        assert report.divergence["kind"] == "state"
        assert "not-a-tier" in report.divergence["detail"]

    def test_truncated_capture_skips_state_comparison(self):
        recorder = make_recorder(max_bytes=4096)
        indexer, pool = make_stack(recorder)
        try:
            drive_mixed_workload(indexer, pool, seed=5, prompts=6)
            blob = recorder.dump_bytes(index=indexer.kv_block_index)
        finally:
            pool.shutdown()
            indexer.shutdown()
        art = load_capture(blob)
        assert art["truncated"]
        report = replay_capture(art)
        assert report.state_compared is False
        assert report.truncated_sources == art["truncated"]

    def test_garbage_artifact_refused(self):
        with pytest.raises(ValueError):
            load_capture(b"not cbor at all")


class TestFingerprintGate:
    def test_mismatched_knob_refused_with_names(self, monkeypatch):
        recorder = make_recorder()
        recorder.record_score(MODEL, [1, 2, 3, 4], None, {})
        blob = recorder.dump_bytes()
        monkeypatch.setenv("BLOCK_SIZE", "999")
        with pytest.raises(CaptureMismatchError) as exc:
            load_capture(blob)
        assert any(
            "BLOCK_SIZE" in diff for diff in exc.value.differences
        )
        # Forensic override still loads.
        art = load_capture(blob, allow_mismatch=True)
        assert art["records"]

    def test_matching_fingerprint_loads(self):
        recorder = make_recorder()
        recorder.record_score(MODEL, [1, 2, 3, 4], None, {})
        assert load_capture(recorder.dump_bytes())["records"]


class TestSloTransitionListener:
    def _engine(self):
        pressure = {"value": 0.0}
        engine = SloEngine(window_fast_s=5.0, window_slow_s=30.0)
        engine.register(
            SloSpec(
                "pressure",
                kind="gauge",
                objective=1.0,
                degraded_bound=2.0,
            ),
            lambda: (pressure["value"], 0.0),
        )
        return engine, pressure

    def test_transitions_fire_once_per_change(self):
        engine, pressure = self._engine()
        calls = []
        engine.add_listener(lambda old, new, p: calls.append((old, new)))
        t0 = 1000.0
        engine.sample(now=t0)
        engine.evaluate(now=t0)
        assert calls == []  # healthy -> healthy: no transition
        pressure["value"] = 5.0
        engine.sample(now=t0 + 1)
        engine.evaluate(now=t0 + 1)
        assert calls == [("healthy", "violated")]
        engine.sample(now=t0 + 2)
        engine.evaluate(now=t0 + 2)
        assert calls == [("healthy", "violated")]  # no re-fire
        # Recovery: the spike must age out of the fast gauge window
        # (max-aggregated) before the state returns to healthy.
        pressure["value"] = 0.0
        engine.sample(now=t0 + 20)
        engine.evaluate(now=t0 + 20)
        assert calls[-1] == ("violated", "healthy")

    def test_reentrant_evaluate_from_listener_delivers_in_order(self):
        """Review-pass pin: transition delivery is FIFO even when a
        listener itself drives another evaluation (the incident
        bundler's sources may hit /debug/slo paths) — the queued
        transition drains after the current one, never interleaved or
        lost, and re-entry cannot deadlock."""
        engine, pressure = self._engine()
        calls = []

        def listener(old, new, payload):
            calls.append((old, new))
            if new == "violated":
                # Recovery observed DURING the violated dispatch: the
                # resulting transition must queue behind it.
                pressure["value"] = 0.0
                engine.sample(now=2000.0)
                engine.evaluate(now=2000.0)

        engine.add_listener(listener)
        pressure["value"] = 5.0
        engine.sample(now=1000.0)
        engine.evaluate(now=1000.0)
        assert calls == [
            ("healthy", "violated"),
            ("violated", "healthy"),
        ]

    def test_raising_listener_never_propagates(self):
        engine, pressure = self._engine()

        def bad(old, new, payload):
            raise RuntimeError("boom")

        engine.add_listener(bad)
        pressure["value"] = 5.0
        engine.sample()
        assert engine.evaluate()["state"] == "violated"


class TestIncidentManager:
    def _manager(self, tmp_path, capture=None, **kw):
        kw.setdefault("min_interval_s", 60.0)
        return IncidentManager(
            str(tmp_path / "incidents"), capture=capture, **kw
        )

    def test_bundle_contents_and_listing(self, tmp_path):
        recorder = make_recorder()
        recorder.record_score(MODEL, [1, 2, 3, 4], None, {"p": 1.0})
        manager = self._manager(
            tmp_path,
            capture=recorder,
            sources={
                "traces": lambda: {"ok": True},
                "boom": lambda: (_ for _ in ()).throw(
                    RuntimeError("down")
                ),
            },
        )
        manifest = manager.trigger("slo:test")
        assert manifest is not None
        assert manifest["reason"] == "slo:test"
        assert "capture.cbor" in manifest["files"]
        assert "traces.json" in manifest["files"]
        assert "boom" in manifest["source_errors"]
        assert manifest["fingerprint"]["fingerprint"] == (
            config_fingerprint()
        )
        bundle = os.path.join(
            str(tmp_path / "incidents"), manifest["id"]
        )
        assert os.path.isdir(bundle)
        assert not os.path.isdir(bundle + ".tmp")
        with open(os.path.join(bundle, "traces.json")) as handle:
            assert json.load(handle) == {"ok": True}
        art = load_capture(os.path.join(bundle, "capture.cbor"))
        assert art["records"]
        listing = manager.list()
        assert listing[0]["id"] == manifest["id"]
        assert manager.last_incident_id() == manifest["id"]
        assert manager.status()["bundles"] == 1

    def test_rate_limit_and_force(self, tmp_path):
        manager = self._manager(tmp_path, min_interval_s=3600.0)
        assert manager.trigger("slo:first") is not None
        assert manager.trigger("slo:second") is None
        assert manager.trigger("admin", force=True) is not None
        assert manager.status()["bundles"] == 2

    def test_retention_prunes_oldest(self, tmp_path):
        manager = self._manager(tmp_path, keep=2, min_interval_s=0.0)
        ids = [
            manager.trigger(f"r{i}", force=True)["id"] for i in range(4)
        ]
        kept = {m["id"] for m in manager.list()}
        assert kept == set(ids[-2:])

    def test_slo_listener_fires_only_into_violated(self, tmp_path):
        manager = self._manager(tmp_path, min_interval_s=0.0)
        listener = manager.slo_listener()
        listener("healthy", "degraded", {"slis": {}})
        assert manager.status()["bundles"] == 0
        listener(
            "healthy",
            "violated",
            {"slis": {"x": {"state": "violated"}}},
        )
        assert manager.status()["bundles"] == 1
        assert manager.list()[0]["reason"] == "slo:x"
        listener("violated", "healthy", {"slis": {}})
        assert manager.status()["bundles"] == 1

    def test_failed_bundle_leaves_no_tmp_dir(self, tmp_path):
        """Review-pass pin: a bundle that dies mid-write (disk full is
        the classic incident-time failure) must not orphan its
        ``inc-*.tmp`` directory — those squat under INCIDENT_DIR
        forever (pruning skips .tmp) and eat the space the next
        bundle needs."""

        class ExplodingCapture:
            def dump_bytes(self, index=None):
                raise OSError("disk full")

        manager = self._manager(tmp_path, capture=ExplodingCapture())
        assert manager.trigger("slo:boom", force=True) is None
        root = str(tmp_path / "incidents")
        assert os.listdir(root) == [], os.listdir(root)
        assert manager.status()["bundles"] == 0

    def test_state_section_from_wired_index(self, tmp_path):
        recorder = make_recorder()
        indexer, pool = make_stack(recorder)
        try:
            drive_mixed_workload(indexer, pool, seed=3, prompts=3)
            manager = self._manager(
                tmp_path,
                capture=recorder,
                index=indexer.kv_block_index,
            )
            manifest = manager.trigger("slo:state")
            bundle = os.path.join(
                str(tmp_path / "incidents"), manifest["id"]
            )
            art = load_capture(os.path.join(bundle, "capture.cbor"))
            assert art["state"] == canonical_state(
                indexer.kv_block_index
            )
            report = replay_capture(art)
            assert report.ok and report.state_compared, report.to_dict()
        finally:
            pool.shutdown()
            indexer.shutdown()


class TestCaptureInertness:
    def test_capture_off_wires_nothing(self, monkeypatch):
        monkeypatch.setenv("CAPTURE", "0")
        assert capture_enabled_env() is False
        indexer, pool = make_stack(None)
        try:
            assert pool._capture is None
            assert indexer.capture is None
            pool.add_task(
                Message(
                    topic=f"kv@p@{MODEL}",
                    payload=stored_payload([1], [1, 2, 3, 4]),
                    pod_identifier="p",
                    model_name=MODEL,
                    seq=1,
                )
            )
            pool.drain()
            indexer.get_pod_scores(render_prompt([1, 2, 3, 4]), MODEL)
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_recorder_has_no_thread(self):
        before = threading.active_count()
        recorder = make_recorder()
        recorder.record_score(MODEL, [1, 2, 3, 4], None, {})
        assert threading.active_count() == before
