"""Serving-fleet chart: render + cross-invariant checks.

The chart's job is to make the fleet-wide invariants (hash seed, block
size, ZMQ port/topic, discovery label, storage path) impossible to
desynchronize: each is defined once in values.yaml and flows into every
consumer.  These tests render the chart (hack/render_chart.py — a
helm-template-compatible subset renderer; real helm renders the same
sources) and assert the rendered engine and indexer agree, mirroring
what the reference chart guarantees by construction
(vllm-setup-helm/templates/deployment.yaml + kv-cache-manager.yaml).
"""

import json
import os
import re
import shutil
import subprocess
import sys

import pytest
import yaml

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "hack"))
from render_chart import render_chart  # noqa: E402

CHART = os.path.join(os.path.dirname(__file__), "..", "deploy", "chart")

# Real helm when present (CI runners ship it), the in-repo subset
# renderer otherwise; KVTPU_CHART_RENDERER=subset|helm forces one.
# Running the SAME assertions through real helm in CI is what keeps a
# subset-renderer divergence from hiding a broken chart (r3 weak #7).
_FORCED = os.environ.get("KVTPU_CHART_RENDERER", "")
HELM = shutil.which("helm") if _FORCED != "subset" else None
if _FORCED == "helm" and not HELM:
    raise RuntimeError("KVTPU_CHART_RENDERER=helm but helm not on PATH")


def render_with_helm(**set_values):
    cmd = ["helm", "template", "kvtpu", CHART]
    for key, value in set_values.items():
        cmd += ["--set", f"{key}={value}"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        # `fail` template messages surface as ValueError, matching the
        # subset renderer, so the guard-rail tests assert one behavior.
        raise ValueError(proc.stderr)
    return proc.stdout


def render(**set_values):
    if HELM:
        text = render_with_helm(**set_values)
    else:
        text = render_chart(CHART, set_values=set_values or None)
    docs = [d for d in yaml.safe_load_all(text) if d is not None]
    return docs


def by_kind(docs, kind, component=None):
    out = []
    for doc in docs:
        if doc["kind"] != kind:
            continue
        labels = doc["metadata"].get("labels", {})
        if component and labels.get("app.kubernetes.io/component") != component:
            continue
        out.append(doc)
    return out


def container(deployment, name):
    for c in deployment["spec"]["template"]["spec"]["containers"]:
        if c["name"] == name:
            return c
    raise AssertionError(f"no container {name!r}")


def env_map(container_spec):
    out = {}
    for env in container_spec.get("env", []):
        if "value" in env:
            out[env["name"]] = env["value"]
    return out


def vllm_args(docs):
    dep = by_kind(docs, "Deployment", component="vllm")[0]
    return container(dep, "vllm")["args"][0]


def extract_kv_transfer(args_text: str) -> dict:
    match = re.search(r"--kv-transfer-config '([^']+)'", args_text)
    assert match, "no --kv-transfer-config in vllm args"
    return json.loads(match.group(1))


def extract_kv_events(args_text: str) -> dict:
    match = re.search(r'--kv-events-config "((?:[^"\\]|\\.)+)"', args_text)
    assert match, "no --kv-events-config in vllm args"
    return json.loads(match.group(1).replace('\\"', '"'))


def flag_value(args_text: str, flag: str) -> str:
    match = re.search(rf"{flag}\s+(\S+)", args_text)
    assert match, f"no {flag} in vllm args"
    return match.group(1).rstrip("\\").strip()


class TestDefaultRender:
    def test_all_documents_parse_with_kind_and_name(self):
        docs = render()
        assert len(docs) >= 7
        for doc in docs:
            assert doc["kind"]
            assert doc["metadata"]["name"]

    def test_expected_components_present(self):
        docs = render()
        kinds = {(d["kind"], d["metadata"]["name"]) for d in docs}
        names = {name for _, name in kinds}
        assert any("vllm" in n for n in names)
        assert any("indexer" in n for n in names)
        assert ("PersistentVolumeClaim", "kvtpu-shared-kv") in kinds
        # Discovery mode needs the pod list/watch grant.
        assert any(k == "Role" for k, _ in kinds)

    def test_tpu_nodepool_no_gpu(self):
        docs = render()
        dep = by_kind(docs, "Deployment", component="vllm")[0]
        pod = dep["spec"]["template"]["spec"]
        assert (
            pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
            == "tpu-v5-lite-podslice"
        )
        resources = container(dep, "vllm")["resources"]
        assert resources["requests"]["google.com/tpu"] == "4"
        assert resources["limits"]["google.com/tpu"] == "4"
        rendered = yaml.safe_dump(dep)
        assert "nvidia.com/gpu" not in rendered


class TestCrossInvariants:
    """A mismatch in any of these silently zeroes the cache-hit rate."""

    def test_hash_seed_agrees(self):
        docs = render()
        vllm_env = env_map(
            container(by_kind(docs, "Deployment", component="vllm")[0], "vllm")
        )
        idx_env = env_map(
            container(
                by_kind(docs, "Deployment", component="indexer")[0], "indexer"
            )
        )
        assert vllm_env["PYTHONHASHSEED"] == idx_env["PYTHONHASHSEED"]

    def test_block_size_agrees(self):
        docs = render()
        args = vllm_args(docs)
        idx_env = env_map(
            container(
                by_kind(docs, "Deployment", component="indexer")[0], "indexer"
            )
        )
        assert flag_value(args, "--block-size") == idx_env["BLOCK_SIZE"]

    def test_offload_block_size_multiple_of_device(self):
        docs = render()
        args = vllm_args(docs)
        transfer = extract_kv_transfer(args)
        extra = transfer["kv_connector_extra_config"]
        device_bs = int(flag_value(args, "--block-size"))
        assert extra["block_size"] % device_bs == 0
        assert extra["spec_name"] == "TPUSharedStorageOffloadingSpec"
        assert (
            extra["spec_module_path"]
            == "llm_d_kv_cache_manager_tpu.offload.vllm_spec"
        )

    def test_engine_hash_algo_is_cbor_interop(self):
        # sha256_cbor engine hashes are absorbed by the indexer's
        # engineKey->requestKey dual-key mapping; the flag must be set
        # whenever events are on (reference deployment.yaml:85).
        args = vllm_args(render())
        assert "--prefix-caching-hash-algo sha256_cbor" in args

    def test_zmq_port_and_topic_agree(self):
        docs = render()
        args = vllm_args(docs)
        events = extract_kv_events(args)
        idx_env = env_map(
            container(
                by_kind(docs, "Deployment", component="indexer")[0], "indexer"
            )
        )
        assert events["enable_kv_cache_events"] is True
        assert events["publisher"] == "zmq"
        # Discovery mode: pod binds locally; indexer dials POD_SOCKET_PORT.
        port = int(events["endpoint"].rsplit(":", 1)[1])
        assert port == int(idx_env["POD_SOCKET_PORT"])
        assert events["topic"].startswith(idx_env["ZMQ_TOPIC"])

    def test_discovery_label_matches_selector(self):
        docs = render()
        vllm_labels = by_kind(docs, "Deployment", component="vllm")[0][
            "spec"
        ]["template"]["metadata"]["labels"]
        idx_env = env_map(
            container(
                by_kind(docs, "Deployment", component="indexer")[0], "indexer"
            )
        )
        key, _, value = idx_env["POD_LABEL_SELECTOR"].partition("=")
        assert vllm_labels.get(key) == value

    def test_shared_storage_path_is_mounted(self):
        docs = render()
        dep = by_kind(docs, "Deployment", component="vllm")[0]
        args = vllm_args(docs)
        extra = extract_kv_transfer(args)["kv_connector_extra_config"]
        mounts = {
            m["name"]: m["mountPath"]
            for m in container(dep, "vllm")["volumeMounts"]
        }
        assert extra["shared_storage_path"].startswith(mounts["shared-kv"])
        volumes = {
            v["name"]: v for v in dep["spec"]["template"]["spec"]["volumes"]
        }
        claim = volumes["shared-kv"]["persistentVolumeClaim"]["claimName"]
        pvc = by_kind(docs, "PersistentVolumeClaim")[0]
        assert pvc["metadata"]["name"] == claim
        assert pvc["spec"]["accessModes"] == ["ReadWriteMany"]

    def test_model_name_agrees(self):
        docs = render()
        args = vllm_args(docs)
        served = args.split("vllm serve ", 1)[1].split()[0]
        idx_env = env_map(
            container(
                by_kind(docs, "Deployment", component="indexer")[0], "indexer"
            )
        )
        assert idx_env["MODEL_NAME"] == served

    def test_tensor_parallel_within_pod_chips(self):
        docs = render()
        args = vllm_args(docs)
        dep = by_kind(docs, "Deployment", component="vllm")[0]
        chips = int(container(dep, "vllm")["resources"]["requests"][
            "google.com/tpu"
        ])
        assert int(flag_value(args, "--tensor-parallel-size")) <= chips


class TestVariants:
    def test_central_socket_mode(self):
        docs = render(**{"indexer.discovery": "false"})
        assert not by_kind(docs, "Role")  # no RBAC needed
        idx = by_kind(docs, "Deployment", component="indexer")[0]
        idx_env = env_map(container(idx, "indexer"))
        assert "POD_DISCOVERY" not in idx_env
        assert idx_env["ZMQ_ENDPOINT"].startswith("tcp://*:")
        bind_port = int(idx_env["ZMQ_ENDPOINT"].rsplit(":", 1)[1])
        # vLLM connects OUT to the indexer service, same port.
        events = extract_kv_events(vllm_args(docs))
        assert "kv-cache-indexer" in events["endpoint"]
        assert int(events["endpoint"].rsplit(":", 1)[1]) == bind_port
        # The service must expose the ZMQ port in this topology.
        svc = by_kind(docs, "Service", component="indexer")[0]
        ports = {p["name"]: p["port"] for p in svc["spec"]["ports"]}
        assert ports["zmq"] == bind_port

    def test_valkey_mode_wires_index_backend(self):
        docs = render(**{"valkey.enabled": "true"})
        valkey_svc = by_kind(docs, "Service", component="valkey")[0]
        idx_env = env_map(
            container(
                by_kind(docs, "Deployment", component="indexer")[0], "indexer"
            )
        )
        backend = idx_env["INDEX_BACKEND"]
        assert backend.startswith("valkey://")
        assert valkey_svc["metadata"]["name"] in backend
        port = valkey_svc["spec"]["ports"][0]["port"]
        assert backend.endswith(f":{port}")

    def test_valkey_disabled_omits_backend(self):
        idx_env = env_map(
            container(
                by_kind(render(), "Deployment", component="indexer")[0],
                "indexer",
            )
        )
        assert "INDEX_BACKEND" not in idx_env

    def test_seed_override_flows_everywhere(self):
        docs = render(**{"hashSeed": '"7"'})
        vllm_env = env_map(
            container(by_kind(docs, "Deployment", component="vllm")[0], "vllm")
        )
        idx_env = env_map(
            container(
                by_kind(docs, "Deployment", component="indexer")[0], "indexer"
            )
        )
        assert vllm_env["PYTHONHASHSEED"] == "7"
        assert idx_env["PYTHONHASHSEED"] == "7"

    def test_existing_claim_suppresses_pvc(self):
        docs = render(**{"sharedStorage.existingClaim": "my-filestore"})
        assert not by_kind(docs, "PersistentVolumeClaim")
        dep = by_kind(docs, "Deployment", component="vllm")[0]
        volumes = {
            v["name"]: v for v in dep["spec"]["template"]["spec"]["volumes"]
        }
        claim = volumes["shared-kv"]["persistentVolumeClaim"]["claimName"]
        assert claim == "my-filestore"

    def test_secret_create_renders_secret(self):
        docs = render(
            **{"secret.create": "true", "secret.hfTokenValue": "hf_abc"}
        )
        secrets = by_kind(docs, "Secret")
        assert len(secrets) == 1
        assert secrets[0]["stringData"]["hf_token"] == "hf_abc"

    def test_offload_disabled_drops_transfer_config(self):
        args = vllm_args(render(**{"vllm.offload.enabled": "false"}))
        assert "--kv-transfer-config" not in args
        assert "--kv-events-config" in args  # events stay on

    def test_offload_without_shared_storage_fails_render(self):
        with pytest.raises(ValueError, match="sharedStorage.enabled"):
            render(
                **{
                    "sharedStorage.enabled": "false",
                    # offload stays on by default — that's the trap the
                    # guard closes.
                }
            )

    def test_multi_replica_indexer_without_valkey_fails_render(self):
        with pytest.raises(ValueError, match="valkey.enabled"):
            render(**{"indexer.replicaCount": "2"})

    def test_multi_replica_indexer_with_valkey_renders(self):
        docs = render(
            **{"indexer.replicaCount": "2", "valkey.enabled": "true"}
        )
        idx = by_kind(docs, "Deployment", component="indexer")[0]
        assert idx["spec"]["replicas"] == 2

    def test_namespace_defaults_to_default_like_helm(self):
        # Real helm sets .Release.Namespace to "default" without -n; the
        # subset renderer must agree or `make chart` output diverges by
        # which binary is installed.
        docs = render()
        assert {d["metadata"]["namespace"] for d in docs} == {"default"}

    def test_shell_command_has_no_dangling_continuation(self):
        for overrides in (
            {},
            {"vllm.offload.enabled": "false"},
            {"indexer.discovery": "false"},
            {"indexer.enabled": "false"},
        ):
            args = vllm_args(render(**overrides))
            lines = [ln.strip() for ln in args.strip().split("\n")]
            assert not lines[-1].endswith("\\"), overrides
            for line in lines[:-1]:
                assert line.endswith("\\"), (overrides, line)


@pytest.mark.skipif(not HELM, reason="real helm not on PATH")
class TestRendererParity:
    """With real helm present, the subset renderer must produce the
    SAME documents — otherwise a renderer bug could pass tests locally
    and fail the install (r3 weak #7)."""

    @pytest.mark.parametrize(
        "overrides",
        [
            {},
            {"valkey.enabled": "true"},
            {"indexer.discovery": "false"},
            {"vllm.offload.enabled": "false"},
            {"sharedStorage.existingClaim": "my-filestore"},
        ],
        ids=["defaults", "valkey", "central", "no-offload", "byo-pvc"],
    )
    def test_subset_renderer_matches_helm(self, overrides):
        def normalize(text):
            docs = [d for d in yaml.safe_load_all(text) if d is not None]
            return sorted(
                docs,
                key=lambda d: (d["kind"], d["metadata"]["name"]),
            )

        helm_docs = normalize(render_with_helm(**overrides))
        subset_docs = normalize(
            render_chart(CHART, set_values=overrides or None)
        )
        assert [
            (d["kind"], d["metadata"]["name"]) for d in helm_docs
        ] == [(d["kind"], d["metadata"]["name"]) for d in subset_docs]
        for helm_doc, subset_doc in zip(helm_docs, subset_docs):
            assert helm_doc == subset_doc, (
                f"renderer divergence in {helm_doc['kind']}/"
                f"{helm_doc['metadata']['name']}"
            )
