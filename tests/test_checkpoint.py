"""Checkpoint/resume: round trip, sharded restore, resume-training."""

import jax
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from llm_d_kv_cache_manager_tpu.models import checkpoint, llama
from llm_d_kv_cache_manager_tpu.parallel.mesh import MeshPlan, make_mesh

CFG = llama.LlamaConfig(
    vocab_size=256,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
)


def test_round_trip(tmp_path):
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    path = checkpoint.save_checkpoint(str(tmp_path / "ckpt"), params)
    restored = checkpoint.restore_checkpoint(path)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        params,
        restored,
    )


def test_sharded_restore_onto_mesh(tmp_path):
    """Save unsharded, restore directly onto a tp=2 x dp=4 mesh — the
    multi-chip resume path."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    path = checkpoint.save_checkpoint(str(tmp_path / "ckpt"), params)

    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices()[:8])
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        llama.param_pspecs(CFG),
        is_leaf=lambda x: isinstance(x, P),
    )
    target = checkpoint.abstract_like(params, shardings)
    restored = checkpoint.restore_checkpoint(path, target)

    embed = restored["embed"]
    assert embed.sharding == shardings["embed"]
    np.testing.assert_array_equal(
        np.asarray(embed), np.asarray(params["embed"])
    )


def test_resume_training_continues(tmp_path):
    """Loss after save/restore matches an uninterrupted run bit-for-bit."""
    optimizer = llama.make_optimizer()
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    opt_state = optimizer.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)

    step = jax.jit(
        lambda p, o, t: llama.train_step(p, o, t, CFG, optimizer)
    )
    params, opt_state, _ = step(params, opt_state, tokens)
    path = checkpoint.save_checkpoint(
        str(tmp_path / "ckpt"), {"params": params, "opt": opt_state}
    )
    params, opt_state, loss_straight = step(params, opt_state, tokens)

    # The optimizer state is a pytree of NamedTuples; restoring against
    # an abstract target preserves that structure (a bare restore
    # returns plain dicts).
    target = checkpoint.abstract_like(
        {"params": params, "opt": opt_state}
    )
    state = checkpoint.restore_checkpoint(path, target)
    _, _, loss_resumed = step(state["params"], state["opt"], tokens)
    assert float(loss_straight) == float(loss_resumed)
