"""CI workflow self-consistency checks.

Round-2 and round-3 reviews both caught `.github/workflows/ci.yaml`
shipping a pip list that could not run the test suite (orbax-checkpoint
was missing while models/checkpoint.py lazily imports orbax at runtime).
This test makes that failure mode structural: it parses the workflow's
`pip install` line and asserts it covers every third-party import
reachable from the suite, so the list can only drift if this test is
updated with it.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
WORKFLOW = REPO / ".github" / "workflows" / "ci.yaml"

# import name -> pip distribution installed by ci.yaml.
IMPORT_TO_DIST = {
    "jax": "jax",
    "jaxlib": "jax",  # jax[cpu] pulls jaxlib
    "numpy": "numpy",
    "msgpack": "msgpack",
    "zmq": "pyzmq",
    "grpc": "grpcio",
    "google": "protobuf",  # google.protobuf
    "prometheus_client": "prometheus-client",
    "transformers": "transformers",
    "huggingface_hub": "transformers",  # hard dependency of transformers
    "tokenizers": "tokenizers",
    "xxhash": "xxhash",
    "ml_dtypes": "ml_dtypes",
    "optax": "optax",
    "orbax": "orbax-checkpoint",
    "yaml": "pyyaml",
    "pytest": "pytest",
    "flake8": "flake8",
}

# Soft-imported integrations the suite skips when absent; CI
# intentionally does not install them.
OPTIONAL_IMPORTS = {
    "torch",  # test_vllm_spec.py gates on pytest.importorskip("torch")
    "vllm",  # offload/vllm_spec.py degrades to stand-in ABCs
    "modelscope",  # services/uds_tokenizer.py: alt hub, gated import
    "flax",
    "chex",
    "einops",
}

LOCAL_TOP_LEVELS = {
    "llm_d_kv_cache_manager_tpu",
    "tests",
    "examples",
    "hack",
    "render_chart",  # hack/render_chart.py imported by test_chart.py
    "helpers",  # tests/helpers, sys.path'd by profiling scripts
    "bench",
    "__graft_entry__",
}


def _workflow_pip_list() -> set:
    text = WORKFLOW.read_text()
    match = re.search(
        r"pip install (.*?)\n\s*- name:", text, flags=re.DOTALL
    )
    assert match, "could not locate the pip install step in ci.yaml"
    tokens = match.group(1).replace("\\\n", " ").split()
    dists = set()
    for token in tokens:
        token = token.strip().strip('"')
        if not token or token == "run:":
            continue
        dists.add(re.split(r"[\[=<>]", token)[0])
    return dists


def _imports_under(path: pathlib.Path, recursive: bool = True) -> set:
    names = set()
    for py in path.rglob("*.py") if recursive else path.glob("*.py"):
        try:
            tree = ast.parse(py.read_text())
        except SyntaxError:  # pragma: no cover - repo must parse
            raise AssertionError(f"unparsable file {py}")
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    names.add(alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                if node.module:
                    names.add(node.module.split(".")[0])
    return names


def test_pip_list_covers_all_required_imports():
    imports = set()
    for sub in ("llm_d_kv_cache_manager_tpu", "tests", "examples", "hack"):
        imports |= _imports_under(REPO / sub)
    # top-level scripts only (bench.py, __graft_entry__.py)
    imports |= _imports_under(REPO, recursive=False)

    stdlib = set(sys.stdlib_module_names)
    third_party = {
        name
        for name in imports
        if name not in stdlib
        and name not in LOCAL_TOP_LEVELS
        and name not in OPTIONAL_IMPORTS
    }

    unmapped = third_party - set(IMPORT_TO_DIST)
    assert not unmapped, (
        f"imports with no pip mapping: {sorted(unmapped)}; add them to "
        "IMPORT_TO_DIST *and* to ci.yaml's pip install list"
    )

    installed = _workflow_pip_list()
    missing = {
        IMPORT_TO_DIST[name]
        for name in third_party
        if IMPORT_TO_DIST[name] not in installed
    }
    assert not missing, (
        f"ci.yaml pip list is missing {sorted(missing)} — the workflow "
        "would fail at the pytest step"
    )


def test_workflow_has_native_format_gate():
    text = WORKFLOW.read_text()
    assert "clang-format" in text, (
        "ci.yaml must gate native/src formatting (reference "
        "ci-pr-checks.yaml runs clang-format)"
    )
    assert (REPO / ".clang-format").exists()


def test_optional_imports_are_really_optional():
    """Every OPTIONAL import must be absent from module import-time paths
    (only inside try/except or function bodies), so CI passes without
    them."""
    import importlib

    for module in (
        "llm_d_kv_cache_manager_tpu.offload.vllm_spec",
        "llm_d_kv_cache_manager_tpu.models.checkpoint",
        "llm_d_kv_cache_manager_tpu.services.uds_tokenizer",
    ):
        importlib.import_module(module)  # must not require optional deps
