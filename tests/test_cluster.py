"""cluster/: ring properties, failover, replication, routing, parity.

The acceptance pins (ISSUE 10 / docs/replication.md):

* the rendezvous ring is deterministic ACROSS PROCESSES (never
  ``hash()``-seeded) and membership changes move ~1/N of the keys —
  each straight to its runner-up, never a full reshuffle;
* a 3-replica in-process cluster returns BIT-IDENTICAL scores to the
  single-process ``InMemoryIndex`` on a randomized workload (the same
  style as the fast-lane parity oracle);
* a killed replica's slice fails over to its journal-fed follower
  warm, and purges are never resurrected by replay;
* the kvevents pool routes admissions to slice owners through the
  unchanged batched-apply surface.
"""

import random
import subprocess
import sys

import pytest

from llm_d_kv_cache_manager_tpu.cluster import (
    ClusterMembership,
    HeartbeatMonitor,
    LocalCluster,
    RemoteIndex,
)
from llm_d_kv_cache_manager_tpu.cluster.replica import (
    ClusterReplica,
    HttpReplicaTransport,
    LocalReplicaTransport,
    ReplicaError,
    ReplicaUnavailable,
)
from llm_d_kv_cache_manager_tpu.cluster.replication import (
    ReplicationFollower,
    standby_record_filter,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    InMemoryIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.persistence.journal import Journal
from tests.test_read_path_fastlane import WordTokenizer, words

MODEL = "m"
POD_A = PodEntry("pod-a", "hbm")
POD_B = PodEntry("pod-b", "host")

KEYS = [((i * 2654435761) ^ (i << 17)) & ((1 << 64) - 1) for i in range(2000)]


# ------------------------------------------------------------- ring


class TestHashRing:
    def test_deterministic_across_processes_and_seeds(self):
        """Ownership must never depend on PYTHONHASHSEED: a router and
        a replica booted with different seeds MUST agree on every
        key's owner (the subprocess recomputes with a different
        seed)."""
        members = ["replica-0", "replica-1", "replica-2"]
        ring = HashRing(members)
        keys = KEYS[:64]
        expected = [ring.owner(k) for k in keys]
        script = (
            "from llm_d_kv_cache_manager_tpu.cluster.ring import "
            "HashRing;"
            f"ring = HashRing({members!r});"
            f"print(','.join(ring.owner(k) for k in {keys!r}))"
        )
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            env={
                "PYTHONHASHSEED": "12345",
                "PATH": "/usr/bin:/bin",
                "PYTHONPATH": ":".join(sys.path),
                "JAX_PLATFORMS": "cpu",
            },
            check=True,
        )
        assert out.stdout.strip().split(",") == expected

    def test_membership_order_is_irrelevant(self):
        a = HashRing(["r2", "r0", "r1"])
        b = HashRing(["r0", "r1", "r2"])
        assert [a.owner(k) for k in KEYS[:200]] == [
            b.owner(k) for k in KEYS[:200]
        ]

    @pytest.mark.parametrize("n", [3, 5, 8])
    def test_remove_moves_about_one_over_n(self, n):
        """Removing one member reassigns ONLY its keys (~1/N of the
        space), each to its rendezvous runner-up — never a reshuffle
        of keys the dead member did not own."""
        members = [f"replica-{i}" for i in range(n)]
        ring = HashRing(members)
        owners = {k: ring.owner(k) for k in KEYS}
        victim = members[0]
        shrunk = ring.without(victim)
        moved = 0
        for k, owner in owners.items():
            new_owner = shrunk.owner(k)
            if owner != victim:
                assert new_owner == owner  # untouched slice
            else:
                moved += 1
                # Straight to the runner-up.
                assert new_owner == ring.owners(k, 2)[1]
        fraction = moved / len(KEYS)
        assert 0.5 / n < fraction < 2.0 / n

    @pytest.mark.parametrize("n", [3, 5])
    def test_add_steals_about_one_over_n_plus_one(self, n):
        members = [f"replica-{i}" for i in range(n)]
        ring = HashRing(members)
        grown = ring.with_member("replica-new")
        moved = sum(
            1 for k in KEYS if grown.owner(k) != ring.owner(k)
        )
        for k in KEYS[:500]:
            if grown.owner(k) != ring.owner(k):
                assert grown.owner(k) == "replica-new"
        fraction = moved / len(KEYS)
        assert 0.5 / (n + 1) < fraction < 2.0 / (n + 1)

    def test_distribution_roughly_uniform(self):
        ring = HashRing(["r0", "r1", "r2", "r3"])
        counts = {}
        for k in KEYS:
            counts[ring.owner(k)] = counts.get(ring.owner(k), 0) + 1
        for member, count in counts.items():
            assert 0.6 * len(KEYS) / 4 < count < 1.4 * len(KEYS) / 4

    def test_versioning_and_immutability(self):
        ring = HashRing(["r0", "r1"], version=3)
        assert ring.version == 3
        shrunk = ring.without("r0")
        assert shrunk.version == 4 and ring.version == 3
        assert ring.without("missing") is ring
        assert ring.with_member("r1") is ring
        grown = ring.with_member("r2")
        assert grown.version == 4 and "r2" in grown

    def test_validation(self):
        with pytest.raises(ValueError):
            HashRing([])
        with pytest.raises(ValueError):
            HashRing([""])


# ------------------------------------------------------- failover


class TestFailover:
    def test_killed_replica_slice_fails_over_warm(self, tmp_path):
        cluster = LocalCluster(journal_root=str(tmp_path))
        try:
            idx = cluster.remote_index
            keys = KEYS[:300]
            idx.add(keys, keys, [POD_A, POD_B])
            assert cluster.sync_followers() > 0

            ring = cluster.membership.ring()
            victim = ring.owner(keys[0])
            owned = [k for k in keys if ring.owner(k) == victim]
            assert owned  # the victim owns a real slice
            before = idx.lookup(owned)

            cluster.kill(victim)
            after = idx.lookup(owned)
            # Warm failover: the runner-up serves the whole slice.
            assert set(after) == set(before)
            for k in owned:
                assert set(after[k]) == set(before[k])
            assert cluster.membership.failover_count() == 1
            assert (
                cluster.membership.ring().version
                == ring.version + 1
            )
        finally:
            cluster.close()

    def test_transport_failure_mid_call_triggers_failover(self, tmp_path):
        """No explicit notice: the first routed call that hits the dead
        replica marks it dead and retries on the new owner."""
        cluster = LocalCluster(journal_root=str(tmp_path))
        try:
            idx = cluster.remote_index
            keys = KEYS[300:400]
            idx.add(keys, keys, [POD_A])
            cluster.sync_followers()
            victim = cluster.membership.ring().owner(keys[0])
            owned = [
                k
                for k in keys
                if cluster.membership.ring().owner(k) == victim
            ]
            cluster.kill(victim, notice=False)
            found = idx.lookup(owned)  # discovers the death inline
            assert set(found) == set(owned)
            assert not cluster.membership.is_alive(victim)
        finally:
            cluster.close()

    def test_purge_is_not_resurrected_by_replay(self, tmp_path):
        """purge_pod is journaled (OP_PURGE) and replays in order: a
        follower syncing AFTER the purge must not resurrect the
        purged pod's entries from the earlier add records."""
        cluster = LocalCluster(journal_root=str(tmp_path))
        try:
            idx = cluster.remote_index
            keys = KEYS[400:500]
            idx.add(keys, keys, [POD_A, POD_B])
            assert idx.purge_pod(POD_B.pod_identifier) > 0
            cluster.sync_followers()  # adds AND the purge replay
            victim = cluster.membership.ring().owner(keys[0])
            cluster.kill(victim)
            found = idx.lookup(keys)
            for pods in found.values():
                assert all(
                    p.pod_identifier != POD_B.pod_identifier
                    for p in pods
                )
        finally:
            cluster.close()

    def test_last_replica_is_never_removed(self):
        cluster = LocalCluster(replica_ids=("only",))
        try:
            assert not cluster.membership.mark_dead("only", "test")
            assert cluster.membership.alive() == ["only"]
        finally:
            cluster.close()

    def test_heartbeat_marks_dead_then_revives(self):
        cluster = LocalCluster()
        try:
            monitor = HeartbeatMonitor(cluster.membership, misses=2)
            transport = cluster.transports["replica-1"]
            transport.kill()
            monitor.beat_once()
            assert cluster.membership.is_alive("replica-1")  # 1 miss
            monitor.beat_once()
            assert not cluster.membership.is_alive("replica-1")
            transport.revive()
            monitor.beat_once()
            assert cluster.membership.is_alive("replica-1")
        finally:
            cluster.close()


# ---------------------------------------------------- replication


class TestReplication:
    def test_bootstrap_then_tail_with_watermark_skip(self, tmp_path):
        """Follower warm-sync: sync_snapshot's dump covers everything
        below the boundary; tailing resumes there, numbered records
        BELOW the watermark are skipped (mirroring recovery), at or
        above replay."""
        primary_dir = str(tmp_path / "primary")
        primary = ClusterReplica(
            "primary",
            index=InMemoryIndex(),
            journal=Journal(primary_dir),
        )
        transport = LocalReplicaTransport(primary)
        # Seq-carrying history (the replica-local event-plane mode).
        primary.index.add([1], [11], [POD_A])
        primary.journal.record_add("pod-a", 5, [1], [11], [POD_A])

        follower_index = InMemoryIndex()
        follower = ReplicationFollower(
            "primary", primary_dir, follower_index
        )
        assert follower.bootstrap(transport) == 1
        assert follower_index.lookup([11]) == {11: [POD_A]}

        # Below-watermark record: its effect is ALREADY in the dump
        # (idempotent anyway); the skip path must classify it.
        primary.journal.record_add("pod-a", 3, [2], [12], [POD_A])
        # At/above watermark: replays.
        primary.journal.record_add("pod-a", 6, [3], [13], [POD_A])
        follower.sync_once()
        status = follower.status()
        assert status["applied"] == 1 and status["skipped"] == 1
        assert follower_index.lookup([13]) == {13: [POD_A]}
        assert follower_index.lookup([13, 12]).get(12) is None
        primary.close()

    def test_standby_filter_trims_to_slice(self):
        full_ring = HashRing(["replica-0", "replica-1", "replica-2"])
        record_filter = standby_record_filter(full_ring, "replica-1")
        from llm_d_kv_cache_manager_tpu.persistence.journal import (
            OP_ADD,
            JournalRecord,
        )

        keys = KEYS[:200]
        record = JournalRecord(
            op=OP_ADD,
            pod_identifier="pod-a",
            seq=0,
            ts_ns=0,
            engine_keys=list(keys),
            request_keys=list(keys),
            entries=[POD_A],
        )
        trimmed = record_filter(record)
        assert trimmed is not None
        expected = [
            k
            for k in keys
            if "replica-1" in full_ring.owners(k, 2)
        ]
        assert trimmed.request_keys == expected
        assert trimmed.engine_keys == expected  # pairs stay aligned
        # A record fully outside the slice drops.
        outside = [
            k
            for k in KEYS
            if "replica-1" not in full_ring.owners(k, 2)
        ][:5]
        record2 = JournalRecord(
            op=OP_ADD,
            pod_identifier="pod-a",
            seq=0,
            ts_ns=0,
            engine_keys=list(outside),
            request_keys=list(outside),
            entries=[POD_A],
        )
        assert record_filter(record2) is None

    def test_mappings_only_records_follow_engine_key_ownership(self):
        """A cross-owner engine->request mapping stub must reach the
        ENGINE-key owner's standby too: after that owner dies,
        get_request_key routes to the standby, and without the mapping
        the router would classify the eviction as 'already gone' and
        leave a stale entry scoring forever."""
        from llm_d_kv_cache_manager_tpu.persistence.journal import (
            OP_ADD,
            JournalRecord,
        )

        full_ring = HashRing(["replica-0", "replica-1", "replica-2"])
        # Find a pair owned on the rk side by someone whose top-2 does
        # NOT include replica-1, while replica-1 stands by the ek side.
        pair = next(
            (ek, rk)
            for ek in KEYS[:500]
            for rk in KEYS[500:600]
            if "replica-1" in full_ring.owners(ek, 2)
            and "replica-1" not in full_ring.owners(rk, 2)
        )
        record = JournalRecord(
            op=OP_ADD,
            pod_identifier="",
            seq=0,
            ts_ns=0,
            engine_keys=[pair[0]],
            request_keys=[pair[1]],
            entries=[],  # mappings-only
        )
        kept = standby_record_filter(full_ring, "replica-1")(record)
        assert kept is not None
        assert kept.engine_keys == [pair[0]]
        assert kept.request_keys == [pair[1]]

    def test_same_owner_pair_eviction_survives_failover(self, tmp_path):
        """A pair whose engine and request keys share a PRIMARY owner
        can still have different standbys: the engine-key standby must
        inherit the mapping (RemoteIndex.add publishes mappings for
        every pair, and the filter keys on either side), or a
        post-failover eviction reads 'already gone' and the stale
        entry scores forever."""
        cluster = LocalCluster(journal_root=str(tmp_path))
        full_ring = cluster.membership.full_ring
        pair = next(
            (ek, rk)
            for ek in KEYS[:300]
            for rk in KEYS[300:500]
            if full_ring.owner(ek) == full_ring.owner(rk)
            and full_ring.owners(ek, 2)[1] != full_ring.owners(rk, 2)[1]
        )
        try:
            idx = cluster.remote_index
            idx.add([pair[0]], [pair[1]], [POD_A])
            cluster.sync_followers()
            victim = full_ring.owner(pair[0])
            cluster.kill(victim)
            # The eviction must resolve through the failed-over
            # engine-key mapping and actually clear the entry.
            idx.evict(pair[0], [POD_A])
            assert idx.lookup([pair[1], KEYS[0]]).get(pair[1]) is None
        finally:
            cluster.close()

    def test_peer_purge_replay_is_slice_scoped(self, tmp_path):
        """Replaying a PEER's pod-wide purge against the whole local
        index would wipe admissions this replica applied to its OWN
        slice after the purge.  The follower scopes the replay to the
        peer's primary slice; the replica's own fresh entries
        survive."""
        cluster = LocalCluster(journal_root=str(tmp_path))
        try:
            idx = cluster.remote_index
            keys = KEYS[:200]
            idx.add(keys, keys, [POD_A])
            cluster.sync_followers()  # standby copies of the adds
            idx.purge_pod(POD_A.pod_identifier)
            # Fresh post-purge claims land on their owners directly.
            idx.add(keys, keys, [POD_A])
            # NOW the followers replay their peers' [adds, purge]
            # streams — the purge must only touch each peer's slice,
            # never the fresh entries of the follower's own slice.
            cluster.sync_followers()
            found = idx.lookup(keys)
            assert set(found) == set(keys)
            # And a failover still serves the slice (the standby
            # replay converged to the same state).
            victim = cluster.membership.ring().owner(keys[0])
            cluster.kill(victim)
            assert set(idx.lookup(keys)) == set(keys)
        finally:
            cluster.close()

    def test_followers_only_hold_standby_slice(self, tmp_path):
        cluster = LocalCluster(journal_root=str(tmp_path))
        try:
            keys = KEYS[:400]
            cluster.remote_index.add(keys, keys, [POD_A])
            cluster.sync_followers()
            full_ring = cluster.membership.full_ring
            for replica_id, replica in cluster.replicas.items():
                resident = {
                    k for k, _ in replica.index.dump_entries()[0]
                }
                for k in resident:
                    assert replica_id in full_ring.owners(k, 2)
        finally:
            cluster.close()


# --------------------------------------- kvevents routing to owners


def _stored_message(
    pod: str, seq: int, block_hashes, token_ids, parent=None
) -> Message:
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(block_hashes),
                parent_block_hash=parent,
                token_ids=list(token_ids),
                block_size=4,
            )
        ],
    )
    return Message(
        topic=f"kv@{pod}@{MODEL}",
        payload=batch.encode(),
        pod_identifier=pod,
        model_name=MODEL,
        seq=seq,
    )


class TestEventRoutingToSliceOwners:
    def test_pool_applies_through_remote_index(self):
        """The unchanged kvevents pool drives the cluster: batched
        admissions land on slice owners, chained parents resolve
        across messages of one batch, evictions route two-hop."""
        cluster = LocalCluster()
        try:
            db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
            pool = Pool(
                cluster.remote_index, db, PoolConfig(concurrency=1)
            )
            pool.start()
            tokens = list(range(1, 13))  # 3 blocks, chained
            pool.add_task(
                _stored_message("pod-a", 1, [101], tokens[:4])
            )
            pool.add_task(
                _stored_message(
                    "pod-a", 2, [102, 103], tokens[4:], parent=101
                )
            )
            pool.drain()

            expected_keys = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, MODEL
            )
            found = cluster.remote_index.lookup(expected_keys)
            assert set(found) == set(expected_keys)
            ring = cluster.membership.ring()
            for key in expected_keys:
                owner = ring.owner(key)
                local = cluster.replicas[owner].index.lookup([key])
                assert key in local  # admission landed on its owner

            # Evictions route through the engine-key mapping.
            removal = EventBatch(
                ts=2.0,
                events=[BlockRemoved(block_hashes=[103])],
            )
            pool.add_task(
                Message(
                    topic=f"kv@pod-a@{MODEL}",
                    payload=removal.encode(),
                    pod_identifier="pod-a",
                    model_name=MODEL,
                    seq=3,
                )
            )
            pool.drain()
            remaining = cluster.remote_index.lookup(expected_keys)
            assert expected_keys[2] not in remaining
            assert expected_keys[1] in remaining
            pool.shutdown()
        finally:
            cluster.close()


# ------------------------------------------------- parity oracle


def _make_indexer(index, fast=True):
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
            kvblock_index_config=IndexConfig(
                in_memory_config=InMemoryIndexConfig(size=200_000)
            ),
            read_path_fast_lane=fast,
            lookup_chunk_size=8,
            score_memo_size=0,
            cache_stats=False,
        ),
        tokenizer=WordTokenizer(),
        kv_block_index=index,
    )
    indexer.run()
    return indexer


class TestScoreParityOracle:
    @pytest.mark.parametrize("seed", [7, 41])
    def test_cluster_scores_bit_identical_to_in_memory(self, seed):
        """The acceptance oracle: a 3-replica in-process cluster must
        return BIT-IDENTICAL scores to the single-process
        InMemoryIndex on a randomized workload, through the real
        scoring read path (fast lane AND straight lane)."""
        rng = random.Random(seed)
        cluster = LocalCluster(strict_wire=True)
        single = _make_indexer(InMemoryIndex())
        clustered = _make_indexer(cluster.remote_index)
        straight = _make_indexer(cluster.remote_index, fast=False)
        try:
            db = single.token_processor
            pods = [
                PodEntry("pod-a", "hbm"),
                PodEntry("pod-b", "host"),
                PodEntry("pod-c", "shared_storage"),
            ]
            prompts = []
            for i in range(30):
                tokens = [
                    rng.randrange(1, 500)
                    for _ in range(rng.randrange(4, 40))
                ]
                prompts.append(tokens)
                # Random pods claim random prefixes of the chain.
                keys = db.tokens_to_kv_block_keys(
                    EMPTY_BLOCK_HASH, tokens, MODEL
                )
                if not keys:
                    continue
                for pod in rng.sample(pods, rng.randrange(0, 4)):
                    prefix = keys[: rng.randrange(1, len(keys) + 1)]
                    single.kv_block_index.add(
                        prefix, prefix, [pod]
                    )
                    cluster.remote_index.add(prefix, prefix, [pod])
            for tokens in prompts:
                prompt = words(tokens)
                want = single.get_pod_scores(prompt, MODEL)
                assert clustered.get_pod_scores(prompt, MODEL) == want
                assert straight.get_pod_scores(prompt, MODEL) == want
                # Pod-filtered scoring stays aligned too.
                subset = ["pod-a", "pod-c"]
                assert clustered.get_pod_scores(
                    prompt, MODEL, subset
                ) == single.get_pod_scores(prompt, MODEL, subset)
        finally:
            single.shutdown()
            clustered.shutdown()
            straight.shutdown()
            cluster.close()

    def test_scores_survive_failover(self, tmp_path):
        """Scores for a killed replica's slice keep flowing (served by
        the warm runner-up) — the cluster-smoke assertion in test
        form."""
        cluster = LocalCluster(journal_root=str(tmp_path))
        clustered = _make_indexer(cluster.remote_index)
        try:
            db = clustered.token_processor
            tokens = list(range(1, 41))
            keys = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, MODEL
            )
            cluster.remote_index.add(keys, keys, [POD_A])
            cluster.sync_followers()
            prompt = words(tokens)
            before = clustered.get_pod_scores(prompt, MODEL)
            assert before  # pod-a scored
            victim = cluster.membership.ring().owner(keys[0])
            cluster.kill(victim)
            assert clustered.get_pod_scores(prompt, MODEL) == before
        finally:
            clustered.shutdown()
            cluster.close()


# ------------------------------------------------- wire + http


class TestWireAndHttp:
    def test_unknown_method_is_application_error(self):
        replica = ClusterReplica("r0")
        transport = LocalReplicaTransport(replica, strict_wire=True)
        with pytest.raises(ReplicaError):
            transport.call("no_such_method", [])

    def test_http_replica_endpoint_with_token_gate(self):
        from llm_d_kv_cache_manager_tpu.api.http_service import serve

        indexer = Indexer(
            IndexerConfig(cache_stats=False), tokenizer=WordTokenizer()
        )
        replica = ClusterReplica("r0", index=indexer.kv_block_index)
        server = serve(
            indexer,
            host="127.0.0.1",
            port=0,
            admin_token="secret",
            replica=replica,
            cluster_status=lambda: {"role": "replica", "replica": "r0"},
        )
        port = server.server_address[1]
        try:
            good = HttpReplicaTransport(
                f"http://127.0.0.1:{port}", token="secret"
            )
            assert good.call("ping", []) == "r0"
            good.call("add", [[1], [11], [["pod-a", "hbm"]]])
            assert good.call("get_request_key", [1]) == [1, 11]

            bad = HttpReplicaTransport(f"http://127.0.0.1:{port}")
            with pytest.raises(ReplicaUnavailable):
                bad.call("ping", [])  # 403 without the token

            import json
            import urllib.request

            payload = json.load(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/cluster"
                )
            )
            assert payload["role"] == "replica"
        finally:
            server.shutdown()
            indexer.shutdown()

    def test_remote_index_over_http_transport(self):
        from llm_d_kv_cache_manager_tpu.api.http_service import serve

        indexer = Indexer(
            IndexerConfig(cache_stats=False), tokenizer=WordTokenizer()
        )
        replica = ClusterReplica("r0", index=indexer.kv_block_index)
        server = serve(
            indexer, host="127.0.0.1", port=0, replica=replica
        )
        port = server.server_address[1]
        try:
            membership = ClusterMembership(
                {
                    "r0": HttpReplicaTransport(
                        f"http://127.0.0.1:{port}"
                    )
                }
            )
            remote = RemoteIndex(membership)
            remote.add([1, 2], [11, 12], [POD_A])
            assert len(remote.lookup_chain([11, 12])) == 2
            remote.evict(1, [POD_A])
            assert remote.lookup([11, 12]).get(11) is None
        finally:
            server.shutdown()
            indexer.shutdown()


class TestDebugClusterRouterPayload:
    def test_local_cluster_status_shape(self, tmp_path):
        cluster = LocalCluster(journal_root=str(tmp_path))
        try:
            cluster.remote_index.add(KEYS[:10], KEYS[:10], [POD_A])
            cluster.sync_followers()
            status = cluster.status()
            assert status["membership"]["ring_version"] == 0
            assert len(status["membership"]["alive"]) == 3
            assert len(status["replication"]) == 6  # 3 replicas x 2 peers
            cluster.kill("replica-0")
            status = cluster.status()
            assert status["membership"]["failovers"] == 1
            assert "replica-0" not in status["membership"]["alive"]
            # Fan-out attribution panel (docs/observability.md): the
            # add above produced per-replica tallies, and the kill's
            # reason is retrievable as last-error context.
            assert status["rpc"]["replicas"], status["rpc"]
            for view in status["rpc"]["replicas"].values():
                assert view["calls"] >= 1
                assert "avg_ms" in view and "methods" in view
            assert "critical_path" in status["rpc"]
            last = status["membership"]["last_errors"]["replica-0"]
            assert last["reason"] == "killed"
        finally:
            cluster.close()
