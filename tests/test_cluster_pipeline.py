"""Read-path fan-out pipelining: parity oracles, memo, arming (ISSUE 19).

The acceptance pins (docs/replication.md "Pipelined read path"):

* the overlapped + pipelined drive (fan-out executor armed, chunk
  pipelining + chain speculation on) returns BIT-IDENTICAL scores to
  the sequential parity oracle (``CLUSTER_FANOUT_WORKERS=0`` +
  ``CLUSTER_PIPELINE_DEPTH=0``) and to a single-process
  ``InMemoryIndex``, on randomized workloads, filtered and unfiltered,
  over the strict canonical wire;
* a replica killed MID-WALK (between a pipelined request's RPC
  rounds) re-routes the failed subset and still lands on the oracle's
  scores when the failover target is journal-warm;
* the cluster score memo (version-vector validated) serves repeat
  prompts with ZERO further lookup RPC rounds, and a memo hit always
  equals a fresh recompute — including across router-driven add /
  evict / purge mutations;
* ``kvtpu_score_memo_disabled`` does NOT latch when the memo runs
  against a ``LocalCluster`` (the RemoteIndex exposes the
  version_vector/touch_chain surface);
* adaptive arming: against the free in-process transport the drive
  stays sequential (EWMA below ``CLUSTER_OVERLAP_MIN_RPC_S``); a zero
  threshold forces the overlapped paths on (what every test here
  uses to actually exercise them).
"""

import random

import pytest

from llm_d_kv_cache_manager_tpu.cluster import LocalCluster
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    InMemoryIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    METRICS,
    gauge_value,
)
from tests.test_read_path_fastlane import WordTokenizer, words

MODEL = "m"
PODS = [
    PodEntry("pod-a", "hbm"),
    PodEntry("pod-b", "host"),
    PodEntry("pod-c", "shared_storage"),
]


def _make_indexer(index, pipeline_depth=None, score_memo=0):
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
            kvblock_index_config=IndexConfig(
                in_memory_config=InMemoryIndexConfig(size=200_000)
            ),
            read_path_fast_lane=True,
            lookup_chunk_size=8,
            score_memo_size=score_memo,
            cache_stats=False,
            pipeline_depth=pipeline_depth,
        ),
        tokenizer=WordTokenizer(),
        kv_block_index=index,
    )
    indexer.run()
    return indexer


def _seed_random_prefixes(rng, db, indexes, n_prompts=30):
    """Random pods claim random prefixes of random chains in every
    index; returns the prompt token lists."""
    prompts = []
    for _ in range(n_prompts):
        tokens = [
            rng.randrange(1, 500)
            for _ in range(rng.randrange(4, 240))
        ]
        prompts.append(tokens)
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        if not keys:
            continue
        for pod in rng.sample(PODS, rng.randrange(0, 4)):
            prefix = keys[: rng.randrange(1, len(keys) + 1)]
            for index in indexes:
                index.add(prefix, prefix, [pod])
    return prompts


class TestPipelinedParityOracle:
    @pytest.mark.parametrize("seed", [3, 17])
    def test_pipelined_matches_sequential_and_single(self, seed):
        """The tentpole oracle: overlapped fan-out + chunk pipelining
        + speculation must be BIT-IDENTICAL to the sequential drive
        and the single-process backend — unfiltered and pod-filtered,
        strict canonical wire."""
        rng = random.Random(seed)
        cluster = LocalCluster(strict_wire=True, overlap_min_rpc_s=0)
        single_index = InMemoryIndex()
        sequential = _make_indexer(
            cluster.remote_index, pipeline_depth=0
        )
        pipelined = _make_indexer(cluster.remote_index)
        single = _make_indexer(single_index)
        try:
            prompts = _seed_random_prefixes(
                rng,
                single.token_processor,
                [cluster.remote_index, single_index],
            )
            for tokens in prompts:
                prompt = words(tokens)
                for pod_filter in (None, ["pod-a", "pod-c"]):
                    want = single.get_pod_scores(
                        prompt, MODEL, pod_filter
                    )
                    assert (
                        sequential.get_pod_scores(
                            prompt, MODEL, pod_filter
                        )
                        == want
                    )
                    assert (
                        pipelined.get_pod_scores(
                            prompt, MODEL, pod_filter
                        )
                        == want
                    )
            # The pipelined lane really speculated (the oracle above
            # would pass vacuously if the async drive never engaged).
            stats = cluster.remote_index.rpc_stats()["critical_path"]
            assert stats["speculative_rpcs"] > 0
        finally:
            sequential.shutdown()
            pipelined.shutdown()
            single.shutdown()
            cluster.close()

    @pytest.mark.parametrize("kill_at_call", [2, 6, 11])
    def test_mid_walk_kill_reroutes_to_oracle_scores(
        self, tmp_path, kill_at_call
    ):
        """A replica dying BETWEEN a pipelined request's RPC rounds
        (transport counter trips the kill mid-walk) re-routes the
        failed subset to the journal-warm follower and the final
        scores still equal the pre-kill oracle."""
        state = {"calls": 0, "armed": False, "killed": None}

        class TripwireTransport:
            def __init__(self, replica_id, inner):
                self._replica_id = replica_id
                self._inner = inner
                self.supports_deadline = getattr(
                    inner, "supports_deadline", False
                )

            def _maybe_trip(self):
                if not state["armed"] or state["killed"] is not None:
                    return
                state["calls"] += 1
                if state["calls"] >= kill_at_call:
                    state["killed"] = victim
                    cluster.kill(victim, notice=False)

            def call(self, method, args):
                self._maybe_trip()
                return self._inner.call(method, args)

            def call_ex(self, method, args, traceparent=None):
                self._maybe_trip()
                return self._inner.call_ex(
                    method, args, traceparent=traceparent
                )

            def call_vv(
                self, method, args, traceparent=None, timeout=None
            ):
                self._maybe_trip()
                return self._inner.call_vv(
                    method, args, traceparent=traceparent, timeout=timeout
                )

        cluster = LocalCluster(
            journal_root=str(tmp_path),
            overlap_min_rpc_s=0,
            transport_wrap=TripwireTransport,
        )
        pipelined = _make_indexer(cluster.remote_index)
        try:
            db = pipelined.token_processor
            tokens = list(range(1, 161))  # 40 blocks -> several chunks
            keys = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, MODEL
            )
            cluster.remote_index.add(keys, keys, [PODS[0]])
            while cluster.sync_followers():
                pass  # followers warm before anything can die
            prompt = words(tokens)
            oracle = pipelined.get_pod_scores(prompt, MODEL)
            assert oracle == {"pod-a": float(len(keys))}
            victim = cluster.membership.ring().owner(keys[0])
            state["armed"] = True
            # Several walks: one of them loses `victim` mid-flight.
            for _ in range(4):
                assert (
                    pipelined.get_pod_scores(prompt, MODEL) == oracle
                )
            assert state["killed"] == victim
            assert cluster.membership.failover_count() >= 1
        finally:
            pipelined.shutdown()
            cluster.close()


class TestClusterScoreMemo:
    def test_memo_enables_and_hits_without_rpc_rounds(self):
        """The memo arms against the RemoteIndex (it exposes
        version_vector/touch_chain), converges after the piggybacked
        vectors arrive, and then serves repeats with ZERO further
        lookup RPC rounds — the ``kvtpu_score_memo_disabled`` gauge
        never latches for a cluster backend."""
        gauge_before = gauge_value(METRICS.score_memo_disabled)
        cluster = LocalCluster(strict_wire=True, overlap_min_rpc_s=0)
        memoized = _make_indexer(cluster.remote_index, score_memo=64)
        try:
            assert memoized._score_memo is not None
            assert (
                gauge_value(METRICS.score_memo_disabled)
                == gauge_before
            )
            db = memoized.token_processor
            tokens = list(range(1, 101))
            keys = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, MODEL
            )
            cluster.remote_index.add(keys, keys, [PODS[0], PODS[1]])
            prompt = words(tokens)
            # Request 1 stores a sentinel-validated entry (no vectors
            # cached yet); request 2 recomputes against the now-real
            # composed vector; request 3+ must hit.
            want = memoized.get_pod_scores(prompt, MODEL)
            memoized.get_pod_scores(prompt, MODEL)
            rounds = lambda: cluster.remote_index.rpc_stats()[  # noqa: E731
                "critical_path"
            ]["lookup_calls"]
            before = rounds()
            for _ in range(5):
                assert memoized.get_pod_scores(prompt, MODEL) == want
            assert rounds() == before  # pure memo hits
        finally:
            memoized.shutdown()
            cluster.close()

    def test_memo_hits_equal_recompute_across_mutations(self):
        """Memo-hit ≡ recompute under router-driven cluster mutations:
        after every add / evict / purge_pod the memoized indexer must
        agree with a memo-free indexer walking the same cluster."""
        rng = random.Random(23)
        cluster = LocalCluster(strict_wire=True, overlap_min_rpc_s=0)
        memoized = _make_indexer(cluster.remote_index, score_memo=64)
        recompute = _make_indexer(cluster.remote_index)
        try:
            db = memoized.token_processor
            tokens = [rng.randrange(1, 500) for _ in range(120)]
            keys = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, MODEL
            )
            prompt = words(tokens)

            def check():
                # Call both indexers in lockstep: the tokenizer prefix
                # store serves repeats a (possibly shorter) cached
                # stream, so scores are only comparable at the SAME
                # request ordinal — the second memoized call is the
                # memo hit and must equal the recompute twin's fresh
                # walk at that ordinal.
                want1 = recompute.get_pod_scores(prompt, MODEL)
                got1 = memoized.get_pod_scores(prompt, MODEL)
                want2 = recompute.get_pod_scores(prompt, MODEL)
                got2 = memoized.get_pod_scores(prompt, MODEL)
                assert (got1, got2) == (want1, want2)

            cluster.remote_index.add(keys, keys, [PODS[0]])
            check()
            # Deepen one pod's claim: the memo entry for the old state
            # must invalidate (owner vector advanced on the add reply).
            cluster.remote_index.add(keys, keys, [PODS[1]])
            check()
            # Shrink it again via evict at the chain head.
            cluster.remote_index.evict(keys[0], [PODS[1]])
            check()
            # Wipe a pod fleet-wide.
            cluster.remote_index.purge_pod("pod-a")
            check()
        finally:
            memoized.shutdown()
            recompute.shutdown()
            cluster.close()


class TestAdaptiveArming:
    def test_local_transport_stays_sequential(self):
        """Against the free in-process transport the per-RPC EWMA
        stays below the default CLUSTER_OVERLAP_MIN_RPC_S, so neither
        the fan-out pool nor the pipe pool arms — results unchanged,
        no pool handoff tax."""
        cluster = LocalCluster(strict_wire=True)
        try:
            remote = cluster.remote_index
            assert remote.overlap_min_rpc_s > 0
            remote.add([1, 2, 3], [1, 2, 3], [PODS[0]])
            fanout = remote.rpc_stats()["fanout"]
            assert fanout["rpc_ewma_us"] > 0
            assert fanout["armed"] is False
            # The async surface degenerates to the inline handle.
            handle = remote.lookup_chain_async([1, 2, 3])
            assert type(handle).__name__ == "_CompletedLookup"
            assert len(handle.result()) == 3
        finally:
            cluster.close()

    def test_zero_threshold_forces_overlap(self):
        """overlap_min_rpc_s=0 (CLUSTER_OVERLAP_MIN_RPC_S=0) arms the
        overlapped paths unconditionally — the deployment posture for
        real network transports and what the parity tests pin."""
        cluster = LocalCluster(strict_wire=True, overlap_min_rpc_s=0)
        try:
            remote = cluster.remote_index
            remote.add([1, 2, 3], [1, 2, 3], [PODS[0]])
            assert remote.rpc_stats()["fanout"]["armed"] is True
            handle = remote.lookup_chain_async([1, 2, 3])
            assert type(handle).__name__ != "_CompletedLookup"
            assert len(handle.result()) == 3
        finally:
            cluster.close()

    def test_close_degrades_async_surface_inline(self):
        """After close() the pools are gone: lookup_chain_async still
        answers (inline) so a racing scorer completes instead of
        crashing."""
        cluster = LocalCluster(strict_wire=True, overlap_min_rpc_s=0)
        remote = cluster.remote_index
        remote.add([7, 8], [7, 8], [PODS[0]])
        with remote._exec_lock:
            pass  # lock healthy before close
        remote.close()
        handle = remote.lookup_chain_async([7, 8])
        assert type(handle).__name__ == "_CompletedLookup"
        assert len(handle.result()) == 2
        cluster.close()
