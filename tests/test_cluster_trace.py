"""Cross-replica trace stitching (docs/observability.md "Fleet
tracing"): traceparent round-trips over the strict-wire codec and the
real HTTP transport, killed-replica retries visible as re-routed
spans, error-kind classification, and the piggyback knob."""

import threading

import pytest

from llm_d_kv_cache_manager_tpu.cluster import LocalCluster
from llm_d_kv_cache_manager_tpu.cluster.membership import (
    ClusterMembership,
)
from llm_d_kv_cache_manager_tpu.cluster.remote_index import RemoteIndex
from llm_d_kv_cache_manager_tpu.cluster.replica import (
    ClusterReplica,
    HttpReplicaTransport,
    LocalReplicaTransport,
    ReplicaUnavailable,
    decode_request,
    decode_response_ex,
    encode_request,
    encode_response,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER, use_trace
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding

POD_A = PodEntry("pod-a", "hbm")


class WordTokenizer:
    def type(self):
        return "test-word"

    def encode(self, prompt, model_name, add_special_tokens):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]) if word.startswith("t") else 0)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def traced(fn):
    """Run ``fn`` under a forced trace; returns the finished trace."""
    trace = TRACER.start_trace("test.cluster", force=True)
    assert trace is not None
    with use_trace(trace):
        fn()
    trace.finish()
    return trace


def spans_named(trace, name):
    return [
        s for s in trace.to_dict()["spans"] if s["name"] == name
    ]


class TestWireCodec:
    def test_request_round_trip_with_traceparent(self):
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        data = encode_request("lookup", [[1], None], tp)
        assert decode_request(data) == ("lookup", [[1], None], tp)

    def test_two_element_request_still_decodes(self):
        data = encode_request("ping", [])
        assert decode_request(data) == ("ping", [], None)

    def test_response_round_trip_with_spans(self):
        spans = [["replica.lookup", "cluster.rpc", 5, 10, "ok",
                  [["replica", "r0"]]]]
        payload, got = decode_response_ex(
            encode_response(0, [1, 2], spans)
        )
        assert payload == [1, 2]
        assert got == spans

    def test_two_element_response_still_decodes(self):
        payload, spans = decode_response_ex(encode_response(0, "x"))
        assert payload == "x"
        assert spans is None


class TestLocalStrictWireStitching:
    def test_lookup_stitches_per_owner_rpc_and_server_spans(self):
        cluster = LocalCluster(strict_wire=True)
        try:
            keys = list(range(1, 65))
            cluster.remote_index.add(keys, keys, [POD_A])
            trace = traced(lambda: cluster.remote_index.lookup(keys))
            rpcs = spans_named(trace, "cluster.rpc")
            lookups = [
                s for s in rpcs if s["attributes"]["method"] == "lookup"
            ]
            # 64 keys over 3 replicas: every owner answered one RPC.
            owners = {s["attributes"]["replica"] for s in lookups}
            assert owners == set(cluster.replicas)
            # Server-side spans rode the reply and nest under the RPC.
            server = spans_named(trace, "replica.lookup")
            assert {s["attributes"]["replica"] for s in server} == owners
            assert all(s["parent"] == "cluster.rpc" for s in server)
            decode = spans_named(trace, "replica.decode")
            assert {s["attributes"]["replica"] for s in decode} == owners
            # Stitched spans sit inside their RPC window (re-anchored
            # to the router's clock).
            for rpc in lookups:
                children = [
                    s
                    for s in server
                    if s["attributes"]["replica"]
                    == rpc["attributes"]["replica"]
                ]
                for child in children:
                    assert child["start_ms"] >= rpc["start_ms"] - 0.5
                    assert (
                        child["start_ms"] + child["duration_ms"]
                        <= rpc["start_ms"] + rpc["duration_ms"] + 0.5
                    )
        finally:
            cluster.close()

    def test_nonstrict_local_transport_records_server_spans_directly(self):
        cluster = LocalCluster()  # same-thread dispatch, no codec
        try:
            cluster.remote_index.add([1], [11], [POD_A])
            trace = traced(lambda: cluster.remote_index.lookup([11]))
            assert spans_named(trace, "cluster.rpc")
            assert spans_named(trace, "replica.lookup")
        finally:
            cluster.close()

    def test_untraced_calls_send_two_element_frames(self):
        """The untraced path pays zero extra wire bytes: no
        traceparent element, no span piggyback."""
        replica = ClusterReplica("r0")
        seen = []
        original = replica.handle_wire

        def spy(data):
            seen.append(decode_request(data))
            return original(data)

        replica.handle_wire = spy
        transport = LocalReplicaTransport(replica, strict_wire=True)
        membership = ClusterMembership({"r0": transport})
        remote = RemoteIndex(membership)
        remote.add([1], [11], [POD_A])
        assert seen and all(tp is None for _, _, tp in seen)

    def test_killed_replica_retry_appears_as_rerouted_span(self):
        cluster = LocalCluster()
        try:
            keys = list(range(1, 33))
            cluster.remote_index.add(keys, keys, [POD_A])
            ring = cluster.membership.ring()
            victim = ring.owner(keys[0])
            # Transport down, membership not yet told: the traced
            # lookup itself discovers the death and re-routes.
            cluster.kill(victim, notice=False)
            trace = traced(lambda: cluster.remote_index.lookup(keys))
            rpcs = spans_named(trace, "cluster.rpc")
            failed = [s for s in rpcs if s["status"] == "error"]
            assert failed, "the dead owner's RPC must record an error"
            assert any(
                s["attributes"]["replica"] == victim for s in failed
            )
            retried = [
                s
                for s in rpcs
                if s["status"] == "ok"
                and s["attributes"]["method"] == "lookup"
                and s["attributes"]["replica"] != victim
            ]
            assert retried, "the re-route must appear as its own span"
            stats = cluster.remote_index.rpc_stats()
            assert stats["reroutes"] >= 1
            last = stats["replicas"][victim]["last_error"]
            assert last["kind"] == "killed"
        finally:
            cluster.close()

    def test_piggyback_disabled_on_replica_returns_no_spans(self):
        replica = ClusterReplica("r0", trace_piggyback=False)
        transport = LocalReplicaTransport(replica, strict_wire=True)
        membership = ClusterMembership({"r0": transport})
        remote = RemoteIndex(membership)
        remote.add([1], [11], [POD_A])
        trace = traced(lambda: remote.lookup([11]))
        assert spans_named(trace, "cluster.rpc")  # router side intact
        assert not spans_named(trace, "replica.lookup")

    def test_trace_rpcs_disabled_on_router_records_nothing(self):
        cluster = LocalCluster(strict_wire=True)
        try:
            cluster.remote_index.trace_rpcs = False
            cluster.remote_index.add([1], [11], [POD_A])
            trace = traced(lambda: cluster.remote_index.lookup([11]))
            assert not spans_named(trace, "cluster.rpc")
            assert not spans_named(trace, "replica.lookup")
        finally:
            cluster.close()

    def test_trace_rpcs_disabled_nonstrict_leaks_no_orphan_spans(self):
        """The non-strict local transport dispatches on the caller's
        thread; with the router plane off the replica's direct
        context-var record must be shielded, or orphan replica.* spans
        dangle under a cluster.rpc parent that was never opened."""
        cluster = LocalCluster()  # non-strict: same-thread dispatch
        try:
            cluster.remote_index.trace_rpcs = False
            cluster.remote_index.add([1], [11], [POD_A])
            trace = traced(lambda: cluster.remote_index.lookup([11]))
            span_names = {s["name"] for s in trace.to_dict()["spans"]}
            assert not {
                n for n in span_names if n.startswith(("cluster.", "replica."))
            }, span_names
        finally:
            cluster.close()

    def test_replica_piggyback_off_nonstrict_records_no_server_spans(self):
        """trace_piggyback=False means the same thing over both
        transports: no server-side spans, even via the in-process
        direct record."""
        replica = ClusterReplica("r0", trace_piggyback=False)
        transport = LocalReplicaTransport(replica)  # non-strict
        membership = ClusterMembership({"r0": transport})
        remote = RemoteIndex(membership)
        remote.add([1], [11], [POD_A])
        trace = traced(lambda: remote.lookup([11]))
        assert spans_named(trace, "cluster.rpc")  # router side intact
        assert not [
            s
            for s in trace.to_dict()["spans"]
            if s["name"].startswith("replica.")
        ]

    def test_garbled_piggyback_never_fails_the_call(self):
        replica = ClusterReplica("r0")
        transport = LocalReplicaTransport(replica, strict_wire=True)

        original = transport.call_ex

        def garbled(method, args, traceparent=None):
            payload, _ = original(method, args, traceparent)
            return payload, [["bad-record"]]  # wrong arity

        transport.call_ex = garbled
        membership = ClusterMembership({"r0": transport})
        remote = RemoteIndex(membership)
        remote.add([1], [11], [POD_A])
        trace = traced(lambda: remote.lookup([11]))
        assert spans_named(trace, "cluster.rpc")


class TestScoreParityUnderTracing:
    def test_traced_and_untraced_scores_identical(self):
        cluster = LocalCluster(strict_wire=True)
        indexer = Indexer(
            IndexerConfig(cache_stats=False),
            tokenizer=WordTokenizer(),
            kv_block_index=cluster.remote_index,
        )
        try:
            tokens = list(range(1, 65))
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                0, tokens, "m"
            )
            cluster.remote_index.add(keys, keys, [POD_A])
            prompt = " ".join(f"t{t}" for t in tokens)
            plain = indexer.get_pod_scores(prompt, "m")

            box = {}

            def run():
                box["scores"] = indexer.get_pod_scores(prompt, "m")

            traced(run)
            assert box["scores"] == plain
        finally:
            indexer.shutdown()
            cluster.close()


class TestHttpTransportStitching:
    def _serve_replica(self, replica_id="r0"):
        from llm_d_kv_cache_manager_tpu.api.http_service import serve

        indexer = Indexer(
            IndexerConfig(cache_stats=False), tokenizer=WordTokenizer()
        )
        replica = ClusterReplica(
            replica_id, index=indexer.kv_block_index
        )
        server = serve(
            indexer, host="127.0.0.1", port=0, replica=replica
        )
        return indexer, server

    def test_traceparent_round_trip_over_real_http(self):
        indexer, server = self._serve_replica()
        port = server.server_address[1]
        try:
            membership = ClusterMembership(
                {"r0": HttpReplicaTransport(f"http://127.0.0.1:{port}")}
            )
            remote = RemoteIndex(membership)
            remote.add([1, 2], [11, 12], [POD_A])
            trace = traced(lambda: remote.lookup([11, 12]))
            rpcs = spans_named(trace, "cluster.rpc")
            assert any(
                s["attributes"]["method"] == "lookup" for s in rpcs
            )
            server_side = spans_named(trace, "replica.lookup")
            assert server_side
            assert all(
                s["attributes"]["replica"] == "r0" for s in server_side
            )
        finally:
            server.shutdown()
            indexer.shutdown()

    def test_http_error_kinds_refused_and_killed(self):
        refused = HttpReplicaTransport("http://127.0.0.1:9")  # closed
        with pytest.raises(ReplicaUnavailable) as info:
            refused.call("ping", [])
        assert info.value.kind in ("refused", "io", "timeout")

        replica = ClusterReplica("r0")
        transport = LocalReplicaTransport(replica)
        transport.kill()
        with pytest.raises(ReplicaUnavailable) as info:
            transport.call("ping", [])
        assert info.value.kind == "killed"

    def test_http_failure_lands_in_error_metric_and_debug(self):
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
        from llm_d_kv_cache_manager_tpu.obs.slo import (
            counter_label_total,
        )

        alive = ClusterReplica("alive")
        membership = ClusterMembership(
            {
                "alive": LocalReplicaTransport(alive),
                "dead": HttpReplicaTransport("http://127.0.0.1:9"),
            }
        )
        remote = RemoteIndex(membership)
        before = counter_label_total(
            METRICS.cluster_rpc_errors, replica="dead"
        )
        # Drive keys until one routes to the dead replica and fails
        # over; the tally and metric must attribute the transport kind.
        for key in range(1, 50):
            remote.add([key], [key + 1000], [POD_A])
            if not membership.is_alive("dead"):
                break
        assert not membership.is_alive("dead")
        after = counter_label_total(
            METRICS.cluster_rpc_errors, replica="dead"
        )
        assert after > before
        stats = remote.rpc_stats()
        assert stats["replicas"]["dead"]["errors"] >= 1
        assert stats["replicas"]["dead"]["last_error"]["kind"] in (
            "refused", "io", "timeout",
        )
        status = membership.status()
        assert "dead" in status["last_errors"]


class TestEventPlaneTraceCrossesReplicaBoundary:
    def test_kvevents_message_trace_carries_cluster_rpc_spans(self):
        """The ingest path (subscriber/ingestor -> pool -> RemoteIndex)
        rides the same wire propagation: a sampled event message's
        trace shows the per-owner apply RPCs."""
        from llm_d_kv_cache_manager_tpu.kvevents.events import (
            BlockStored,
            EventBatch,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.pool import (
            Message,
            Pool,
            PoolConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E501 - test-local import
            ChunkedTokenDatabase,
            TokenProcessorConfig,
        )

        cluster = LocalCluster(strict_wire=True)
        processor = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=4)
        )
        pool = Pool(
            cluster.remote_index, processor, PoolConfig(concurrency=1)
        )
        TRACER.configure(sample_rate=1.0)
        try:
            pool.start()
            batch = EventBatch(
                ts=1.0,
                events=[
                    BlockStored(
                        block_hashes=[1, 2, 3, 4],
                        parent_block_hash=None,
                        token_ids=list(range(16)),
                        block_size=4,
                        medium="hbm",
                    )
                ],
            )
            pool.add_task(
                Message(
                    topic="kv@pod-1@m",
                    payload=batch.encode(),
                    pod_identifier="pod-1",
                    model_name="m",
                    seq=1,
                )
            )
            pool.drain()
            recorded = [
                t
                for t in TRACER.recorder.recent(50)
                if t.name == "kvevents.message"
            ]
            assert recorded, "the event message must have been traced"
            spans = recorded[0].to_dict()["spans"]
            rpcs = [s for s in spans if s["name"] == "cluster.rpc"]
            assert rpcs, [s["name"] for s in spans]
            assert all(
                s["parent"] == "kvevents.apply" for s in rpcs
            )
            assert [
                s for s in spans if s["name"] == "replica.apply"
            ], "server-side apply spans must ride the reply"
        finally:
            TRACER.configure(sample_rate=0.0)
            TRACER.reset()
            pool.shutdown()
            cluster.close()


class TestConcurrentTracedFanout:
    def test_parallel_traced_lookups_do_not_cross_traces(self):
        cluster = LocalCluster(strict_wire=True)
        try:
            keys = list(range(1, 129))
            cluster.remote_index.add(keys, keys, [POD_A])
            traces = [None] * 8
            errors = []

            def work(i):
                try:
                    traces[i] = traced(
                        lambda: cluster.remote_index.lookup(keys)
                    )
                except Exception as exc:  # noqa: BLE001 - reraised below
                    errors.append(exc)

            threads = [
                threading.Thread(target=work, args=(i,))
                for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            ids = {t.trace_id for t in traces}
            assert len(ids) == 8
            for trace in traces:
                rpcs = spans_named(trace, "cluster.rpc")
                lookups = [
                    s
                    for s in rpcs
                    if s["attributes"]["method"] == "lookup"
                ]
                # Exactly one RPC per owner per trace: no span leaked
                # into a sibling trace.
                assert len(lookups) == len(cluster.replicas)
        finally:
            cluster.close()
