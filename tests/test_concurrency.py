"""Concurrency stress: the race-detection coverage the reference lacks.

The reference relies on by-design safety (mutex-guarded pod caches,
double-checked inserts, documented benign races — SURVEY §5) but wires
no race detector into CI.  These tests hammer the shared structures
from many threads and assert the invariants that matter: no lost
updates, no exceptions, ordered per-pod event processing, and a
consistent index after concurrent add/evict/lookup storms.
"""

import random
import threading
import time

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    CostAwareIndexConfig,
    InMemoryIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.ttl_cache import TTLCache

THREADS = 8
OPS = 300


@pytest.fixture(autouse=True)
def lock_order_watchdog():
    """Arm the runtime lock-order watchdog for every storm in this
    module: the structures under test are constructed inside the tests
    (after enable), so their locks become order-asserting TrackedLocks
    and any acquisition against the declared KV006 order raises
    LockOrderViolation instead of deadlocking flakily.  `make
    lockorder-smoke` runs this same suite with KVTPU_LOCK_ORDER_DEBUG=1
    so even import-time-constructed locks are covered."""
    previous = lockorder.enable(True)
    try:
        yield
    finally:
        lockorder.enable(previous)


class TestIndexUnderContention:
    def test_concurrent_add_lookup_evict(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=50_000))
        errors = []
        barrier = threading.Barrier(THREADS)

        def worker(worker_id: int):
            rng = random.Random(worker_id)
            pod = PodEntry(f"pod-{worker_id}", "hbm")
            try:
                barrier.wait()
                for i in range(OPS):
                    key = rng.randrange(1000)
                    index.add([key], [key], [pod])
                    index.lookup([key], None)
                    if i % 7 == 0:
                        index.evict(key, [pod])
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors

    def test_no_lost_adds_across_threads(self):
        """Every pod's final add for a key must be visible: N threads
        add disjoint pods to the same keys; all must survive."""
        index = InMemoryIndex(
            InMemoryIndexConfig(size=10_000, pod_cache_size=THREADS + 1)
        )
        keys = list(range(64))
        barrier = threading.Barrier(THREADS)

        def worker(worker_id: int):
            pod = PodEntry(f"pod-{worker_id}", "hbm")
            barrier.wait()
            for key in keys:
                index.add([key], [key], [pod])

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

        hits = index.lookup(keys, None)
        for key in keys:
            assert len(hits[key]) == THREADS, (
                f"key {key} lost adds: {hits[key]}"
            )


def _make_backend(name):
    if name == "in_memory":
        return InMemoryIndex(
            InMemoryIndexConfig(size=10_000, pod_cache_size=THREADS + 2)
        )
    return CostAwareMemoryIndex(
        CostAwareIndexConfig(pod_cache_size=THREADS + 2)
    )


class TestBackendStorm:
    """The runtime counterpart of kvlint's KV001 lock rule: hammer each
    index backend with a mixed add/evict/lookup/dump_entries storm and
    assert the guarded invariants actually hold under contention."""

    @pytest.mark.parametrize("backend", ["in_memory", "cost_aware"])
    def test_mixed_storm_no_lost_updates(self, backend):
        index = _make_backend(backend)
        keys = list(range(96))
        errors = []
        barrier = threading.Barrier(THREADS)

        def worker(worker_id: int):
            rng = random.Random(worker_id)
            pod = PodEntry(f"pod-{worker_id}", "hbm")
            try:
                barrier.wait()
                for i in range(OPS):
                    key = rng.choice(keys)
                    # Engine key is per-pod so one thread's evict can
                    # only target its own entries.
                    engine_key = key * 1000 + worker_id
                    index.add([engine_key], [key], [pod])
                    roll = i % 10
                    if roll < 5:
                        index.lookup([key], None)
                    elif roll < 7:
                        index.evict(engine_key, [pod])
                    elif roll == 7:
                        block_entries, engine_map = index.dump_entries()
                        # A dump taken mid-storm must be structurally
                        # sound even while writers churn under it.
                        for _, pods in block_entries:
                            assert isinstance(pods, list)
                        assert isinstance(engine_map, list)
                # Final pass: every key ends with this pod present.
                for key in keys:
                    index.add([key * 1000 + worker_id], [key], [pod])
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors

        # No lost updates: after the storm every thread's final add for
        # every key must be visible (disjoint pods, ample capacity).
        hits = index.lookup(keys, None)
        for key in keys:
            pods = {entry.pod_identifier for entry in hits.get(key, [])}
            missing = {
                f"pod-{worker_id}" for worker_id in range(THREADS)
            } - pods
            assert not missing, f"key {key} lost adds from {missing}"

        # And the post-storm dump agrees with lookup (same snapshot
        # machinery persistence relies on).
        block_entries, engine_map = index.dump_entries()
        dumped = {request_key for request_key, _ in block_entries}
        assert set(keys) <= dumped
        assert len(engine_map) >= THREADS * len(keys)


class TestShardedIndexStorm:
    """Reader/writer storms across the lock-striped shards: scoring
    readers (lookup + lookup_chain), kvevents-style writers (add /
    batched add / evict), and admin sweeps (dump, purge) all at once.
    The per-shard locks must never lose an update, deadlock, or hand a
    reader a torn snapshot."""

    @pytest.mark.parametrize("shards", [1, 4, 16])
    def test_reader_writer_storm_across_shards(self, shards):
        index = InMemoryIndex(
            InMemoryIndexConfig(
                size=50_000, pod_cache_size=THREADS + 2, shards=shards
            )
        )
        keys = list(range(256))
        errors = []
        barrier = threading.Barrier(THREADS)

        def writer(worker_id: int):
            rng = random.Random(worker_id)
            pod = PodEntry(f"pod-{worker_id}", "hbm")
            try:
                barrier.wait()
                for i in range(OPS):
                    start = rng.randrange(len(keys) - 8)
                    chain = keys[start:start + 8]
                    engine = [k * 1000 + worker_id for k in chain]
                    if i % 3 == 0:
                        # The kvevents batched-apply surface.
                        index.add_mappings(engine, chain)
                        index.add_entries_batch([(chain, [pod])])
                    else:
                        index.add(engine, chain, [pod])
                    if i % 5 == 0:
                        index.evict(engine[0], [pod])
                for key in keys:
                    index.add([key * 1000 + worker_id], [key], [pod])
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        def reader(worker_id: int):
            rng = random.Random(1000 + worker_id)
            try:
                barrier.wait()
                for i in range(OPS):
                    start = rng.randrange(len(keys) - 16)
                    chain = keys[start:start + 16]
                    if i % 2 == 0:
                        for pods in index.lookup_chain(chain):
                            # A torn snapshot would not be a tuple of
                            # PodEntry.
                            assert all(
                                isinstance(p, PodEntry) for p in pods
                            )
                    else:
                        index.lookup(chain, None)
                    if i % 29 == 0:
                        index.dump_entries()
                    if i % 97 == 0:
                        index.purge_pod(f"pod-{rng.randrange(4)}")
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        threads = [
            threading.Thread(target=writer, args=(i,))
            for i in range(THREADS // 2)
        ] + [
            threading.Thread(target=reader, args=(i,))
            for i in range(THREADS - THREADS // 2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors

        # Writers' final adds all visible (readers' purges only target
        # pods 0-3 mid-storm; re-add them to normalize, then assert no
        # lost updates for every writer pod).
        writer_ids = range(THREADS // 2)
        for worker_id in writer_ids:
            pod = PodEntry(f"pod-{worker_id}", "hbm")
            for key in keys:
                index.add([key * 1000 + worker_id], [key], [pod])
        hits = index.lookup(keys, None)
        for key in keys:
            pods = {entry.pod_identifier for entry in hits.get(key, [])}
            missing = {
                f"pod-{worker_id}" for worker_id in writer_ids
            } - pods
            assert not missing, f"key {key} lost adds from {missing}"


class TestScoreMemoStorm:
    """Scoring readers hammering the memoized read path (fills, hits,
    version-invalidated re-walks) while writers mutate the index: no
    exceptions, and at quiesce the memoized fast lane agrees exactly
    with a straight-path walk over the same index."""

    def test_memoized_scoring_vs_concurrent_writers(self):
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
            Indexer,
            IndexerConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            EMPTY_BLOCK_HASH,
            IndexConfig,
        )
        from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
            Encoding,
        )

        class WordTokenizer:
            def type(self):
                return "storm-word"

            def encode(self, prompt, model_name, add_special_tokens):
                tokens, offsets, pos = [], [], 0
                for word in prompt.split(" "):
                    tokens.append(int(word[1:]))
                    offsets.append((pos, pos + len(word)))
                    pos += len(word) + 1
                return Encoding(tokens=tokens, offsets=offsets)

        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
                kvblock_index_config=IndexConfig(
                    in_memory_config=InMemoryIndexConfig(
                        size=50_000, shards=4
                    )
                ),
                read_path_fast_lane=True,
            ),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        index = indexer.kv_block_index
        rng = random.Random(5)
        convos = [
            [rng.randrange(1, 60_000) for _ in range(80)]
            for _ in range(4)
        ]
        prompts = [" ".join(f"t{t}" for t in c) for c in convos]
        chains = [
            indexer.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, c, "m"
            )
            for c in convos
        ]
        errors = []
        barrier = threading.Barrier(THREADS)

        def writer(worker_id):
            w_rng = random.Random(worker_id)
            pod = PodEntry(f"pod-{worker_id}", "hbm")
            try:
                barrier.wait()
                for _ in range(OPS):
                    chain = chains[w_rng.randrange(len(chains))]
                    cut = w_rng.randrange(1, len(chain) + 1)
                    index.add(chain[:cut], chain[:cut], [pod])
                    if w_rng.random() < 0.3:
                        index.evict(chain[0], [pod])
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        def reader(worker_id):
            r_rng = random.Random(100 + worker_id)
            try:
                barrier.wait()
                for _ in range(OPS):
                    prompt = prompts[r_rng.randrange(len(prompts))]
                    scores = indexer.get_pod_scores(prompt, "m")
                    assert all(v > 0 for v in scores.values()), scores
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        threads = [
            threading.Thread(target=writer, args=(i,))
            for i in range(THREADS // 2)
        ] + [
            threading.Thread(target=reader, args=(i,))
            for i in range(THREADS - THREADS // 2)
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            # Quiesced: the memoized fast lane must agree bit-exactly
            # with the straight-line oracle over the same index, twice
            # (fill/validate, then a pure memo hit).
            for prompt in prompts:
                oracle = indexer._get_pod_scores_straight(prompt, "m")
                assert indexer.get_pod_scores(prompt, "m") == oracle
                assert indexer.get_pod_scores(prompt, "m") == oracle
        finally:
            indexer.shutdown()


class TestEventPoolOrdering:
    def test_per_pod_ordering_under_concurrency(self):
        """Events from one pod must apply in publish order even with
        many workers: a store chain built out of order would break the
        parent linkage and drop request keys."""
        token_db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        index = InMemoryIndex(InMemoryIndexConfig(size=100_000))
        pool = Pool(index, token_db, PoolConfig(concurrency=4))
        pool.start()
        try:
            n_chains = 6
            chain_len = 20
            for pod_i in range(n_chains):
                pod = f"pod-{pod_i}"
                for j in range(chain_len):
                    event = BlockStored(
                        block_hashes=[0x1000 * (pod_i + 1) + j],
                        parent_block_hash=(
                            0x1000 * (pod_i + 1) + j - 1 if j else None
                        ),
                        token_ids=[j * 4 + t for t in range(4)],
                        block_size=4,
                        medium="hbm",
                    )
                    batch = EventBatch(ts=time.time(), events=[event])
                    pool.add_task(
                        Message(
                            topic=f"kv@{pod}@m",
                            payload=batch.encode(),
                            pod_identifier=pod,
                            model_name="m",
                            seq=j,
                        )
                    )
            pool.drain()
            # Every chain's full depth resolved: the last engine key of
            # each chain has a request key (parent linkage held).
            for pod_i in range(n_chains):
                last = 0x1000 * (pod_i + 1) + chain_len - 1
                assert index.get_request_key(last)
        finally:
            pool.shutdown()


class TestSubscriberManagerChurn:
    """Fleet churn storm over the consolidated poller registry:
    concurrent ensure_subscriber endpoint-flip restarts +
    remove_subscriber + shutdown racing the poller threads.  Asserts
    the registry stays consistent, no poller threads or sockets leak
    (thread names are the observable; sockets close when their poller
    exits or processes the detach — KV008 pins the static half), and
    no events are delivered for a pod after its detach returned."""

    def test_ensure_remove_shutdown_storm(self):
        import uuid as _uuid

        import zmq

        from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
            SubscriberManager,
        )

        def poller_threads():
            return [
                t
                for t in threading.enumerate()
                if t.name.startswith("kvtpu-evplane-poller-")
            ]

        before = len(poller_threads())
        context = zmq.Context.instance()
        run = _uuid.uuid4().hex
        manager = SubscriberManager(
            sink=lambda m: None,
            context=context,
            pollers=2,
            poll_interval_ms=5,
        )
        pods = [f"churn-{run}-{i}" for i in range(16)]
        stop = threading.Event()
        errors = []

        def churner(worker: int):
            rng = random.Random(worker)
            try:
                while not stop.is_set():
                    pod = rng.choice(pods)
                    op = rng.random()
                    if op < 0.5:
                        # Endpoint flip forces detach+attach restarts.
                        manager.ensure_subscriber(
                            pod,
                            f"tcp://10.255.0.{rng.randrange(1, 9)}:5557",
                        )
                    elif op < 0.8:
                        manager.remove_subscriber(pod)
                    else:
                        manager.active_pods()
            except Exception as exc:  # noqa: BLE001 - surfaced below
                errors.append(exc)

        threads = [
            threading.Thread(target=churner, args=(i,)) for i in range(THREADS)
        ]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        # Registry consistent: every listed pod detaches cleanly.
        for pod in manager.active_pods():
            assert manager.remove_subscriber(pod)
        assert manager.active_pods() == []
        manager.shutdown()
        # Shutdown is idempotent and racing churn can't resurrect it.
        assert not manager.ensure_subscriber(
            pods[0], "tcp://10.255.0.1:5557"
        )
        manager.shutdown()
        assert len(poller_threads()) == before, (
            "poller threads leaked by the churn storm"
        )

    def test_no_events_after_detach_under_churn(self):
        import struct
        import uuid as _uuid

        import zmq

        from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
            SubscriberManager,
        )

        context = zmq.Context.instance()
        run = _uuid.uuid4().hex
        endpoint = f"inproc://churn-detach-{run}"
        delivered = []
        lock = threading.Lock()

        def sink(message):
            with lock:
                delivered.append(message.seq)

        pub = context.socket(zmq.PUB)
        pub.setsockopt(zmq.LINGER, 0)
        pub.bind(endpoint)
        manager = SubscriberManager(
            sink=sink, context=context, poll_interval_ms=5
        )
        try:
            manager.ensure_subscriber("cd", endpoint)
            seq = 0
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not delivered:
                seq += 1
                pub.send_multipart(
                    [b"kv@cd@m", struct.pack(">Q", seq), b"p"]
                )
                time.sleep(0.02)
            assert delivered, "subscription never became live"
            manager.remove_subscriber("cd")
            detach_marker = seq
            for _ in range(50):
                seq += 1
                pub.send_multipart(
                    [b"kv@cd@m", struct.pack(">Q", seq), b"p"]
                )
                time.sleep(0.002)
            time.sleep(0.2)
            with lock:
                late = [s for s in delivered if s > detach_marker]
            assert late == [], "events delivered after detach"
        finally:
            manager.shutdown()
            pub.close()


class TestTTLCacheUnderContention:
    def test_concurrent_set_sweep(self):
        evicted = []
        cache = TTLCache(0.02, on_evict=lambda k, v: evicted.append(k))
        stop = threading.Event()

        def setter():
            i = 0
            while not stop.is_set():
                cache.set(f"k{i % 50}", i)
                i += 1

        def sweeper():
            while not stop.is_set():
                cache.sweep()

        threads = [threading.Thread(target=setter) for _ in range(4)] + [
            threading.Thread(target=sweeper) for _ in range(2)
        ]
        for t in threads:
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        # No exceptions and the cache still functions.
        cache.set("alive", 1)
        assert cache.get("alive") == 1

    def test_eviction_skipped_when_key_reinserted(self):
        """A set() landing between expiry-removal and the on_evict call
        must not have its fresh entry torn down by the stale eviction
        (the subscriber-lifecycle race in the scheduler plugin)."""
        evicted = []
        cache = TTLCache(60.0, on_evict=lambda k, v: evicted.append((k, v)))

        # Deterministic interleave of the race window: the key was
        # already removed under the lock, and a concurrent set()
        # re-inserted it before the callback fires.
        cache.set("pod", "fresh-subscriber")
        cache._fire_eviction("pod", "stale-subscriber")
        assert evicted == []

        # Once the key is truly absent the eviction does fire.
        cache.delete("pod")
        cache._fire_eviction("pod", "stale-subscriber")
        assert evicted == [("pod", "stale-subscriber")]


class TestClusterFanoutStorm:
    """Pipelined fan-out vs membership kills: scoring readers drive the
    overlapped cluster read path (chunk pipelining + concurrent owner
    RPCs, arming forced) while a chaos thread kills and revives one
    replica at a time.  Every replica is seeded with the FULL record
    set (not just its slice + standby) and nothing writes during the
    storm, so no matter how kills, re-routes, and late failure reports
    interleave — a reader's in-flight mark_dead can land after the
    chaos thread already revived the victim, briefly removing two
    replicas from the ring — every read must equal the pre-storm
    oracle, not merely 'no exceptions'."""

    def test_pipelined_reads_survive_kill_revive(self, tmp_path):
        from llm_d_kv_cache_manager_tpu.cluster import LocalCluster
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
            Indexer,
            IndexerConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            EMPTY_BLOCK_HASH,
            IndexConfig,
            PodEntry,
        )
        from llm_d_kv_cache_manager_tpu.tokenization.pool import (
            TokenizationPoolConfig,
        )
        from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
            Encoding,
        )

        class WordTokenizer:
            def type(self):
                return "storm-word"

            def encode(self, prompt, model_name, add_special_tokens):
                tokens, offsets, pos = [], [], 0
                for word in prompt.split(" "):
                    tokens.append(int(word[1:]))
                    offsets.append((pos, pos + len(word)))
                    pos += len(word) + 1
                return Encoding(tokens=tokens, offsets=offsets)

        cluster = LocalCluster(
            journal_root=str(tmp_path),
            # Force arming: the in-process transport's latency EWMA
            # would otherwise keep the storm on the sequential path.
            overlap_min_rpc_s=0,
        )
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
                kvblock_index_config=IndexConfig(
                    in_memory_config=InMemoryIndexConfig(size=50_000)
                ),
                # Exact tokenization keeps every read on the chunked
                # drive (the prefix store would otherwise serve warm
                # repeats as one pre-hashed chunk and the storm would
                # stop exercising chunk pipelining after pass one).
                tokenizers_pool_config=TokenizationPoolConfig(
                    min_prefix_overlap_ratio=1.01
                ),
                read_path_fast_lane=True,
                lookup_chunk_size=8,
            ),
            tokenizer=WordTokenizer(),
            kv_block_index=cluster.remote_index,
        )
        indexer.run()
        try:
            rng = random.Random(11)
            pods = [
                PodEntry("pod-a", "hbm"),
                PodEntry("pod-b", "host"),
                PodEntry("pod-c", "shared_storage"),
            ]
            prompts = []
            for _ in range(6):
                tokens = [
                    rng.randrange(1, 60_000) for _ in range(96)
                ]
                chain = indexer.token_processor.tokens_to_kv_block_keys(
                    EMPTY_BLOCK_HASH, tokens, "m"
                )
                chosen = pods[: rng.randrange(1, len(pods) + 1)]
                cluster.remote_index.add(chain, chain, chosen)
                # Top every replica up to the full record set directly
                # (adds are idempotent): the ring can then route a key
                # anywhere during the storm — including the two-dead
                # window a late mark_dead opens — without changing what
                # a lookup returns.
                for replica in cluster.replicas.values():
                    replica.index.add(chain, chain, chosen)
                prompts.append(" ".join(f"t{t}" for t in tokens))
            # Drain the journal followers too, so the replication plane
            # is quiet (not mid-apply) when the storm starts.
            while cluster.sync_followers():
                pass

            # Two pre-storm passes pin the oracle and prove the read
            # path is repeat-stable before any chaos starts.
            oracle = [indexer.get_pod_scores(p, "m") for p in prompts]
            assert all(oracle), oracle
            assert [
                indexer.get_pod_scores(p, "m") for p in prompts
            ] == oracle

            readers = THREADS - 1
            errors = []
            stop = threading.Event()
            barrier = threading.Barrier(readers + 1)

            def reader(worker_id):
                r_rng = random.Random(200 + worker_id)
                try:
                    barrier.wait()
                    for _ in range(OPS):
                        pick = r_rng.randrange(len(prompts))
                        scores = indexer.get_pod_scores(
                            prompts[pick], "m"
                        )
                        assert scores == oracle[pick], (
                            pick,
                            scores,
                            oracle[pick],
                        )
                except Exception as exc:  # pragma: no cover - failure
                    errors.append(exc)

            def chaos():
                ids = list(cluster.replicas)
                turn = 0
                try:
                    barrier.wait()
                    while not stop.is_set():
                        victim = ids[turn % len(ids)]
                        turn += 1
                        cluster.kill(victim)
                        time.sleep(0.005)
                        cluster.transports[victim].revive()
                        cluster.membership.mark_alive(victim)
                        time.sleep(0.005)
                except Exception as exc:  # pragma: no cover - failure
                    errors.append(exc)

            threads = [
                threading.Thread(target=reader, args=(i,))
                for i in range(readers)
            ]
            chaos_thread = threading.Thread(target=chaos)
            for t in threads:
                t.start()
            chaos_thread.start()
            for t in threads:
                t.join(timeout=120)
            stop.set()
            chaos_thread.join(timeout=30)
            assert not errors, errors

            # Quiesce: revive everyone, then the pipelined lane must
            # still agree with the oracle AND the straight-path walk.
            for replica_id in cluster.transports:
                cluster.transports[replica_id].revive()
                cluster.membership.mark_alive(replica_id)
            for pick, prompt in enumerate(prompts):
                assert indexer.get_pod_scores(prompt, "m") == oracle[pick]
                assert (
                    indexer._get_pod_scores_straight(prompt, "m")
                    == oracle[pick]
                )

            stats = cluster.remote_index.rpc_stats()
            assert stats["fanout"]["armed"], stats["fanout"]
            assert stats["critical_path"]["speculative_rpcs"] > 0, stats
        finally:
            indexer.shutdown()
            cluster.close()
