"""E2E tests for the tracing debug surface through the booted service.

Acceptance criteria from ISSUE 3 live here: a scored request with a
sampled traceparent yields a retrievable trace whose spans cover
templating/tokenization/hashing/index-lookup/scoring with stage
durations summing to ~the end-to-end latency; ``explain=1`` names the
block index where each pod's prefix chain broke; parallel traced
requests lose and duplicate nothing; the gRPC surface ingests and
echoes traceparent metadata; ``/healthz`` carries the observability
block.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import urllib.error
import urllib.request

import pytest

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import (
    build_transformers_tokenizer,
    save_tokenizer_json,
)

MODEL = "test-model"
BLOCK_SIZE = 4
SENTENCE = "the quick brown fox jumps over the lazy dog . "


def sampled_tp(seed: int) -> str:
    return f"00-{seed:032x}-{(seed | 1):016x}-01"


class Fleet:
    def __init__(self, indexer, event_pool, base_url):
        self.indexer = indexer
        self.event_pool = event_pool
        self.base_url = base_url
        self._next_hash = 0x1000

    def publish(self, pod, tokens, parent=None, medium="hbm"):
        n_blocks = len(tokens) // BLOCK_SIZE
        hashes = [self._next_hash + i for i in range(n_blocks)]
        self._next_hash += n_blocks
        batch = EventBatch(
            ts=1.0,
            events=[
                BlockStored(
                    block_hashes=hashes,
                    parent_block_hash=parent,
                    token_ids=tokens[: n_blocks * BLOCK_SIZE],
                    block_size=BLOCK_SIZE,
                    medium=medium,
                )
            ],
        )
        self.event_pool.add_task(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=batch.encode(),
                pod_identifier=pod,
                model_name=MODEL,
            )
        )
        self.event_pool.drain()
        return hashes

    def tokenize(self, prompt):
        return self.indexer.tokenization_pool.tokenize(prompt, MODEL, None)

    def post(self, path, obj, headers=None):
        request = urllib.request.Request(
            self.base_url + path,
            data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST",
        )
        with urllib.request.urlopen(request, timeout=30) as response:
            return dict(response.headers), json.load(response)

    def get(self, path):
        with urllib.request.urlopen(
            self.base_url + path, timeout=30
        ) as response:
            return json.load(response)


@pytest.fixture()
def fleet(tmp_path):
    tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.chat_processor.register_tokenizer(
        MODEL, build_transformers_tokenizer()
    )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    # Rate 0 proves the traceparent/explain forcing paths; restore after.
    previous_rate = TRACER.config.sample_rate
    TRACER.configure(sample_rate=0.0)
    yield Fleet(indexer, event_pool, base)
    TRACER.configure(sample_rate=previous_rate)
    server.shutdown()
    event_pool.shutdown()
    indexer.shutdown()


class TestTraceparentSurface:
    def test_sampled_traceparent_echoed_and_retrievable(self, fleet):
        trace_id = f"{0xDEADBEEF:032x}"
        header = f"00-{trace_id}-{'ab' * 8}-01"
        headers, scores = fleet.post(
            "/score_completions",
            {"prompt": SENTENCE * 8, "model": MODEL},
            headers={"traceparent": header},
        )
        assert isinstance(scores, dict)
        echoed = headers.get("traceparent")
        assert echoed is not None and echoed.split("-")[1] == trace_id
        assert echoed.split("-")[2] != "ab" * 8  # our span, not theirs

        listing = fleet.get("/debug/traces?kind=recent")
        assert trace_id in [t["trace_id"] for t in listing["traces"]]

        full = fleet.get(f"/debug/traces/{trace_id}")
        assert full["name"] == "http.score_completions"
        assert full["parent_span_id"] == "ab" * 8

    def test_spans_cover_stages_and_sum_to_total(self, fleet):
        """Acceptance: spans cover tokenization, hashing, index lookup
        and scoring; top-level stage durations sum to the end-to-end
        trace latency within 5%.  Best-of-3 requests: the pin is on
        the instrumentation, and a single scheduler hiccup between
        stages (full-suite runs share one core) must not flake it."""
        prompt = SENTENCE * 200  # long enough that stages dominate
        best_gap = None
        for attempt in range(3):
            trace_id = f"{0x51051 + attempt:032x}"
            fleet.post(
                "/score_completions",
                {"prompt": prompt, "model": MODEL},
                headers={"traceparent": f"00-{trace_id}-{'cd' * 8}-01"},
            )
            full = fleet.get(f"/debug/traces/{trace_id}")
            stages = {
                s["stage"]: s["duration_ms"] for s in full["stages"]
            }
            assert {
                "tokenize",
                "hash_blocks",
                "index_lookup",
                "score",
            } <= set(stages)
            total = full["duration_ms"]
            gap = abs(sum(stages.values()) - total) / total
            best_gap = gap if best_gap is None else min(best_gap, gap)
            if best_gap <= 0.05:
                break
        assert best_gap <= 0.05, best_gap
        # Worker-side sub-spans attached under the tokenize stage.
        sub_spans = {
            s["name"] for s in full["spans"] if s["parent"] == "tokenize"
        }
        assert sub_spans & {
            "tokenize.queue_wait",
            "tokenize.prefix_probe",
            "tokenize.encode",
        }

    def test_unsampled_request_untraced(self, fleet):
        headers, scores = fleet.post(
            "/score_completions",
            {"prompt": SENTENCE * 4, "model": MODEL},
        )
        assert isinstance(scores, dict)
        assert "traceparent" not in {k.lower() for k in headers}

    def test_parallel_traced_requests_no_lost_or_dup_ids(self, fleet):
        """Acceptance: the flight-recorder ring under parallel traced
        HTTP requests — every id retrievable exactly once."""
        n_threads, per_thread = 8, 5
        errors = []

        def worker(worker_index):
            try:
                for i in range(per_thread):
                    seed = 0xA000_0000 + worker_index * 1000 + i
                    fleet.post(
                        "/score_completions",
                        {"prompt": SENTENCE * 4, "model": MODEL},
                        headers={"traceparent": sampled_tp(seed)},
                    )
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(n_threads)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=60)
        assert not errors
        listing = fleet.get("/debug/traces?kind=recent&limit=1000")
        ids = [t["trace_id"] for t in listing["traces"]]
        expected = {
            f"{0xA000_0000 + w * 1000 + i:032x}"
            for w in range(n_threads)
            for i in range(per_thread)
        }
        present = [t for t in ids if t in expected]
        assert len(present) == len(expected)
        assert len(set(present)) == len(expected)


class TestExplain:
    def test_break_index_and_tiers_per_pod(self, fleet):
        """Acceptance: explain names, per pod, the block index where
        the consecutive-prefix chain broke."""
        prompt = SENTENCE * 16
        tokens = fleet.tokenize(prompt)
        n_blocks = len(tokens) // BLOCK_SIZE
        half = n_blocks // 2 * BLOCK_SIZE
        fleet.publish("pod-half", tokens[:half])
        fleet.publish("pod-full", tokens, medium="host")

        _, body = fleet.post(
            "/score_completions?explain=1",
            {"prompt": prompt, "model": MODEL},
        )
        assert body["scores"]["pod-full"] == pytest.approx(0.8 * n_blocks)
        explain = body["explain"]
        assert explain["block_keys"] == n_blocks
        half_detail = explain["pods"]["pod-half"]
        assert half_detail["blocks_matched"] == half // BLOCK_SIZE
        assert half_detail["break_index"] == half // BLOCK_SIZE
        assert half_detail["tiers"] == {"hbm": half // BLOCK_SIZE}
        full_detail = explain["pods"]["pod-full"]
        assert full_detail["break_index"] is None
        assert full_detail["tiers"] == {"host": n_blocks}
        # Stage breakdown rides along with a live trace id.
        assert explain["stages"]
        assert fleet.get(f"/debug/traces/{explain['trace_id']}")

    def test_explain_scores_match_plain_scores(self, fleet):
        prompt = SENTENCE * 8
        fleet.publish("pod-1", fleet.tokenize(prompt))
        _, plain = fleet.post(
            "/score_completions", {"prompt": prompt, "model": MODEL}
        )
        _, explained = fleet.post(
            "/score_completions?explain=1",
            {"prompt": prompt, "model": MODEL},
        )
        assert explained["scores"] == plain

    def test_chat_explain_covers_templating(self, fleet):
        """Acceptance: spans cover templating on the chat path."""
        messages = [
            {"role": "system", "content": "you are a helpful assistant ."},
            {"role": "user", "content": SENTENCE * 4},
        ]
        _, body = fleet.post(
            "/score_chat_completions?explain=1",
            {"model": MODEL, "messages": messages},
        )
        full = fleet.get(f"/debug/traces/{body['explain']['trace_id']}")
        names = {s["name"] for s in full["spans"]}
        assert "tokenize.chat_template" in names


class TestDebugEndpoints:
    def test_healthz_observability_block(self, fleet):
        fleet.post(
            "/score_completions",
            {"prompt": SENTENCE * 4, "model": MODEL},
            headers={"traceparent": sampled_tp(0xBEEF)},
        )
        health = fleet.get("/healthz")
        obs = health["observability"]
        assert obs["ring_size"] == TRACER.recorder.ring_size
        assert obs["ring_occupancy"] >= 1
        assert obs["traces_sampled"] >= 1
        assert "traces_unsampled" in obs
        assert "slow_threshold_ms" in obs

    def test_debug_traces_kind_filters(self, fleet):
        for kind in ("recent", "slow", "errored"):
            listing = fleet.get(f"/debug/traces?kind={kind}")
            assert listing["kind"] == kind
            assert isinstance(listing["traces"], list)

    def test_debug_traces_rejects_bad_kind(self, fleet):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            fleet.get("/debug/traces?kind=bogus")
        assert excinfo.value.code == 400

    def test_unknown_trace_id_404(self, fleet):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            fleet.get(f"/debug/traces/{'9' * 32}")
        assert excinfo.value.code == 404


class TestGrpcTraceparent:
    def test_grpc_metadata_ingest_and_echo(self, fleet, tmp_path):
        from llm_d_kv_cache_manager_tpu.api import indexer_pb2
        from llm_d_kv_cache_manager_tpu.api.indexer_service import (
            new_client,
            serve as grpc_serve,
        )

        uds = os.path.join(
            tempfile.mkdtemp(dir=str(tmp_path)), "indexer.sock"
        )
        server = grpc_serve(fleet.indexer, f"unix://{uds}")
        try:
            client = new_client(f"unix://{uds}")
            trace_id = f"{0x6677:032x}"
            response, call = client.GetPodScores.with_call(
                indexer_pb2.GetPodScoresRequest(
                    prompt=SENTENCE * 4, model_name=MODEL
                ),
                metadata=(
                    ("traceparent", f"00-{trace_id}-{'ef' * 8}-01"),
                ),
                timeout=30,
            )
            echoed = {
                key: value for key, value in call.initial_metadata()
            }.get("traceparent")
            assert echoed is not None
            assert echoed.split("-")[1] == trace_id
            full = fleet.get(f"/debug/traces/{trace_id}")
            assert full["name"] == "grpc.get_pod_scores"
            client.channel.close()
        finally:
            server.stop(grace=None)
