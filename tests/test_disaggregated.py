"""Disaggregated prefill/decode: cross-pod KV transfer via the connector.

BASELINE.json config #5: a prefill pod computes a prompt's KV and
persists it through the offload connector; a separate decode pod, with
its own independent block pool, discovers the prefix in shared storage
(manager lookup), pages it in, and continues decoding — producing
exactly the logits the prefill pod would have.  The shared-storage file
layout is pod-independent (model/geometry/mesh/rank/dtype only), which
is what makes the transfer medium work across pods, mirroring the
reference's cross-pod shared-filesystem design (manager.py:44-54).
"""

import jax
import jax.numpy as jnp
import numpy as np

from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import (
    KVCachePool,
    KVCachePoolConfig,
)
from llm_d_kv_cache_manager_tpu.native.engine import JobStatus
from llm_d_kv_cache_manager_tpu.offload.spec import (
    TPUOffloadConnector,
    TPUOffloadSpec,
)
from llm_d_kv_cache_manager_tpu.offload.worker import group_blocks_per_file

CFG = llama.LlamaConfig(
    vocab_size=512,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    block_size=8,
)
PROMPT_TOKENS = 32  # 4 device blocks
POOL = KVCachePoolConfig(
    num_layers=CFG.n_layers,
    num_blocks=16,
    block_size=CFG.block_size,
    num_kv_heads=CFG.n_kv_heads,
    head_dim=CFG.head_dim,
    dtype="bfloat16",
)


def make_connector(tmp_path, pool):
    return TPUOffloadConnector(
        TPUOffloadSpec(
            shared_storage_path=str(tmp_path),
            model_name="test/llama",
            device_block_size=CFG.block_size,
            offloaded_block_size=CFG.block_size * 2,
            threads_per_chip=2,
        ),
        pool,
    )


def test_prefill_pod_to_decode_pod(tmp_path):
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, PROMPT_TOKENS), 0, CFG.vocab_size
    )
    n_blocks = PROMPT_TOKENS // CFG.block_size
    file_hashes = [0x9A00 + i for i in range(n_blocks // 2)]

    # --- prefill pod: compute KV, persist through its connector ---
    prefill_pool = KVCachePool(POOL)
    prefill_conn = make_connector(tmp_path, prefill_pool)
    prefill_ids = list(range(n_blocks))
    logits_prefill, prefill_pool.kv = llama.prefill_paged(
        params,
        tokens,
        prefill_pool.kv,
        jnp.asarray([prefill_ids], jnp.int32),
        CFG,
    )
    groups = group_blocks_per_file(
        file_hashes, prefill_ids, prefill_conn.spec.blocks_per_file
    )
    prefill_conn.store_handler.transfer_async(1, groups)
    assert prefill_conn.store_handler.wait(1) == JobStatus.SUCCEEDED

    # --- decode pod: discover, page in, continue ---
    decode_pool = KVCachePool(POOL)
    decode_conn = make_connector(tmp_path, decode_pool)
    # Scheduler-side lookup: how many consecutive offloaded blocks exist?
    assert decode_conn.get_manager().lookup(file_hashes) == len(file_hashes)

    decode_ids = [7, 3, 11, 5]  # deliberately different pool layout
    decode_groups = group_blocks_per_file(
        file_hashes, decode_ids, decode_conn.spec.blocks_per_file
    )
    decode_conn.load_handler.transfer_async(2, decode_groups)
    assert decode_conn.load_handler.wait(2) == JobStatus.SUCCEEDED

    # Decode the next token on each pod; logits must agree exactly.
    next_token = jnp.argmax(logits_prefill[:, -1], axis=-1).astype(
        jnp.int32
    )
    max_blocks = n_blocks + 1
    ctx = jnp.asarray([PROMPT_TOKENS + 1], jnp.int32)

    logits_a, _ = llama.decode_step(
        params,
        next_token,
        prefill_pool.kv,
        jnp.asarray([prefill_ids + [8]], jnp.int32),
        ctx,
        CFG,
    )
    logits_b, _ = llama.decode_step(
        params,
        next_token,
        decode_pool.kv,
        jnp.asarray([decode_ids + [0]], jnp.int32),
        ctx,
        CFG,
    )
    np.testing.assert_array_equal(
        np.asarray(logits_a), np.asarray(logits_b)
    )


def test_decode_pod_partial_prefix_detected(tmp_path):
    """A partially-transferred prefix is reported as the consecutive
    head only — the decode pod prefills the tail itself."""
    pool = KVCachePool(POOL)
    conn = make_connector(tmp_path, pool)
    hashes = [0x9B00 + i for i in range(3)]
    groups = group_blocks_per_file(
        hashes[:2], list(range(4)), conn.spec.blocks_per_file
    )
    conn.store_handler.transfer_async(1, groups)
    assert conn.store_handler.wait(1) == JobStatus.SUCCEEDED
    assert conn.get_manager().lookup(hashes) == 2
