"""Event-plane fast lane: consolidated poller, per-pod flow control,
gap-driven resync (docs/event-plane.md).

Covers the fleet-scale subscription layer (PollerPool multiplexing many
SUB sockets over a fixed thread pool), the ingestion pool's per-pod
lanes (fairness property: a pod under its effective budget is never
shed), the seq-tracker's gap / publisher-restart / duplicate
classification, publisher thread-safety, and the anti-entropy resync
state machine (suspect -> fetch -> purge + re-apply -> staleness
report).
"""

import threading
import time
import uuid

import pytest
import zmq

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import InMemoryIndexConfig
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
    ResyncJob,
    _ShardQueue,
)
from llm_d_kv_cache_manager_tpu.kvevents.poller import (
    ChannelConfig,
    PollerPool,
    PollerPoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher
from llm_d_kv_cache_manager_tpu.kvevents.resync import (
    CallableInventorySource,
    EmptyInventorySource,
    InventoryBlock,
    PodInventory,
    ResyncConfig,
    ResyncManager,
)
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
    TopicSeqTracker,
    parse_event_message,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    METRICS,
    counter_total,
)

MODEL = "m"


def _msg(pod: str, i: int = 0, resync=None) -> Message:
    return Message(
        topic=f"kv@{pod}@{MODEL}",
        payload=str(i).encode(),
        pod_identifier=pod,
        model_name=MODEL,
        seq=i,
        resync=resync,
    )


def _labeled_total(counter, **labels) -> float:
    total = 0.0
    for metric in counter.collect():
        for sample in metric.samples:
            if sample.name.endswith("_total") and all(
                sample.labels.get(k) == v for k, v in labels.items()
            ):
                total += sample.value
    return total


class TestTopicSeqTracker:
    def test_in_order_and_gap(self):
        tracker = TopicSeqTracker()
        assert tracker.observe("t", 1).gap == 0
        assert tracker.observe("t", 2).gap == 0
        observed = tracker.observe("t", 5)
        assert observed.gap == 2 and not observed.restarted
        assert tracker.gap_count == 2

    def test_regression_is_restart_not_gap(self):
        """Satellite: a publisher restart (counter reset to 1) resets
        the watermark and counts a restart — NOT a gap."""
        tracker = TopicSeqTracker()
        tracker.observe("t", 41)
        observed = tracker.observe("t", 1)
        assert observed.restarted and observed.gap == 0
        assert tracker.gap_count == 0
        assert tracker.restart_count == 1
        # Watermark reset: the restarted stream continues gap-free.
        assert tracker.observe("t", 2).gap == 0
        # And a real gap after the restart is still detected.
        assert tracker.observe("t", 5).gap == 2

    def test_duplicate_not_restart(self):
        tracker = TopicSeqTracker()
        tracker.observe("t", 7)
        observed = tracker.observe("t", 7)
        assert observed.duplicate
        assert tracker.restart_count == 0
        assert tracker.observe("t", 8).gap == 0

    def test_topics_independent(self):
        tracker = TopicSeqTracker()
        tracker.observe("a", 10)
        assert tracker.observe("b", 1).gap == 0
        assert tracker.observe("a", 11).gap == 0

    def test_parse_message_restart_metric_and_callback(self):
        import struct

        tracker = TopicSeqTracker()
        gaps = []

        def deliver(seq):
            return parse_event_message(
                [b"kv@rp@m", struct.pack(">Q", seq), b"x"],
                endpoint="inproc://t",
                pod_identifier="rp",
                tracker=tracker,
                on_gap=lambda pod, topic, gap: gaps.append((pod, gap)),
            )

        restarts_before = _labeled_total(
            METRICS.kvevents_publisher_restarts, pod="rp"
        )
        gaps_before = _labeled_total(METRICS.kvevents_seq_gaps, pod="rp")
        assert deliver(5) is not None
        assert deliver(1) is not None  # restart
        assert deliver(2) is not None
        assert deliver(9) is not None  # gap of 6
        assert deliver(9) is None  # duplicate: dropped
        assert (
            _labeled_total(METRICS.kvevents_publisher_restarts, pod="rp")
            - restarts_before
            == 1.0
        )
        assert (
            _labeled_total(METRICS.kvevents_seq_gaps, pod="rp") - gaps_before
            == 6.0
        )
        assert gaps == [("rp", 6)]


class TestPublisherThreadSafety:
    def test_concurrent_publish_unique_ordered_seqs(self):
        """Satellite regression: unlocked `self._seq += 1` + send let
        concurrent publishers interleave seq assignment and emit false
        gaps.  With the lock, seqs are unique, dense, and each send
        happens in seq order."""
        context = zmq.Context.instance()
        pub = Publisher(
            f"inproc://pub-safety-{uuid.uuid4().hex}",
            "pod-x",
            MODEL,
            bind=True,
            context=context,
        )
        seqs = []
        seq_lock = threading.Lock()
        threads = 8
        per_thread = 200
        barrier = threading.Barrier(threads)

        def worker():
            barrier.wait()
            mine = [
                pub.publish(
                    BlockStored(
                        block_hashes=[1],
                        parent_block_hash=None,
                        token_ids=[1],
                        block_size=1,
                    )
                )
                for _ in range(per_thread)
            ]
            with seq_lock:
                seqs.extend(mine)

        pool = [threading.Thread(target=worker) for _ in range(threads)]
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        pub.close()
        assert sorted(seqs) == list(range(1, threads * per_thread + 1))

    def test_advance_seq_forces_gap(self):
        context = zmq.Context.instance()
        pub = Publisher(
            f"inproc://pub-gap-{uuid.uuid4().hex}",
            "pod-x",
            MODEL,
            bind=True,
            context=context,
        )
        assert pub.publish() == 1
        assert pub.advance_seq(3) == 4
        assert pub.publish() == 5
        pub.close()


class TestShardQueueFlowControl:
    def test_round_robin_drain(self):
        q = _ShardQueue(max_depth=64, pod_budget=64, per_pod=True)
        for i in range(3):
            q.put(_msg("a", i))
        for i in range(3):
            q.put(_msg("b", i))
        q.put(_msg("c", 0))
        batch, closed, _ = q.get_batch(7)
        assert not closed
        order = [(m.pod_identifier, m.seq) for m in batch]
        # One message per pod per rotation, per-pod FIFO preserved.
        assert order == [
            ("a", 0), ("b", 0), ("c", 0),
            ("a", 1), ("b", 1),
            ("a", 2), ("b", 2),
        ]
        q.task_done(len(batch))
        q.join()

    def test_pod_budget_self_shed(self):
        q = _ShardQueue(max_depth=100, pod_budget=4, per_pod=True)
        shed_all = []
        for i in range(10):
            shed, depth = q.put(_msg("a", i))
            shed_all.extend(shed)
            assert depth <= 4
        assert len(shed_all) == 6
        assert all(reason == "pod_budget" for _, reason in shed_all)
        # Oldest shed first; newest survive in order.
        assert [m.seq for m, _ in shed_all] == list(range(6))
        batch, _, _ = q.get_batch(10)
        assert [m.seq for m in batch] == [6, 7, 8, 9]

    def test_fairness_property_quiet_pod_never_shed(self):
        """THE fairness property: a pod under its effective budget
        (min(pod_budget, max_depth // active pods)) is never shed, no
        matter how chatty its shard neighbors are."""
        q = _ShardQueue(max_depth=16, pod_budget=16, per_pod=True)
        # Quiet pod: 3 messages (< 16 // 2 = 8 fair share).
        for i in range(3):
            q.put(_msg("quiet", i))
        # Chatty pod floods far past the shard bound.
        shed_all = []
        for i in range(100):
            shed, _ = q.put(_msg("chatty", i))
            shed_all.extend(shed)
        assert shed_all, "the flood must shed"
        assert all(
            m.pod_identifier == "chatty" for m, _ in shed_all
        ), "only the over-budget pod pays for its own flood"
        depths = q.lane_depths()
        assert depths["quiet"] == 3
        assert depths["quiet"] + depths["chatty"] <= 16

    def test_overflow_reason_is_queue_full_not_pod_budget(self):
        """Whole-shard overflow keeps its long-documented queue_full
        reason even when the overflowing lane also sits at its budget
        — in legacy single-lane mode (budget == depth) every overflow
        would otherwise be relabeled pod_budget, silencing dashboards
        keyed on queue_full."""
        legacy = _ShardQueue(max_depth=4, pod_budget=4, per_pod=False)
        shed_all = []
        for i in range(6):
            shed, _ = legacy.put(_msg("a", i))
            shed_all.extend(shed)
        assert shed_all and all(
            reason == "queue_full" for _, reason in shed_all
        )
        # Same at a full per-pod shard monopolized by one lane.
        per_pod = _ShardQueue(max_depth=4, pod_budget=4, per_pod=True)
        shed_all = []
        for i in range(6):
            shed, _ = per_pod.put(_msg("a", i))
            shed_all.extend(shed)
        assert shed_all and all(
            reason == "queue_full" for _, reason in shed_all
        )

    def test_global_fifo_compat_mode(self):
        q = _ShardQueue(max_depth=4, pod_budget=4, per_pod=False)
        shed_all = []
        for i in range(6):
            shed, _ = q.put(_msg("a" if i % 2 else "b", i))
            shed_all.extend(shed)
        # Legacy drop-oldest: the two OLDEST messages shed regardless
        # of pod.
        assert [m.seq for m, _ in shed_all] == [0, 1]
        batch, _, _ = q.get_batch(10)
        assert [m.seq for m in batch] == [2, 3, 4, 5]

    def test_commands_never_shed(self):
        q = _ShardQueue(max_depth=2, pod_budget=2, per_pod=True)
        job = ResyncJob(pod_identifier="a", model_name=MODEL)
        q.put(_msg("a", 0, resync=job))
        shed_all = []
        for i in range(1, 6):
            shed, _ = q.put(_msg("a", i))
            shed_all.extend(shed)
        assert all(m.resync is None for m, _ in shed_all)
        batch, _, _ = q.get_batch(10)
        assert batch[0].resync is job

    def test_closed_queue_rejects(self):
        q = _ShardQueue(max_depth=4, pod_budget=4, per_pod=True)
        q.put(_msg("a", 0))
        q.close()
        shed, depth = q.put(_msg("a", 1))
        assert depth == -1 and shed[0][1] == "shutdown"
        # Close drains the remainder, then reports closed.
        batch, closed, _ = q.get_batch(10)
        assert [m.seq for m in batch] == [0] and not closed
        q.task_done(1)
        batch, closed, _ = q.get_batch(10)
        assert closed and not batch


class TestPoolFlowControl:
    def test_chatty_pod_cannot_starve_shard_neighbor(self):
        """Pool-level fairness: flood one pod of an UNSTARTED pool (so
        the shard backs up) and the co-sharded quiet pod's messages all
        survive; per-pod shed metrics name only the chatty pod."""
        index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = Pool(
            index, db, PoolConfig(concurrency=1, max_queue_depth=8)
        )
        chatty_before = _labeled_total(
            METRICS.kvevents_pod_shed, pod="chatty"
        )
        quiet_before = _labeled_total(METRICS.kvevents_pod_shed, pod="quiet")

        def stored(i):
            return BlockStored(
                block_hashes=[i + 1],
                parent_block_hash=None,
                token_ids=[1, 2, 3, 4],
                block_size=4,
            )

        def deliver(pod, i):
            batch = EventBatch(ts=float(i), events=[stored(i)])
            pool.add_task(
                Message(
                    topic=f"kv@{pod}@{MODEL}",
                    payload=batch.encode(),
                    pod_identifier=pod,
                    model_name=MODEL,
                )
            )

        for i in range(3):
            deliver("quiet", i)
        for i in range(50):
            deliver("chatty", i)
        # Both pods always co-shard at concurrency=1.
        shard = pool._shard_for("quiet")
        assert shard is pool._shard_for("chatty")
        depths = shard.lane_depths()
        assert depths["quiet"] == 3
        assert (
            _labeled_total(METRICS.kvevents_pod_shed, pod="quiet")
            == quiet_before
        )
        assert (
            _labeled_total(METRICS.kvevents_pod_shed, pod="chatty")
            > chatty_before
        )
        pool.start()
        pool.drain()
        pool.shutdown()


def _make_pool(block_size=4, **kw):
    index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=block_size))
    pool = Pool(index, db, PoolConfig(concurrency=2, **kw))
    pool.start()
    return pool, index, db


class TestResync:
    def _seed_stale(self, pool, index, db, pod="pod-r"):
        """Apply one live event, then plant a STALE entry the inventory
        will not contain."""
        tokens = [1, 2, 3, 4]
        batch = EventBatch(
            ts=1.0,
            events=[
                BlockStored(
                    block_hashes=[0xA],
                    parent_block_hash=None,
                    token_ids=tokens,
                    block_size=4,
                )
            ],
        )
        pool.add_task(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=batch.encode(),
                pod_identifier=pod,
                model_name=MODEL,
            )
        )
        pool.drain()
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        assert index.lookup(keys)
        return keys

    def test_resync_purges_and_reapplies_inventory(self):
        pool, index, db = _make_pool()
        pod = "pod-r"
        stale_keys = self._seed_stale(pool, index, db, pod)
        fresh_tokens = [9, 9, 9, 9, 8, 8, 8, 8]

        source = CallableInventorySource(
            lambda p: PodInventory(
                pod_identifier=p,
                model_name=MODEL,
                blocks=[
                    InventoryBlock(
                        block_hashes=[0xB1, 0xB2],
                        token_ids=fresh_tokens,
                        block_size=4,
                        medium="hbm",
                    )
                ],
            )
        )
        manager = ResyncManager(pool, source, ResyncConfig())
        manager.start()
        ok_before = counter_total(METRICS.kvevents_resyncs)
        assert manager.mark_suspect(pod, MODEL)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and manager.is_suspect(pod):
            time.sleep(0.01)
        assert not manager.is_suspect(pod), manager.stats()
        manager.close()
        pool.shutdown()
        # Stale claim gone, inventory claims present.
        assert not index.lookup(stale_keys)
        fresh_keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, fresh_tokens, MODEL
        )
        found = index.lookup(fresh_keys)
        assert set(found) == set(fresh_keys)
        assert found[fresh_keys[0]] == [PodEntry(pod, "hbm")]
        assert counter_total(METRICS.kvevents_resyncs) > ok_before
        assert manager.stats()["resyncs_ok"] >= 1

    def test_empty_source_is_purge_only(self):
        pool, index, db = _make_pool()
        pod = "pod-r"
        stale_keys = self._seed_stale(pool, index, db, pod)
        manager = ResyncManager(pool, EmptyInventorySource())
        manager.start()
        manager.mark_suspect(pod)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and manager.is_suspect(pod):
            time.sleep(0.01)
        assert not manager.is_suspect(pod)
        manager.close()
        pool.shutdown()
        assert not index.lookup(stale_keys)

    def test_failing_source_leaves_pod_suspect(self):
        pool, index, db = _make_pool()
        pod = "pod-r"
        self._seed_stale(pool, index, db, pod)
        failed_before = None
        manager = ResyncManager(
            pool,
            CallableInventorySource(lambda p: None),
            ResyncConfig(max_attempts=2, retry_backoff_s=0.01),
        )
        manager.start()
        manager.mark_suspect(pod)
        deadline = time.monotonic() + 10
        while (
            time.monotonic() < deadline
            and manager.stats()["resyncs_failed"] == 0
        ):
            time.sleep(0.01)
        stats = manager.stats()
        assert stats["resyncs_failed"] >= 1
        assert manager.is_suspect(pod), "failed resync must keep suspicion"
        manager.close()
        pool.shutdown()
        assert failed_before is None  # silence lint: var used as marker

    def test_mark_suspect_idempotent_while_suspect(self):
        pool, _index, _db = _make_pool()
        manager = ResyncManager(
            pool, CallableInventorySource(lambda p: None),
            ResyncConfig(max_attempts=1, retry_backoff_s=0.01),
        )
        # NOT started: marks accumulate without being consumed.
        assert manager.mark_suspect("p1")
        assert not manager.mark_suspect("p1")
        assert manager.suspect_pods() == ["p1"]
        manager.close()
        pool.shutdown()

    def test_resync_ordered_with_live_events(self):
        """A resync job rides the pod's shard lane: events enqueued
        BEFORE it are purged; events enqueued AFTER it survive."""
        pool, index, db = _make_pool()
        pod = "pod-r"
        stale_keys = self._seed_stale(pool, index, db, pod)

        done = threading.Event()
        job = ResyncJob(
            pod_identifier=pod,
            model_name=MODEL,
            events=[],
            on_done=lambda j, ok, purged, detail: done.set(),
        )
        pool.enqueue_resync(job)
        after_tokens = [5, 5, 5, 5]
        batch = EventBatch(
            ts=2.0,
            events=[
                BlockStored(
                    block_hashes=[0xC],
                    parent_block_hash=None,
                    token_ids=after_tokens,
                    block_size=4,
                )
            ],
        )
        pool.add_task(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=batch.encode(),
                pod_identifier=pod,
                model_name=MODEL,
            )
        )
        pool.drain()
        assert done.wait(5)
        assert not index.lookup(stale_keys), "pre-resync state purged"
        after_keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, after_tokens, MODEL
        )
        assert index.lookup(after_keys), "post-resync event survived"
        pool.shutdown()

    def test_shutdown_fails_pending_job(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=100))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = Pool(index, db, PoolConfig(concurrency=1))
        # Never started: the queued job must still be reported failed.
        outcome = {}

        def on_done(job, ok, purged, detail):
            outcome["ok"] = ok
            outcome["detail"] = detail

        pool.enqueue_resync(
            ResyncJob(
                pod_identifier="p", model_name=MODEL, on_done=on_done
            )
        )
        pool._started = True
        pool.shutdown()
        assert outcome == {"ok": False, "detail": "pool shutdown"}


class TestPollerPool:
    def test_many_pods_one_poller_inproc(self):
        context = zmq.Context.instance()
        run = uuid.uuid4().hex
        pods = [f"pp-{run}-{i}" for i in range(16)]
        received = []
        lock = threading.Lock()

        def sink(message):
            with lock:
                received.append((message.pod_identifier, message.payload))

        publishers = {
            pod: Publisher(
                f"inproc://{pod}", pod, MODEL, bind=True, context=context
            )
            for pod in pods
        }
        pool = PollerPool(
            context=context,
            config=PollerPoolConfig(pollers=1, poll_interval_ms=10),
        )
        channels = {
            pod: pool.attach(
                ChannelConfig(endpoint=f"inproc://{pod}", pod_identifier=pod),
                sink,
            )
            for pod in pods
        }
        try:
            deadline = time.monotonic() + 15
            seen = set()
            while time.monotonic() < deadline and len(seen) < len(pods):
                for pod in pods:
                    publishers[pod].publish(
                        BlockStored(
                            block_hashes=[1],
                            parent_block_hash=None,
                            token_ids=[1],
                            block_size=1,
                        )
                    )
                time.sleep(0.05)
                with lock:
                    seen = {pod for pod, _ in received}
            assert seen == set(pods)
            # One poller thread serves all 16 pods.
            evplane = [
                t.name
                for t in threading.enumerate()
                if t.name.startswith("kvtpu-evplane-poller-")
            ]
            assert len(evplane) == 1
        finally:
            for channel in channels.values():
                pool.detach(channel)
            pool.shutdown()
            for pub in publishers.values():
                pub.close()

    def test_no_delivery_after_detach(self):
        context = zmq.Context.instance()
        run = uuid.uuid4().hex
        endpoint = f"inproc://detach-{run}"
        received = []
        lock = threading.Lock()

        def sink(message):
            with lock:
                received.append(message.seq)

        pub = Publisher(endpoint, "dp", MODEL, bind=True, context=context)
        pool = PollerPool(
            context=context,
            config=PollerPoolConfig(pollers=1, poll_interval_ms=5),
        )
        channel = pool.attach(
            ChannelConfig(endpoint=endpoint, pod_identifier="dp"), sink
        )
        try:
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not received:
                pub.publish()
                time.sleep(0.02)
            assert received, "subscription never became live"
            pool.detach(channel)
            marker_start = pub.advance_seq(0)
            for _ in range(20):
                pub.publish()
                time.sleep(0.005)
            time.sleep(0.2)
            with lock:
                late = [s for s in received if s > marker_start]
            assert late == [], "events delivered after detach"
        finally:
            pool.shutdown()
            pub.close()

    def test_least_loaded_distribution(self):
        context = zmq.Context.instance()
        pool = PollerPool(
            context=context,
            config=PollerPoolConfig(pollers=2, poll_interval_ms=10),
        )
        channels = [
            pool.attach(
                ChannelConfig(
                    endpoint="tcp://10.255.0.1:1",
                    pod_identifier=f"lb-{i}",
                ),
                lambda m: None,
            )
            for i in range(8)
        ]
        by_poller = {}
        for channel in channels:
            by_poller.setdefault(channel.poller_index, 0)
            by_poller[channel.poller_index] += 1
        assert by_poller == {0: 4, 1: 4}
        pool.shutdown()


class TestSubscriberManagerRegistry:
    def test_gap_listener_wired_to_channels(self):
        import struct

        context = zmq.Context.instance()
        run = uuid.uuid4().hex
        endpoint = f"inproc://gap-{run}"
        gaps = []
        sunk = []
        manager = SubscriberManager(
            sink=sunk.append,
            context=context,
            poll_interval_ms=5,
            on_gap=lambda pod, topic, gap: gaps.append((pod, gap)),
        )
        pub_sock = context.socket(zmq.PUB)
        pub_sock.setsockopt(zmq.LINGER, 0)
        pub_sock.bind(endpoint)
        manager.ensure_subscriber("gp", endpoint)
        try:
            deadline = time.monotonic() + 15
            seq = 0
            while time.monotonic() < deadline and not sunk:
                seq += 1
                pub_sock.send_multipart(
                    [b"kv@gp@m", struct.pack(">Q", seq), b"p"]
                )
                time.sleep(0.02)
            assert sunk, "subscription never became live"
            # Force a gap of 5.
            seq += 5
            pub_sock.send_multipart(
                [b"kv@gp@m", struct.pack(">Q", seq + 1), b"p"]
            )
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not gaps:
                time.sleep(0.01)
            assert gaps and gaps[0][0] == "gp" and gaps[0][1] >= 5
            assert manager.gap_count("gp") >= 5
        finally:
            manager.shutdown()
            pub_sock.close()

    def test_shutdown_stops_poller_threads(self):
        manager = SubscriberManager(sink=lambda m: None, poll_interval_ms=5)
        manager.ensure_subscriber("sp", "tcp://10.255.0.9:5557")
        assert any(
            t.name.startswith("kvtpu-evplane-poller-")
            for t in threading.enumerate()
        )
        manager.shutdown()
        assert not any(
            t.name.startswith("kvtpu-evplane-poller-")
            for t in threading.enumerate()
        )
        # Post-shutdown ensure is refused, not resurrected.
        assert not manager.ensure_subscriber("sp", "tcp://10.255.0.9:5557")

    def test_dead_poller_replaced_on_attach(self):
        """A crashed poller thread must not keep collecting attach
        assignments: the pool replaces it on the next attach so fresh
        subscriptions land on a live thread and deliver."""
        context = zmq.Context.instance()
        run = uuid.uuid4().hex
        pool = PollerPool(
            context=context,
            config=PollerPoolConfig(pollers=1, poll_interval_ms=5),
        )
        received = []
        lock = threading.Lock()

        def sink(message):
            with lock:
                received.append(message.pod_identifier)

        first = pool.attach(
            ChannelConfig(
                endpoint=f"inproc://dead-{run}-a", pod_identifier="pa"
            ),
            sink,
        )
        # Simulate a poller crash: stop its thread directly, leaving
        # the pool itself running.
        dead = pool._pollers[0]
        dead._stop.set()
        dead._thread.join(timeout=10)
        assert not dead.alive()
        pub = Publisher(
            f"inproc://dead-{run}-b", "pb", MODEL, bind=True,
            context=context,
        )
        try:
            channel = pool.attach(
                ChannelConfig(
                    endpoint=f"inproc://dead-{run}-b",
                    pod_identifier="pb",
                ),
                sink,
            )
            assert pool._pollers[0] is not dead, "dead poller not replaced"
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and "pb" not in received:
                pub.publish()
                time.sleep(0.02)
            with lock:
                assert "pb" in received, (
                    "attach after a poller crash never delivered"
                )
            pool.detach(channel)
            pool.detach(first)
        finally:
            pool.shutdown()
            pub.close()

    def test_kvevents_package_kvlint_clean(self):
        """The whole event plane stays kvlint-clean with an empty
        baseline (KV001-KV008, incl. the resource-leak rule over the
        poller's sockets/threads)."""
        import io
        import contextlib

        from hack.kvlint.__main__ import main as kvlint_main

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = kvlint_main(
                [
                    "llm_d_kv_cache_manager_tpu/kvevents",
                    "--no-baseline",
                    "--rules",
                    "KV001,KV003,KV004,KV005,KV008",
                ]
            )
        assert rc == 0, buf.getvalue()
