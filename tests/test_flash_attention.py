"""Flash (blockwise) attention vs the dense reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.ops.attention import causal_gqa_attention
from llm_d_kv_cache_manager_tpu.ops.flash_attention import flash_gqa_attention


def _qkv(key, B, Tq, Tk, H, Hkv, D):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, Tq, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, Tk, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, Tk, Hkv, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("q_block,kv_block", [(8, 8), (16, 4), (64, 64)])
def test_matches_dense_causal(q_block, kv_block):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 24, 24, 4, 2, 8)
    dense = causal_gqa_attention(q, k, v)
    flash = flash_gqa_attention(q, k, v, q_block=q_block, kv_block=kv_block)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_matches_dense_with_q_offset():
    """Continuation shape: short q attending over a longer key axis."""
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 8, 40, 4, 4, 8)
    dense = causal_gqa_attention(q, k, v, q_offset=32)
    flash = flash_gqa_attention(q, k, v, q_offset=32, q_block=4, kv_block=8)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_matches_dense_with_kv_len():
    q, k, v = _qkv(jax.random.PRNGKey(2), 3, 12, 16, 6, 2, 4)
    kv_len = jnp.asarray([16, 9, 3])
    dense = causal_gqa_attention(q, k, v, kv_len=kv_len)
    flash = flash_gqa_attention(q, k, v, kv_len=kv_len, q_block=4, kv_block=4)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_non_divisible_lengths_padded_internally():
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 13, 19, 2, 1, 8)
    dense = causal_gqa_attention(q, k, v)
    flash = flash_gqa_attention(q, k, v, q_block=8, kv_block=8)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_jit_and_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 32, 32, 4, 2, 8)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    fn = jax.jit(
        lambda q, k, v: flash_gqa_attention(q, k, v, q_block=16, kv_block=16)
    )
    out = fn(q, k, v)
    assert out.dtype == jnp.bfloat16
    dense = causal_gqa_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32),
        np.asarray(dense, np.float32),
        rtol=3e-2,
        atol=3e-2,
    )


# ----------------------- pallas kernel (interpret) ------------------------

from llm_d_kv_cache_manager_tpu.ops.flash_pallas import (  # noqa: E402
    flash_gqa_attention_pallas,
)


@pytest.mark.parametrize(
    "B,Tq,Tk,H,Hkv,D,q_offset",
    [
        (1, 512, 512, 4, 2, 64, 0),  # square causal, GQA
        (2, 256, 1280, 8, 4, 64, 1024),  # continuation
        (1, 300, 300, 4, 4, 128, 0),  # Tq not a q_block multiple
        (1, 128, 896, 4, 2, 64, 768),  # Tk not a kv_chunk multiple
    ],
)
def test_pallas_matches_dense(B, Tq, Tk, H, Hkv, D, q_offset):
    """The TPU kernel in interpreter mode vs the dense reference; the
    same code compiles on-chip (exercised by bench.py)."""
    q, k, v = _qkv(jax.random.PRNGKey(7), B, Tq, Tk, H, Hkv, D)
    q = q.astype(jnp.bfloat16)
    k = k.astype(jnp.bfloat16)
    v = v.astype(jnp.bfloat16)
    dense = causal_gqa_attention(q, k, v, q_offset=q_offset)
    got = flash_gqa_attention_pallas(
        q, k, v, q_offset=q_offset, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(dense, np.float32),
        rtol=0.05,
        atol=0.05,
    )


def test_pallas_pad_rows_are_finite():
    """Padded q rows (Tq % q_block != 0) must come back 0, not NaN —
    q_block=32 forces real padding (40 -> 64) and the padded rows'
    l==0 guard."""
    q, k, v = _qkv(jax.random.PRNGKey(8), 1, 40, 40, 2, 2, 64)
    got = flash_gqa_attention_pallas(
        q.astype(jnp.bfloat16),
        k.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16),
        q_block=32,
        interpret=True,
    )
    assert got.shape == q.shape
    assert bool(jnp.all(jnp.isfinite(got.astype(jnp.float32))))
    dense = causal_gqa_attention(
        q.astype(jnp.bfloat16),
        k.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16),
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(dense, np.float32),
        rtol=0.05,
        atol=0.05,
    )


def test_vmem_gate():
    from llm_d_kv_cache_manager_tpu.ops.flash_pallas import fits_vmem

    assert fits_vmem(8448, 128)  # the bench shape
    assert not fits_vmem(32768, 128)  # long-context falls back to scan
