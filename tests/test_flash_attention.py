"""Flash (blockwise) attention vs the dense reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.ops.attention import causal_gqa_attention
from llm_d_kv_cache_manager_tpu.ops.flash_attention import flash_gqa_attention


def _qkv(key, B, Tq, Tk, H, Hkv, D):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, Tq, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, Tk, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, Tk, Hkv, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("q_block,kv_block", [(8, 8), (16, 4), (64, 64)])
def test_matches_dense_causal(q_block, kv_block):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 24, 24, 4, 2, 8)
    dense = causal_gqa_attention(q, k, v)
    flash = flash_gqa_attention(q, k, v, q_block=q_block, kv_block=kv_block)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_matches_dense_with_q_offset():
    """Continuation shape: short q attending over a longer key axis."""
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 8, 40, 4, 4, 8)
    dense = causal_gqa_attention(q, k, v, q_offset=32)
    flash = flash_gqa_attention(q, k, v, q_offset=32, q_block=4, kv_block=8)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_matches_dense_with_kv_len():
    q, k, v = _qkv(jax.random.PRNGKey(2), 3, 12, 16, 6, 2, 4)
    kv_len = jnp.asarray([16, 9, 3])
    dense = causal_gqa_attention(q, k, v, kv_len=kv_len)
    flash = flash_gqa_attention(q, k, v, kv_len=kv_len, q_block=4, kv_block=4)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_non_divisible_lengths_padded_internally():
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 13, 19, 2, 1, 8)
    dense = causal_gqa_attention(q, k, v)
    flash = flash_gqa_attention(q, k, v, q_block=8, kv_block=8)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_jit_and_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 32, 32, 4, 2, 8)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    fn = jax.jit(
        lambda q, k, v: flash_gqa_attention(q, k, v, q_block=16, kv_block=16)
    )
    out = fn(q, k, v)
    assert out.dtype == jnp.bfloat16
    dense = causal_gqa_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32),
        np.asarray(dense, np.float32),
        rtol=3e-2,
        atol=3e-2,
    )
