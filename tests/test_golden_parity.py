"""Golden cross-implementation hash parity.

The four hashes below were precomputed by the *reference* Go indexer
(examples/testdata/data.go:32-37) for the Lorem-Ipsum prompt in
tests/testdata/golden/prompt.txt, tokenized with bert-base-uncased
(the tokenizer fixture checked into the reference e2e suite), chunked
into 256-token blocks and hashed with the chained canonical-CBOR +
FNV-64a pipeline.  Reproducing them here proves bit-equality of the
entire contract — tokenizer, special-token policy, chunking, CBOR
canonical form, FNV chain — with the reference implementation, closing
the "an agreeing bug in reading the algorithm would pass" gap that
self-derived vectors leave open.

A second set of tests verifies the canonical-CBOR encoder against an
independent, spec-written decoder (RFC 8949), so encoder bugs can't
hide behind their own output.
"""

import os
import struct

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    encode_canonical,
    encode_hash_payload,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    InMemoryIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")
PROMPT_PATH = os.path.join(TESTDATA, "golden", "prompt.txt")
TOKENIZERS_DIR = os.path.join(TESTDATA, "tokenizers")

MODEL = "bert-base-uncased"
BLOCK_SIZE = 256  # reference examples/kv_cache_index/main.go

# Reference examples/testdata/data.go:32-37 (PromptHashes).
GOLDEN_HASHES = [
    3246512376769953277,
    2932514196368075983,
    6384763183060574933,
    13975137892230421288,
]
# The prompt is 1309 tokens incl. [CLS]/[SEP]: 5 full 256-token blocks,
# so the golden values pin the first 4 links of a 5-link chain.
GOLDEN_TOKEN_COUNT = 1309


def load_prompt() -> str:
    with open(PROMPT_PATH, encoding="utf-8") as f:
        return f.read()


def tokenize_prompt() -> list:
    tokenizer = LocalFastTokenizer(TOKENIZERS_DIR)
    return tokenizer.encode(load_prompt(), MODEL, True).tokens


class TestGoldenChain:
    def test_tokenizer_fixture_reproduces_reference_tokens(self):
        tokens = tokenize_prompt()
        assert len(tokens) == GOLDEN_TOKEN_COUNT
        # bert special-token framing, as the reference's non-chat path
        # encodes (addSpecialToken=true).
        assert tokens[0] == 101  # [CLS]
        assert tokens[-1] == 102  # [SEP]

    def test_chain_reproduces_reference_prompt_hashes(self):
        tokens = tokenize_prompt()
        db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=BLOCK_SIZE, hash_seed=""),
            use_native=False,
        )
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        assert keys[: len(GOLDEN_HASHES)] == GOLDEN_HASHES

    def test_native_chain_matches_golden(self):
        db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=BLOCK_SIZE, hash_seed=""),
            use_native=True,
        )
        if db._native_chain is None:
            pytest.skip("native engine unavailable")
        tokens = tokenize_prompt()
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        assert keys[: len(GOLDEN_HASHES)] == GOLDEN_HASHES

    def test_indexer_read_path_scores_golden_blocks(self):
        """Mirror reference examples/kv_cache_index/main.go: seed the index
        with the golden hashes as engine==request keys for one pod, then
        score the golden prompt through the full read path."""
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE, hash_seed=""
                ),
                kvblock_index_config=IndexConfig(
                    in_memory_config=InMemoryIndexConfig(size=10_000)
                ),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=2, model_name=MODEL
                ),
            ),
            tokenizer=LocalFastTokenizer(TOKENIZERS_DIR),
        )
        indexer.run()
        try:
            prompt = load_prompt()
            assert indexer.get_pod_scores(prompt, MODEL) == {}

            indexer.kv_block_index.add(
                GOLDEN_HASHES,
                GOLDEN_HASHES,
                [PodEntry("pod1", "gpu")],
            )
            scores = indexer.get_pod_scores(prompt, MODEL)
            # 4 consecutive prefix blocks at gpu-tier weight 1.0.
            assert scores == {"pod1": 4.0}
        finally:
            indexer.shutdown()


# --- Independent CBOR verification ----------------------------------------


def decode_cbor(data: bytes):
    """Minimal independent RFC 8949 decoder for the payload's type subset.

    Written from the spec (not from the encoder) so a shared misreading
    would have to be made twice, in two different directions.  Returns
    the decoded value and asserts *canonical* heads: rejects any
    argument that could have been encoded shorter.
    """

    def head(off):
        ib = data[off]
        major, info = ib >> 5, ib & 0x1F
        if info < 24:
            return major, info, off + 1
        if info == 24:
            val = data[off + 1]
            assert val >= 24, "non-canonical 1-byte head"
            return major, val, off + 2
        if info == 25:
            (val,) = struct.unpack_from(">H", data, off + 1)
            assert val > 0xFF, "non-canonical 2-byte head"
            return major, val, off + 3
        if info == 26:
            (val,) = struct.unpack_from(">I", data, off + 1)
            assert val > 0xFFFF, "non-canonical 4-byte head"
            return major, val, off + 5
        if info == 27:
            (val,) = struct.unpack_from(">Q", data, off + 1)
            assert val > 0xFFFFFFFF, "non-canonical 8-byte head"
            return major, val, off + 9
        raise AssertionError(f"indefinite/reserved head {info}")

    def item(off):
        ib = data[off]
        if ib == 0xF6:
            return None, off + 1
        if ib == 0xF5:
            return True, off + 1
        if ib == 0xF4:
            return False, off + 1
        major, arg, off = head(off)
        if major == 0:
            return arg, off
        if major == 1:
            return -1 - arg, off
        if major == 2:
            return data[off : off + arg], off + arg
        if major == 3:
            return data[off : off + arg].decode("utf-8"), off + arg
        if major == 4:
            out = []
            for _ in range(arg):
                value, off = item(off)
                out.append(value)
            return out, off
        raise AssertionError(f"unexpected major type {major}")

    value, consumed = item(0)
    assert consumed == len(data), "trailing bytes after CBOR item"
    return value


class TestCanonicalCBOR:
    BOUNDARY_INTS = [
        0, 1, 23, 24, 25, 0xFF, 0x100, 0xFFFF, 0x10000,
        0xFFFFFFFF, 0x100000000, 0xFFFFFFFFFFFFFFFF,
    ]

    def test_payload_roundtrips_through_independent_decoder(self):
        for parent in self.BOUNDARY_INTS:
            payload = encode_hash_payload(parent, [0, 23, 24, 70000], None)
            assert decode_cbor(payload) == [parent, [0, 23, 24, 70000], None]

    def test_nil_tokens_encode_as_null(self):
        payload = encode_hash_payload(5, None, "model")
        assert decode_cbor(payload) == [5, None, "model"]

    def test_boundary_values_roundtrip(self):
        for value in self.BOUNDARY_INTS:
            assert decode_cbor(encode_canonical(value)) == value
        for value in [-1, -24, -25, -256, -257]:
            assert decode_cbor(encode_canonical(value)) == value
        assert decode_cbor(encode_canonical("héllo")) == "héllo"
        assert decode_cbor(encode_canonical(b"\x00\xff")) == b"\x00\xff"
        assert decode_cbor(encode_canonical([True, False, None])) == [
            True,
            False,
            None,
        ]

    def test_known_spec_bytes(self):
        """Hand-checked byte strings from RFC 8949 appendix A examples."""
        assert encode_canonical(0) == bytes.fromhex("00")
        assert encode_canonical(23) == bytes.fromhex("17")
        assert encode_canonical(24) == bytes.fromhex("1818")
        assert encode_canonical(1000) == bytes.fromhex("1903e8")
        assert encode_canonical(1000000) == bytes.fromhex("1a000f4240")
        assert encode_canonical(1000000000000) == bytes.fromhex(
            "1b000000e8d4a51000"
        )
        assert encode_canonical(-1) == bytes.fromhex("20")
        assert encode_canonical(-1000) == bytes.fromhex("3903e7")
        assert encode_canonical("IETF") == bytes.fromhex("6449455446")
        assert encode_canonical([1, [2, 3], [4, 5]]) == bytes.fromhex(
            "8301820203820405"
        )
