"""gRPC API layer: indexer scoring service + tokenizer sidecar.

Covers the reference's api/ surface (indexer.proto, tokenizer.proto)
end-to-end over real grpcio channels on Unix-domain sockets: score
round-trips, sidecar tokenize/render/init, the UDS client backend, and
the Value kwargs codec.
"""

import os

import pytest

from llm_d_kv_cache_manager_tpu.api import indexer_pb2, tokenizer_pb2
from llm_d_kv_cache_manager_tpu.api.grpc_services import (
    python_to_value,
    value_to_python,
)
from llm_d_kv_cache_manager_tpu.api.indexer_service import new_client, serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.services.uds_tokenizer import (
    TokenizerRegistry,
)
from llm_d_kv_cache_manager_tpu.services.uds_tokenizer import (
    serve as serve_tokenizer,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from llm_d_kv_cache_manager_tpu.tokenization.uds_tokenizer import UdsTokenizer
from tests.helpers.tiny_tokenizer import (
    build_transformers_tokenizer,
    save_tokenizer_json,
)

MODEL = "test-model"
BLOCK_SIZE = 4
PROMPT = "the quick brown fox jumps over the lazy dog"


@pytest.fixture()
def indexer(tmp_path):
    tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    yield indexer
    indexer.shutdown()


def seed_index(indexer, prompt, pod):
    """Store the prompt's block chain for a pod, bypassing events."""
    tokens = indexer.tokenization_pool.tokenize(prompt, MODEL, None)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        EMPTY_BLOCK_HASH, tokens, MODEL
    )
    indexer.kv_block_index.add(keys, keys, [PodEntry(pod, "hbm")])
    return keys


@pytest.fixture()
def scoring_endpoint(indexer, tmp_path):
    uds = os.path.join(str(tmp_path), "indexer.sock")
    server = serve(indexer, f"unix://{uds}")
    yield indexer, f"unix://{uds}"
    server.stop(grace=None)


class TestIndexerService:
    def test_score_round_trip(self, scoring_endpoint):
        indexer, address = scoring_endpoint
        seed_index(indexer, PROMPT, "pod-a")
        client = new_client(address)
        response = client.GetPodScores(
            indexer_pb2.GetPodScoresRequest(
                prompt=PROMPT,
                model_name=MODEL,
                pod_identifiers=["pod-a", "pod-b"],
            )
        )
        scores = {s.pod: s.score for s in response.scores}
        assert scores["pod-a"] > 0
        assert "pod-b" not in scores or scores["pod-b"] == 0

    def test_empty_index_scores_nothing(self, scoring_endpoint):
        _, address = scoring_endpoint
        client = new_client(address)
        response = client.GetPodScores(
            indexer_pb2.GetPodScoresRequest(
                prompt=PROMPT, model_name=MODEL
            )
        )
        assert len(response.scores) == 0

    def test_scores_sorted_descending(self, scoring_endpoint):
        indexer, address = scoring_endpoint
        # pod-a holds the full chain, pod-b only the first block.
        keys = seed_index(indexer, PROMPT, "pod-a")
        indexer.kv_block_index.add(
            keys[:1], keys[:1], [PodEntry("pod-b", "hbm")]
        )
        client = new_client(address)
        response = client.GetPodScores(
            indexer_pb2.GetPodScoresRequest(
                prompt=PROMPT, model_name=MODEL
            )
        )
        values = [s.score for s in response.scores]
        assert values == sorted(values, reverse=True)
        assert response.scores[0].pod == "pod-a"


@pytest.fixture()
def tokenizer_sidecar(tmp_path):
    registry = TokenizerRegistry()
    registry.register(MODEL, build_transformers_tokenizer())
    uds = os.path.join(str(tmp_path), "tokenizer.sock")
    server = serve_tokenizer(uds, max_workers=2, registry=registry)
    yield uds
    server.stop(grace=None)


class TestTokenizerSidecar:
    def test_tokenize_with_offsets(self, tokenizer_sidecar):
        client = UdsTokenizer(tokenizer_sidecar)
        encoding = client.encode(PROMPT, MODEL, add_special_tokens=False)
        assert len(encoding.tokens) == len(PROMPT.split())
        assert len(encoding.offsets) == len(encoding.tokens)
        # Offsets index into the prompt at word boundaries.
        start, end = encoding.offsets[1]
        assert PROMPT[start:end] == "quick"
        client.close()

    def test_matches_local_backend(self, tokenizer_sidecar, tmp_path):
        local_dir = save_tokenizer_json(str(tmp_path / "local"), MODEL)
        local = LocalFastTokenizer(local_dir)
        client = UdsTokenizer(tokenizer_sidecar)
        via_uds = client.encode(PROMPT, MODEL, add_special_tokens=False)
        via_local = local.encode(PROMPT, MODEL, add_special_tokens=False)
        assert via_uds.tokens == via_local.tokens
        assert via_uds.offsets == via_local.offsets
        client.close()

    def test_initialize_and_render(self, tokenizer_sidecar):
        client = UdsTokenizer(tokenizer_sidecar)
        client.initialize_model(MODEL)

        request = tokenizer_pb2.ChatTemplateRequest(
            model_name=MODEL, add_generation_prompt=True
        )
        turn = request.conversation_turns.add()
        turn.messages.add(role="user", content="hello world")
        response = client._stub.RenderChatTemplate(request)
        assert response.success
        assert "<|user|> hello world" in response.rendered_prompt
        assert response.rendered_prompt.endswith("<|assistant|>")
        client.close()

    def test_multi_turn_render(self, tokenizer_sidecar):
        client = UdsTokenizer(tokenizer_sidecar)
        request = tokenizer_pb2.ChatTemplateRequest(
            model_name=MODEL, add_generation_prompt=True
        )
        for role, content in (
            ("user", "hello"),
            ("assistant", "world"),
            ("user", "again"),
        ):
            turn = request.conversation_turns.add()
            turn.messages.add(role=role, content=content)
        response = client._stub.RenderChatTemplate(request)
        assert response.success, response.error_message
        assert "<|user|> hello" in response.rendered_prompt
        assert "<|assistant|> world" in response.rendered_prompt
        assert response.rendered_prompt.endswith("<|assistant|>")
        client.close()

    def test_unknown_model_reports_error(self, tokenizer_sidecar):
        client = UdsTokenizer(tokenizer_sidecar)
        request = tokenizer_pb2.TokenizeRequest(
            input="x", model_name="no/such-model-xyz"
        )
        response = client._stub.Tokenize(request)
        assert not response.success
        assert response.error_message
        client.close()


class TestValueCodec:
    def test_round_trip(self):
        payload = {
            "name": "tool",
            "depth": 3,
            "ratio": 0.5,
            "flag": True,
            "items": ["a", 1, False],
            "nested": {"k": "v"},
            "absent": None,
            "empty": {},
        }
        assert value_to_python(python_to_value(payload)) == payload

    def test_int_float_distinction_survives_roundtrip(self):
        """{"temperature": 2.0} must arrive as float 2.0 (not int 2) so
        sidecar and in-process Jinja rendering agree; ints travel on the
        distinct int_value encoding."""
        as_float = value_to_python(python_to_value(2.0))
        assert as_float == 2.0 and isinstance(as_float, float)
        as_int = value_to_python(python_to_value(2))
        assert as_int == 2 and isinstance(as_int, int)
        assert value_to_python(python_to_value(-(2**40))) == -(2**40)

    def test_number_value_stays_float(self):
        value = tokenizer_pb2.Value(number_value=7.0)
        assert value_to_python(value) == 7.0
        assert isinstance(value_to_python(value), float)


class TestUdsInIndexerConfig:
    def test_composite_includes_uds_backend(self, tokenizer_sidecar):
        indexer = Indexer(
            IndexerConfig(
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=1, model_name=MODEL
                ),
                uds_tokenizer_path=tokenizer_sidecar,
            )
        )
        names = indexer.tokenization_pool._tokenizer.type()
        assert "uds" in names
        indexer.shutdown()


class TestWireRobustness:
    """Garbage bytes on the wire method path must yield an error status
    (grpc deserialization failure), never kill the server."""

    def test_garbage_request_bytes_then_valid_call(self, scoring_endpoint):
        import random

        import grpc

        from llm_d_kv_cache_manager_tpu.api.grpc_services import (
            INDEXER_SERVICE,
        )

        indexer, endpoint = scoring_endpoint
        seed_index(indexer, PROMPT, "pod-a")
        rng = random.Random(0)
        channel = grpc.insecure_channel(endpoint)
        raw = channel.unary_unary(
            f"/{INDEXER_SERVICE}/GetPodScores",
            request_serializer=lambda b: b,  # send bytes verbatim
            response_deserializer=lambda b: b,
        )
        for _ in range(20):
            with pytest.raises(grpc.RpcError) as err:
                raw(rng.randbytes(rng.randint(1, 64)), timeout=10)
            assert err.value.code() in (
                grpc.StatusCode.INTERNAL,
                grpc.StatusCode.INVALID_ARGUMENT,
                grpc.StatusCode.UNKNOWN,
            )
        channel.close()

        client = new_client(endpoint)
        response = client.GetPodScores(
            indexer_pb2.GetPodScoresRequest(
                prompt=PROMPT, model_name=MODEL, pod_identifiers=["pod-a"]
            )
        )
        scores = {s.pod: s.score for s in response.scores}
        assert scores["pod-a"] > 0
