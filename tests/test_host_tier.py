"""Host-DRAM tier: LRU budget, load fast path, tiered events."""

import os

import numpy as np

from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import (
    KVCachePool,
    KVCachePoolConfig,
)
from llm_d_kv_cache_manager_tpu.native.engine import JobStatus
from llm_d_kv_cache_manager_tpu.offload.host_tier import HostTierCache
from llm_d_kv_cache_manager_tpu.offload.spec import (
    TPUOffloadConnector,
    TPUOffloadSpec,
)
from llm_d_kv_cache_manager_tpu.offload.worker import (
    group_blocks_per_file,
    host_dtype,
)

POOL = KVCachePoolConfig(
    num_layers=2,
    num_blocks=16,
    block_size=4,
    num_kv_heads=2,
    head_dim=8,
    dtype="bfloat16",
)


class TestHostTierCache:
    def test_put_get_refresh(self):
        cache = HostTierCache(max_bytes=1 << 20)
        group = np.ones((2, 8), np.uint8)
        cache.put(1, group)
        assert cache.get(1) is group
        assert cache.get(2) is None
        assert cache.stats()["hits"] == 1
        assert cache.stats()["misses"] == 1

    def test_budget_evicts_lru(self):
        cache = HostTierCache(max_bytes=100)
        a, b, c = (np.zeros(40, np.uint8) for _ in range(3))
        cache.put(1, a)
        cache.put(2, b)
        cache.get(1)  # refresh 1; 2 becomes LRU
        cache.put(3, c)
        assert cache.get(2) is None
        assert cache.get(1) is not None
        assert cache.get(3) is not None
        assert cache.resident_bytes <= 100

    def test_oversized_group_not_admitted(self):
        cache = HostTierCache(max_bytes=10)
        cache.put(1, np.zeros(100, np.uint8))
        assert cache.get(1) is None

    def test_lookup_consecutive(self):
        cache = HostTierCache()
        for h in (1, 2, 4):
            cache.put(h, np.zeros(4, np.uint8))
        assert cache.lookup_consecutive([1, 2, 3, 4]) == 2
        assert cache.lookup_consecutive([5]) == 0


def make_connector(tmp_path, pool, host_cache_bytes, events=None):
    return TPUOffloadConnector(
        TPUOffloadSpec(
            shared_storage_path=str(tmp_path),
            model_name="test/host-tier",
            device_block_size=POOL.block_size,
            offloaded_block_size=POOL.block_size * 2,
            threads_per_chip=2,
            host_cache_bytes=host_cache_bytes,
        ),
        pool,
        event_sink=(
            (lambda h, m: events.append((list(h), m)))
            if events is not None
            else None
        ),
    )


class TestTieredOffload:
    def test_load_served_from_host_tier_without_files(self, tmp_path):
        """After a store, a load must succeed even if the shared-storage
        files are deleted — the group is host-resident."""
        pool = KVCachePool(POOL)
        events = []
        conn = make_connector(tmp_path, pool, 64 << 20, events)
        rng = np.random.default_rng(0)
        n = 4
        ref = rng.standard_normal(
            (POOL.num_layers, n, 2, POOL.block_size, POOL.num_kv_heads,
             POOL.head_dim)
        ).astype(host_dtype(POOL.dtype))
        pool.scatter_from_host(list(range(n)), ref)

        hashes = [0x11, 0x22]
        groups = group_blocks_per_file(hashes, list(range(n)), 2)
        conn.store_handler.transfer_async(1, groups)
        assert conn.store_handler.wait(1) == JobStatus.SUCCEEDED
        # Tiered events: host immediately, shared_storage on landing.
        assert (hashes, "host") in events
        assert (hashes, "shared_storage") in events

        # Remove the durable copies; wipe the pool; reload.
        for h in hashes:
            os.unlink(conn.file_mapper.get_file_name(h))
        pool.scatter_from_host(list(range(n)), np.zeros_like(ref))
        load_groups = group_blocks_per_file(hashes, [9, 8, 7, 6], 2)
        conn.load_handler.transfer_async(2, load_groups)
        assert conn.load_handler.wait(2) == JobStatus.SUCCEEDED
        back = pool.gather_to_host([9, 8, 7, 6])
        np.testing.assert_array_equal(
            np.asarray(back, np.float32), np.asarray(ref, np.float32)
        )
        assert conn.host_cache.stats()["hits"] == 2

    def test_miss_falls_back_to_files(self, tmp_path):
        pool = KVCachePool(POOL)
        conn = make_connector(tmp_path, pool, 64 << 20)
        rng = np.random.default_rng(1)
        ref = rng.standard_normal(
            (POOL.num_layers, 2, 2, POOL.block_size, POOL.num_kv_heads,
             POOL.head_dim)
        ).astype(host_dtype(POOL.dtype))
        pool.scatter_from_host([0, 1], ref)
        groups = group_blocks_per_file([0x33], [0, 1], 2)
        conn.store_handler.transfer_async(1, groups)
        assert conn.store_handler.wait(1) == JobStatus.SUCCEEDED

        conn.host_cache.evict(0x33)  # force the file path
        pool.scatter_from_host([0, 1], np.zeros_like(ref))
        conn.load_handler.transfer_async(2, groups)
        assert conn.load_handler.wait(2) == JobStatus.SUCCEEDED
        back = pool.gather_to_host([0, 1])
        np.testing.assert_array_equal(
            np.asarray(back, np.float32), np.asarray(ref, np.float32)
        )

    def test_disabled_tier_unchanged_behavior(self, tmp_path):
        pool = KVCachePool(POOL)
        events = []
        conn = make_connector(tmp_path, pool, 0, events)
        assert conn.host_cache is None
        groups = group_blocks_per_file([0x44], [0, 1], 2)
        conn.store_handler.transfer_async(1, groups)
        assert conn.store_handler.wait(1) == JobStatus.SUCCEEDED
        assert [m for _, m in events] == ["shared_storage"]
