"""HTTP scoring service round trips (reference: examples/kv_events/online)."""

import json
import urllib.error
import urllib.request

import pytest

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import (
    build_transformers_tokenizer,
    save_tokenizer_json,
)

MODEL = "test-model"
PROMPT = "the quick brown fox jumps over the lazy dog"


@pytest.fixture()
def service(tmp_path):
    tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.chat_processor.register_tokenizer(
        MODEL, build_transformers_tokenizer()
    )
    indexer.run()
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    yield indexer, base
    server.shutdown()
    indexer.shutdown()


def post(base, path, obj):
    request = urllib.request.Request(
        base + path,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=10) as response:
        return response.status, json.load(response)


def seed(indexer, prompt, pod):
    tokens = indexer.tokenization_pool.tokenize(prompt, MODEL, None)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        EMPTY_BLOCK_HASH, tokens, MODEL
    )
    indexer.kv_block_index.add(keys, keys, [PodEntry(pod, "hbm")])


class TestHTTPService:
    def test_score_completions(self, service):
        indexer, base = service
        seed(indexer, PROMPT, "pod-a")
        status, scores = post(
            base,
            "/score_completions",
            {"prompt": PROMPT, "model": MODEL},
        )
        assert status == 200
        assert scores["pod-a"] > 0

    def test_score_chat_completions(self, service):
        indexer, base = service
        rendered = "<|user|> hello world <|assistant|>"
        seed(indexer, rendered, "pod-chat")
        status, scores = post(
            base,
            "/score_chat_completions",
            {
                "model": MODEL,
                "messages": [{"role": "user", "content": "hello world"}],
            },
        )
        assert status == 200
        assert scores.get("pod-chat", 0) > 0

    def test_missing_prompt_400(self, service):
        _, base = service
        with pytest.raises(urllib.error.HTTPError) as err:
            post(base, "/score_completions", {"model": MODEL})
        assert err.value.code == 400

    def test_metrics_and_healthz(self, service):
        indexer, base = service
        seed(indexer, PROMPT, "pod-a")
        post(base, "/score_completions", {"prompt": PROMPT, "model": MODEL})
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            body = resp.read().decode()
        assert "kvtpu_kvcache_index_lookup_requests_total" in body
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            assert json.load(resp)["status"] == "ok"

    def test_unknown_path_404(self, service):
        _, base = service
        with pytest.raises(urllib.error.HTTPError) as err:
            post(base, "/nope", {})
        assert err.value.code == 404

    def test_admin_purge_pod(self, service):
        indexer, base = service
        other_prompt = "pack my box with five dozen liquor jugs"
        seed(indexer, PROMPT, "pod-a")
        seed(indexer, other_prompt, "pod-b")
        status, body = post(base, "/admin/purge_pod", {"pod": "pod-a"})
        assert status == 200 and body["removed"] > 0
        status, scores = post(
            base, "/score_completions", {"prompt": PROMPT, "model": MODEL}
        )
        assert "pod-a" not in scores
        # Isolation: pod-b's entries survive the purge and still score.
        status, scores = post(
            base,
            "/score_completions",
            {"prompt": other_prompt, "model": MODEL},
        )
        assert scores.get("pod-b", 0) > 0

    def test_admin_purge_pod_requires_pod(self, service):
        _, base = service
        with pytest.raises(urllib.error.HTTPError) as err:
            post(base, "/admin/purge_pod", {})
        assert err.value.code == 400

    def test_non_object_json_body_is_400(self, service):
        """`null`/arrays are valid JSON; without the dict check the
        handler would hang the keep-alive connection (no response) or
        crash it mid-request."""
        _, base = service
        for body in (None, [1, 2], "x"):
            with pytest.raises(urllib.error.HTTPError) as err:
                post(base, "/admin/purge_pod", body)
            assert err.value.code == 400

    def test_admin_token_gate(self, tmp_path):
        """With ADMIN_TOKEN configured, /admin/* requires the bearer
        token even from loopback; scoring stays open."""
        from llm_d_kv_cache_manager_tpu.api.http_service import serve

        tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=2, model_name=MODEL
                ),
            ),
            tokenizer=LocalFastTokenizer(tokenizer_dir),
        )
        indexer.run()
        server = serve(
            indexer, host="127.0.0.1", port=0, admin_token="s3cret"
        )
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                post(base, "/admin/purge_pod", {"pod": "pod-a"})
            assert err.value.code == 403
            request = urllib.request.Request(
                base + "/admin/purge_pod",
                data=json.dumps({"pod": "pod-a"}).encode(),
                headers={
                    "Content-Type": "application/json",
                    "Authorization": "Bearer s3cret",
                },
                method="POST",
            )
            with urllib.request.urlopen(request, timeout=10) as resp:
                assert resp.status == 200
            # Scoring needs no token.
            status, _ = post(
                base,
                "/score_completions",
                {"prompt": PROMPT, "model": MODEL},
            )
            assert status == 200
        finally:
            server.shutdown()
            indexer.shutdown()

    def test_admin_snapshot_without_persistence_503(self, service):
        _, base = service
        with pytest.raises(urllib.error.HTTPError) as err:
            post(base, "/admin/snapshot", {})
        assert err.value.code == 503


class TestPersistenceEndpoints:
    @pytest.fixture()
    def persistent_service(self, tmp_path):
        from llm_d_kv_cache_manager_tpu.api.http_service import serve
        from llm_d_kv_cache_manager_tpu.persistence import (
            PersistenceConfig,
            PersistenceManager,
        )

        tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=2, model_name=MODEL
                ),
            ),
            tokenizer=LocalFastTokenizer(tokenizer_dir),
        )
        indexer.run()
        manager = PersistenceManager(
            PersistenceConfig(directory=str(tmp_path / "state"))
        )
        report = manager.recover(indexer.kv_block_index)
        server = serve(
            indexer,
            host="127.0.0.1",
            port=0,
            persistence=manager,
            recovery_report=report,
        )
        base = f"http://127.0.0.1:{server.server_address[1]}"
        yield indexer, manager, base
        server.shutdown()
        manager.close()
        indexer.shutdown()

    def test_healthz_reports_recovery_and_persistence(
        self, persistent_service
    ):
        indexer, manager, base = persistent_service
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            health = json.load(resp)
        assert health["status"] == "ok"
        assert health["recovery"]["status"] == "cold"  # empty dir
        assert health["persistence"]["snapshot_path"] is None

    def test_admin_snapshot_publishes_and_updates_healthz(
        self, persistent_service
    ):
        indexer, manager, base = persistent_service
        seed(indexer, PROMPT, "pod-a")
        status, body = post(base, "/admin/snapshot", {})
        assert status == 200
        assert body["block_keys"] > 0
        assert body["path"].endswith(".snap")
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            health = json.load(resp)
        assert health["persistence"]["snapshot_path"] == body["path"]
        assert health["persistence"]["snapshot_age_s"] is not None

    def test_snapshot_then_recover_round_trip(self, persistent_service):
        """The service-level warm restart: snapshot via the admin
        endpoint, recover into a fresh indexer, identical scores."""
        indexer, manager, base = persistent_service
        seed(indexer, PROMPT, "pod-a")
        post(base, "/admin/snapshot", {})
        from llm_d_kv_cache_manager_tpu.persistence import recover

        restored = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
            ),
            tokenizer=indexer.tokenization_pool._tokenizer,
        )
        report = recover(restored.kv_block_index, manager.config)
        assert report.status == "warm"
        tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, MODEL
        )
        assert restored.kv_block_index.lookup(
            keys
        ) == indexer.kv_block_index.lookup(keys)
        restored.shutdown()
