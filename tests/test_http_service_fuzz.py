"""Seeded fuzz of the HTTP scoring service: the server must answer every
request with a well-formed HTTP status (2xx-5xx) and keep serving —
garbage bodies, type-confused fields, hostile Content-Length headers, and
random paths must never wedge a handler thread or kill the listener.

Complements test_http_service.py's example-based cases the same way
test_kvevents_fuzz.py complements test_kvevents.py.
"""

import http.client
import json
import random

import pytest

from llm_d_kv_cache_manager_tpu.api.http_service import (
    MAX_BODY_BYTES,
    serve,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json

MODEL = "test-model"
PATHS = [
    "/score_completions",
    "/score_chat_completions",
    "/admin/purge_pod",
    "/metrics",
    "/healthz",
    "/nope",
]


@pytest.fixture()
def service(tmp_path):
    tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    server = serve(indexer, host="127.0.0.1", port=0)
    yield server.server_address[1]
    server.shutdown()
    indexer.shutdown()


def _request(port, method, path, body=b"", headers=None):
    """One raw request; returns the status, or raises on a dropped
    connection (the failure mode the fuzz exists to rule out)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        response = conn.getresponse()
        response.read()
        return response.status
    finally:
        conn.close()


def _random_json(rng: random.Random, depth=0):
    kinds = ["int", "str", "none", "float", "bool", "list", "dict"]
    if depth >= 3:
        kinds = kinds[:5]
    kind = rng.choice(kinds)
    if kind == "int":
        return rng.randint(-(2**40), 2**40)
    if kind == "str":
        return rng.choice(["", "x", "prompt", "pods", "model", " "])
    if kind == "none":
        return None
    if kind == "float":
        return rng.random()
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "list":
        return [_random_json(rng, depth + 1) for _ in range(rng.randint(0, 4))]
    keys = ["prompt", "model", "pods", "messages", "tools", "pod", "x"]
    return {
        rng.choice(keys): _random_json(rng, depth + 1)
        for _ in range(rng.randint(0, 5))
    }


class TestHTTPFuzz:
    def test_random_bodies_always_answered(self, service):
        port = service
        rng = random.Random(0)
        for _ in range(60):
            path = rng.choice(PATHS)
            if rng.random() < 0.5:
                body = json.dumps(_random_json(rng)).encode()
            else:
                body = rng.randbytes(rng.randint(0, 64))
            status = _request(
                port,
                rng.choice(["POST", "GET"]),
                path,
                body=body,
                headers={"Content-Type": "application/json"},
            )
            assert 200 <= status < 600

    def test_hostile_content_length(self, service):
        port = service
        body = b'{"prompt": "x"}'
        for bad in ["-1", "-99999", "notanint", str(MAX_BODY_BYTES + 1)]:
            status = _request(
                port,
                "POST",
                "/score_completions",
                body=body,
                headers={"Content-Length": bad},
            )
            assert status in (400, 413), f"Content-Length {bad}: {status}"

    def test_rejected_body_does_not_desync_keepalive(self, service):
        """An unread body on a keep-alive connection must not be parsed
        as the next request line: the server closes the connection after
        rejecting.  A follow-up on the same socket either fails (closed)
        or — never — returns 501 for the garbage 'method'."""
        port = service
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request(
                "POST",
                "/score_completions",
                body=b"A" * 64,
                headers={"Content-Length": str(MAX_BODY_BYTES + 1)},
            )
            response = conn.getresponse()
            response.read()
            assert response.status == 413
            try:
                conn.request("GET", "/healthz")
                response = conn.getresponse()
                response.read()
                assert response.status == 200
            except (http.client.HTTPException, ConnectionError, OSError):
                pass  # server dropped the desynced connection: correct
        finally:
            conn.close()

    def test_server_alive_after_fuzz(self, service):
        port = service
        rng = random.Random(1)
        for _ in range(30):
            _request(
                port,
                "POST",
                rng.choice(PATHS),
                body=rng.randbytes(rng.randint(0, 32)),
            )
        status = _request(
            port,
            "POST",
            "/score_completions",
            body=json.dumps({"prompt": "hello world", "model": MODEL}).encode(),
            headers={"Content-Type": "application/json"},
        )
        assert status == 200
