"""Seeded fuzz of the HTTP scoring service: the server must answer every
request with a well-formed HTTP status (2xx-5xx) and keep serving —
garbage bodies, type-confused fields, hostile Content-Length headers, and
random paths must never wedge a handler thread or kill the listener.

Complements test_http_service.py's example-based cases the same way
test_kvevents_fuzz.py complements test_kvevents.py.
"""

import http.client
import json
import random

import pytest

from llm_d_kv_cache_manager_tpu.api.http_service import (
    MAX_BODY_BYTES,
    serve,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json

MODEL = "test-model"
PATHS = [
    "/score_completions",
    "/score_chat_completions",
    "/admin/purge_pod",
    "/metrics",
    "/healthz",
    "/nope",
]


@pytest.fixture()
def service(tmp_path):
    tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    server = serve(indexer, host="127.0.0.1", port=0)
    yield server.server_address[1]
    server.shutdown()
    indexer.shutdown()


def _request(port, method, path, body=b"", headers=None):
    """One raw request; returns the status, or None on a dropped
    connection.  A drop is a DESIGNED outcome for requests whose body
    the server never consumes (close-with-unread-data RSTs on Linux
    and can race away the queued error reply); callers that require an
    answer assert the status is not None."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        response = conn.getresponse()
        response.read()
        return response.status
    except (http.client.HTTPException, ConnectionError, OSError):
        return None
    finally:
        conn.close()


def _random_json(rng: random.Random, depth=0):
    kinds = ["int", "str", "none", "float", "bool", "list", "dict"]
    if depth >= 3:
        kinds = kinds[:5]
    kind = rng.choice(kinds)
    if kind == "int":
        return rng.randint(-(2**40), 2**40)
    if kind == "str":
        return rng.choice(["", "x", "prompt", "pods", "model", " "])
    if kind == "none":
        return None
    if kind == "float":
        return rng.random()
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "list":
        return [_random_json(rng, depth + 1) for _ in range(rng.randint(0, 4))]
    keys = ["prompt", "model", "pods", "messages", "tools", "pod", "x"]
    return {
        rng.choice(keys): _random_json(rng, depth + 1)
        for _ in range(rng.randint(0, 5))
    }


class TestHTTPFuzz:
    def test_random_bodies_always_answered(self, service):
        port = service
        rng = random.Random(0)
        post_routes = (
            "/score_completions",
            "/score_chat_completions",
            "/admin/purge_pod",
        )
        for _ in range(60):
            path = rng.choice(PATHS)
            if rng.random() < 0.5:
                body = json.dumps(_random_json(rng)).encode()
            else:
                body = rng.randbytes(rng.randint(0, 64))
            method = rng.choice(["POST", "GET"])
            status = _request(
                port,
                method,
                path,
                body=body,
                headers={"Content-Type": "application/json"},
            )
            if body and (method == "GET" or path not in post_routes):
                # A drop (None) is designed ONLY for requests whose
                # declared body may go unconsumed (404 paths,
                # GET-with-body): close-with-unread-data RSTs can race
                # away the reply.
                assert status is None or 200 <= status < 600
            else:
                # POSTs to real routes consume their body (loopback
                # passes the admin gate) and bodyless requests declare
                # nothing: no legitimate drop — the server must
                # answer, or the suite has lost the always-answered
                # regression it exists to rule out.
                assert status is not None and 200 <= status < 600
        # Liveness canary: whatever the fuzz provoked, a clean request
        # afterwards must still be answered.
        assert _request(port, "GET", "/healthz") == 200

    def test_hostile_content_length(self, service):
        port = service
        body = b'{"prompt": "x"}'
        # '+15', '1_5' and ' 15 ' are accepted by Python's liberal
        # int() but are corrupted headers under the strict digit
        # grammar (same policy as RespClient._parse_int).
        for bad in [
            "-1",
            "-99999",
            "notanint",
            "+15",
            "1_5",
            " 15 ",
            "0x10",
            str(MAX_BODY_BYTES + 1),
            # Past CPython's ~4300-digit str->int limit: must be
            # rejected by the digit-count bound, not crash the handler.
            "1" * 5000,
        ]:
            status = _request(
                port,
                "POST",
                "/score_completions",
                body=body,
                headers={"Content-Length": bad},
            )
            # None tolerated: the reject-and-close leaves the body
            # unread, and the RST can race away the queued reply.
            assert status in (None, 400, 413), (
                f"Content-Length {bad}: {status}"
            )
        # Liveness canary: a well-formed request (body fully consumed,
        # no legitimate drop) must still be answered after the storm.
        status = _request(
            port,
            "POST",
            "/score_completions",
            body=body,
            headers={"Content-Type": "application/json"},
        )
        assert status == 200, status

    def test_unconsumed_body_on_404_route_closes_connection(self, service):
        """A POST to an unknown path replies 404 before reading the
        body; the unread bytes must not be parsed as the next request
        line — the server closes the connection instead."""
        port = service
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            # The server may close with the body bytes unread (Linux
            # RSTs on such a close), which can race away the 404 —
            # either outcome proves the desync protection.
            try:
                conn.request(
                    "POST",
                    "/no/such/path",
                    body=b'{"x": 1}',
                    headers={"Content-Type": "application/json"},
                )
                response = conn.getresponse()
                response.read()
                assert response.status == 404
            except (http.client.HTTPException, ConnectionError, OSError):
                return  # server dropped the connection early: correct
            try:
                conn.request("GET", "/healthz")
                response = conn.getresponse()
                response.read()
                # If the connection survived, the reply must be a real
                # 200 — never a 400 from the body bytes parsed as a
                # request line.
                assert response.status == 200
            except (http.client.HTTPException, ConnectionError, OSError):
                pass  # server dropped the desynced connection: correct
        finally:
            conn.close()

    def test_conflicting_content_length_headers_rejected(self, service):
        """Duplicate Content-Length headers with different values are a
        request-smuggling primitive (read(first) leaves body bytes
        buffered as the next request line); reject with 400."""
        port = service
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.putrequest("POST", "/score_completions")
            conn.putheader("Content-Length", "5")
            conn.putheader("Content-Length", "100")
            conn.endheaders()
            conn.send(b"A" * 100)
            response = conn.getresponse()
            response.read()
            assert response.status == 400
        except (http.client.HTTPException, ConnectionError, OSError):
            pass  # early close is also a correct rejection
        finally:
            conn.close()

    def test_chunked_transfer_encoding_rejected(self, service):
        """A chunked body is never decoded by _read_json; accepting it
        would leave the chunk framing buffered and desync keep-alive.
        The server must reject and drop the connection."""
        port = service
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            # The server may 501-and-close before the chunk bytes are
            # even sent (close-with-unread-data RSTs on Linux), so the
            # send and first read are themselves race-tolerant: either
            # we see the 501, or the connection is already gone —
            # both prove the reject-and-drop behavior.
            try:
                conn.putrequest("POST", "/score_completions")
                conn.putheader("Transfer-Encoding", "chunked")
                conn.endheaders()
                conn.send(b"5\r\nhello\r\n0\r\n\r\n")
                response = conn.getresponse()
                response.read()
                assert response.status == 501
            except (http.client.HTTPException, ConnectionError, OSError):
                return  # server dropped the connection early: correct
            # The connection must be closed: a follow-up either fails or
            # never sees the chunk bytes parsed as a request line.
            try:
                conn.request("GET", "/healthz")
                response = conn.getresponse()
                response.read()
                assert response.status == 200
            except (http.client.HTTPException, ConnectionError, OSError):
                pass  # server dropped the desynced connection: correct
        finally:
            conn.close()

    def test_rejected_body_does_not_desync_keepalive(self, service):
        """An unread body on a keep-alive connection must not be parsed
        as the next request line: the server closes the connection after
        rejecting.  A follow-up on the same socket either fails (closed)
        or — never — returns 501 for the garbage 'method'."""
        port = service
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request(
                "POST",
                "/score_completions",
                body=b"A" * 64,
                headers={"Content-Length": str(MAX_BODY_BYTES + 1)},
            )
            response = conn.getresponse()
            response.read()
            assert response.status == 413
            try:
                conn.request("GET", "/healthz")
                response = conn.getresponse()
                response.read()
                assert response.status == 200
            except (http.client.HTTPException, ConnectionError, OSError):
                pass  # server dropped the desynced connection: correct
        finally:
            conn.close()

    def test_server_alive_after_fuzz(self, service):
        port = service
        rng = random.Random(1)
        for _ in range(30):
            _request(
                port,
                "POST",
                rng.choice(PATHS),
                body=rng.randbytes(rng.randint(0, 32)),
            )
        status = _request(
            port,
            "POST",
            "/score_completions",
            body=json.dumps({"prompt": "hello world", "model": MODEL}).encode(),
            headers={"Content-Type": "application/json"},
        )
        assert status == 200
