"""ONE behavioral contract suite, parameterized over EVERY index backend.

Mirrors the reference's testing idea (pkg/kvcache/kvblock/index_test.go
``testCommonIndexBehavior`` run against in-memory / cost-aware / redis):
backends must be interchangeable.  The parity harness runs against the
in-memory, cost-aware, instrumented, fake-redis, and REMOTE (3-replica
in-process cluster over the strict wire codec) backends, so the
``lookup`` / ``lookup_chain`` / batched-add / dump-restore contract
cannot drift per backend — a backend that diverges fails here before
any cluster or persistence test ever sees it.
"""

import pytest

from llm_d_kv_cache_manager_tpu.cluster import LocalCluster
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    IndexConfig,
    PodEntry,
    new_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    CostAwareIndexConfig,
    InMemoryIndexConfig,
    RedisIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (
    InstrumentedIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import RedisIndex
from tests.helpers.miniresp import MiniRespServer

POD1 = PodEntry("pod-1", "hbm")
POD1_HOST = PodEntry("pod-1", "host")
POD2 = PodEntry("pod-2", "hbm")

BACKENDS = ["in_memory", "cost_aware", "redis", "instrumented", "remote"]


@pytest.fixture(scope="module")
def resp_server():
    server = MiniRespServer()
    yield server
    server.close()


@pytest.fixture(params=BACKENDS)
def index(request, resp_server):
    if request.param == "in_memory":
        yield InMemoryIndex(InMemoryIndexConfig(size=10_000))
    elif request.param == "cost_aware":
        yield CostAwareMemoryIndex(
            CostAwareIndexConfig(max_cost_bytes=64 * 1024 * 1024)
        )
    elif request.param == "instrumented":
        yield InstrumentedIndex(InMemoryIndex(InMemoryIndexConfig(size=10_000)))
    elif request.param == "remote":
        # 3 in-process replicas through the strict wire codec: the
        # same method table the HTTP endpoint serves, so contract
        # parity here covers the RPC serialization too.
        cluster = LocalCluster(strict_wire=True)
        yield cluster.remote_index
        cluster.close()
    else:
        idx = RedisIndex(RedisIndexConfig(address=resp_server.address))
        yield idx
        idx._client.execute("FLUSHALL")


class TestIndexContract:
    def test_add_then_lookup(self, index):
        index.add([101, 102], [201, 202], [POD1])
        found = index.lookup([201, 202])
        assert set(found) == {201, 202}
        assert found[201] == [POD1]

    def test_lookup_filters_by_pod_set(self, index):
        index.add([110], [210], [POD1, POD2])
        found = index.lookup([210], {"pod-2"})
        assert found == {210: [POD2]}

    def test_lookup_missing_keys_skipped(self, index):
        index.add([120], [220], [POD1])
        found = index.lookup([9999, 220])
        assert found == {220: [POD1]}

    def test_lookup_empty_keys_raises(self, index):
        with pytest.raises(ValueError):
            index.lookup([])

    def test_multiple_tiers_per_pod(self, index):
        index.add([130], [230], [POD1, POD1_HOST])
        found = index.lookup([230])
        assert set(found[230]) == {POD1, POD1_HOST}

    def test_get_request_key_and_eviction(self, index):
        index.add([140], [240], [POD1, POD2])
        assert index.get_request_key(140) == 240

        index.evict(140, [POD1])
        assert index.lookup([240]) == {240: [POD2]}

        index.evict(140, [POD2])
        # Fully evicted: the key disappears and the engine mapping with it.
        assert index.lookup([240, 240]) == {}
        with pytest.raises(KeyError):
            index.get_request_key(140)

    def test_evict_unknown_engine_key_is_noop(self, index):
        index.evict(31337, [POD1])

    def test_add_validates_lengths(self, index):
        with pytest.raises(ValueError):
            index.add([1, 2], [1], [POD1])
        with pytest.raises(ValueError):
            index.add([], [], [POD1])
        with pytest.raises(ValueError):
            index.evict(1, [])

    def test_purge_pod_removes_only_that_pod(self, index):
        both = [POD1, POD2]
        index.add([301, 302], [401, 402], both)
        index.add([303], [403], [POD1])  # POD1-only key

        removed = index.purge_pod(POD1.pod_identifier)
        assert removed == 3

        found = index.lookup([401, 402, 403])
        # Shared keys keep POD2; the POD1-only key is gone entirely
        # (an empty pod set would break every pod's prefix chain).
        assert set(found) == {401, 402}
        assert all(
            p.pod_identifier == POD2.pod_identifier
            for pods in found.values()
            for p in pods
        )
        # Unknown pods purge nothing.
        assert index.purge_pod("no-such-pod") == 0

    def test_purge_pod_removes_every_tier(self, index):
        tiers = [
            PodEntry(POD1.pod_identifier, "hbm"),
            PodEntry(POD1.pod_identifier, "host"),
            PodEntry(POD1.pod_identifier, "shared_storage"),
        ]
        index.add([311], [411], tiers)
        assert index.purge_pod(POD1.pod_identifier) == 3
        assert index.lookup([411]) == {}

    def test_readd_after_evict(self, index):
        index.add([150], [250], [POD1])
        index.evict(150, [POD1])
        index.add([150], [250], [POD2])
        assert index.lookup([250]) == {250: [POD2]}
        assert index.get_request_key(150) == 250

    # -- the modern contract surface (fast lane + batched apply + dump) --

    def test_lookup_chain_aligned_and_truncated(self, index):
        """lookup_chain is the fast lane's shape: aligned per-key pod
        lists, truncated at the first key with no resident pods —
        whether the backend overrides it (in-memory, redis, remote) or
        inherits the default adapter."""
        index.add([501, 502, 503], [601, 602, 603], [POD1, POD2])
        chain = index.lookup_chain([601, 602, 603])
        assert len(chain) == 3
        for pods in chain:
            assert set(pods) == {POD1, POD2}
        # A missing key cuts the chain for every pod.
        truncated = index.lookup_chain([601, 9999, 603])
        assert len(truncated) == 1
        assert index.lookup_chain([9999, 601]) == []

    def test_lookup_chain_agrees_with_lookup(self, index):
        """Chain results must be lookup's view of the same keys (the
        scorer relies on either shape producing identical scores)."""
        index.add([511, 512], [611, 612], [POD1])
        keys = [611, 612, 613]
        chain = index.lookup_chain(keys)
        flat = index.lookup(keys)
        for key, pods in zip(keys, chain):
            assert set(pods) == set(flat[key])
        assert len(chain) == 2  # 613 never added

    def test_batched_apply_surface(self, index):
        """add_mappings + add_entries_batch (the kvevents batched-apply
        split) must equal a plain add; backends without the surface
        are exercised through the applier's fallback path instead
        (tests/test_read_path_fastlane.py)."""
        if not (
            callable(getattr(index, "add_mappings", None))
            and callable(getattr(index, "add_entries_batch", None))
        ):
            pytest.skip("backend has no batched-apply surface")
        index.add_mappings([701, 702], [801, 802])
        index.add_entries_batch(
            [([801], [POD1]), ([802], [POD1, POD2])]
        )
        assert index.get_request_key(701) == 801
        found = index.lookup([801, 802])
        assert found[801] == [POD1]
        assert set(found[802]) == {POD1, POD2}
        # The mapping resolves evictions exactly like add's would.
        index.evict(701, [POD1])
        assert index.lookup([802, 801]).get(801) is None

    def test_dump_restore_round_trip(self, index):
        """Every backend answers the persistence contract with a real
        round trip — including Redis (SCAN-based, replacing the old
        documented no-op) and the remote cluster (concatenated replica
        dumps routed back to their owners)."""
        index.add([160, 161], [260, 261], [POD1, POD2])
        block_entries, engine_map = index.dump_entries()
        assert {k for k, _ in block_entries} >= {260, 261}
        assert dict(engine_map)[160] == 260
        restored = index.restore_entries(block_entries, engine_map)
        assert restored == len(
            [e for _, e in block_entries if e]
        )  # idempotent re-add
        assert set(index.lookup([260, 261])) == {260, 261}


class TestInMemorySpecifics:
    def test_pod_cache_bounded(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=100, pod_cache_size=3))
        pods = [PodEntry(f"pod-{i}", "hbm") for i in range(6)]
        index.add([1], [2], pods)
        resident = index.lookup([2])[2]
        assert len(resident) == 3
        # Most recently added pods survive.
        assert set(resident) == set(pods[3:])

    def test_key_lru_eviction(self):
        # shards=1 pins the exact single-LRU capacity semantics this
        # test asserts; the sharded default bounds capacity per shard
        # (see InMemoryIndexConfig.shards).
        index = InMemoryIndex(InMemoryIndexConfig(size=2, shards=1))
        index.add([1, 2, 3], [11, 12, 13], [POD1])
        # Capacity 2: the oldest request key fell out.
        assert index.lookup([11, 12, 13]) == {12: [POD1], 13: [POD1]}

    def test_per_shard_lru_eviction(self):
        """Sharded capacity: eviction is LRU within each stripe, so
        keys landing on distinct shards never evict each other."""
        index = InMemoryIndex(InMemoryIndexConfig(size=4, shards=4))
        # Keys 0..3 hit four distinct shards (key & 3); per-shard
        # capacity is 1, so a same-shard key (4 -> shard 0) evicts key
        # 0 while the other shards keep theirs.
        index.add([0, 1, 2, 3], [0, 1, 2, 3], [POD1])
        index.add([4], [4], [POD1])
        assert index.lookup([4]) == {4: [POD1]}
        found = index.lookup([1, 2, 3])
        assert found == {1: [POD1], 2: [POD1], 3: [POD1]}
        assert index.lookup([0]) == {}

    def test_empty_podcache_stops_scan(self):
        """A present-but-empty key must cut the lookup early."""
        index = InMemoryIndex(InMemoryIndexConfig(size=100))
        index.add([1, 2], [21, 22], [POD1])
        index.add([3], [23], [POD1])
        # Drain key 22's pods without removing the key.
        index._shard(22).get(22).remove_all([POD1])
        found = index.lookup([21, 22, 23])
        assert found == {21: [POD1]}

    def test_lookup_batched_get_refreshes_recency(self):
        """lookup batches its locking (LRUCache.peek_many, then one
        touch_many for the keys that yielded pods); a looked-up key
        must end as recency-fresh as a per-key get would have left it
        — the next insert evicts an UNTOUCHED key, not the looked-up
        one.  shards=1: the assertion depends on exact global LRU."""
        index = InMemoryIndex(InMemoryIndexConfig(size=2, shards=1))
        index.add([1], [11], [POD1])
        index.add([2], [12], [POD1])
        index.lookup([11])  # refreshes 11; 12 is now the LRU victim
        index.add([3], [13], [POD1])
        assert index.lookup([11, 13]) == {11: [POD1], 13: [POD1]}
        assert index.lookup([11, 12, 13]) == {11: [POD1], 13: [POD1]}


class TestCostAwareSpecifics:
    def test_budget_eviction(self):
        index = CostAwareMemoryIndex(CostAwareIndexConfig(max_cost_bytes=2000))
        for i in range(100):
            index.add([1000 + i], [2000 + i], [POD1])
        assert index.resident_cost_bytes <= 2000
        keys = list(range(2000, 2100))
        found = index.lookup(keys)
        assert 0 < len(found) < 100
        # Most recent keys survive.
        assert 2099 in found


def test_factory_backend_priority(resp_server):
    assert isinstance(new_index(IndexConfig()), InMemoryIndex)
    assert isinstance(
        new_index(IndexConfig(cost_aware_config=CostAwareIndexConfig())),
        CostAwareMemoryIndex,
    )
    assert isinstance(
        new_index(
            IndexConfig(
                in_memory_config=None,
                redis_config=RedisIndexConfig(address=resp_server.address),
            )
        ),
        RedisIndex,
    )
    wrapped = new_index(IndexConfig(enable_metrics=True))
    assert isinstance(wrapped, InstrumentedIndex)
    assert isinstance(wrapped.inner, InMemoryIndex)
