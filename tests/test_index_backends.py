"""One behavioral contract suite, parameterized over every index backend.

Mirrors the reference's testing idea (pkg/kvcache/kvblock/index_test.go
``testCommonIndexBehavior`` run against in-memory / cost-aware / redis):
backends must be interchangeable.
"""

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    IndexConfig,
    PodEntry,
    new_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    CostAwareIndexConfig,
    InMemoryIndexConfig,
    RedisIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (
    InstrumentedIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import RedisIndex
from tests.helpers.miniresp import MiniRespServer

POD1 = PodEntry("pod-1", "hbm")
POD1_HOST = PodEntry("pod-1", "host")
POD2 = PodEntry("pod-2", "hbm")


@pytest.fixture(scope="module")
def resp_server():
    server = MiniRespServer()
    yield server
    server.close()


@pytest.fixture(
    params=["in_memory", "cost_aware", "redis", "instrumented"]
)
def index(request, resp_server):
    if request.param == "in_memory":
        yield InMemoryIndex(InMemoryIndexConfig(size=10_000))
    elif request.param == "cost_aware":
        yield CostAwareMemoryIndex(
            CostAwareIndexConfig(max_cost_bytes=64 * 1024 * 1024)
        )
    elif request.param == "instrumented":
        yield InstrumentedIndex(InMemoryIndex(InMemoryIndexConfig(size=10_000)))
    else:
        idx = RedisIndex(RedisIndexConfig(address=resp_server.address))
        yield idx
        idx._client.execute("FLUSHALL")


class TestIndexContract:
    def test_add_then_lookup(self, index):
        index.add([101, 102], [201, 202], [POD1])
        found = index.lookup([201, 202])
        assert set(found) == {201, 202}
        assert found[201] == [POD1]

    def test_lookup_filters_by_pod_set(self, index):
        index.add([110], [210], [POD1, POD2])
        found = index.lookup([210], {"pod-2"})
        assert found == {210: [POD2]}

    def test_lookup_missing_keys_skipped(self, index):
        index.add([120], [220], [POD1])
        found = index.lookup([9999, 220])
        assert found == {220: [POD1]}

    def test_lookup_empty_keys_raises(self, index):
        with pytest.raises(ValueError):
            index.lookup([])

    def test_multiple_tiers_per_pod(self, index):
        index.add([130], [230], [POD1, POD1_HOST])
        found = index.lookup([230])
        assert set(found[230]) == {POD1, POD1_HOST}

    def test_get_request_key_and_eviction(self, index):
        index.add([140], [240], [POD1, POD2])
        assert index.get_request_key(140) == 240

        index.evict(140, [POD1])
        assert index.lookup([240]) == {240: [POD2]}

        index.evict(140, [POD2])
        # Fully evicted: the key disappears and the engine mapping with it.
        assert index.lookup([240, 240]) == {}
        with pytest.raises(KeyError):
            index.get_request_key(140)

    def test_evict_unknown_engine_key_is_noop(self, index):
        index.evict(31337, [POD1])

    def test_add_validates_lengths(self, index):
        with pytest.raises(ValueError):
            index.add([1, 2], [1], [POD1])
        with pytest.raises(ValueError):
            index.add([], [], [POD1])
        with pytest.raises(ValueError):
            index.evict(1, [])

    def test_purge_pod_removes_only_that_pod(self, index):
        both = [POD1, POD2]
        index.add([301, 302], [401, 402], both)
        index.add([303], [403], [POD1])  # POD1-only key

        removed = index.purge_pod(POD1.pod_identifier)
        assert removed == 3

        found = index.lookup([401, 402, 403])
        # Shared keys keep POD2; the POD1-only key is gone entirely
        # (an empty pod set would break every pod's prefix chain).
        assert set(found) == {401, 402}
        assert all(
            p.pod_identifier == POD2.pod_identifier
            for pods in found.values()
            for p in pods
        )
        # Unknown pods purge nothing.
        assert index.purge_pod("no-such-pod") == 0

    def test_purge_pod_removes_every_tier(self, index):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            PodEntry,
        )

        tiers = [
            PodEntry(POD1.pod_identifier, "hbm"),
            PodEntry(POD1.pod_identifier, "host"),
            PodEntry(POD1.pod_identifier, "shared_storage"),
        ]
        index.add([311], [411], tiers)
        assert index.purge_pod(POD1.pod_identifier) == 3
        assert index.lookup([411]) == {}

    def test_readd_after_evict(self, index):
        index.add([150], [250], [POD1])
        index.evict(150, [POD1])
        index.add([150], [250], [POD2])
        assert index.lookup([250]) == {250: [POD2]}
        assert index.get_request_key(150) == 250

    def test_dump_restore_entries_part_of_contract(self, index):
        """Every backend answers the persistence contract; the durable
        Redis backend answers it with the documented no-op (state
        already lives server-side), the in-process ones round-trip."""
        index.add([160, 161], [260, 261], [POD1, POD2])
        block_entries, engine_map = index.dump_entries()
        restored = index.restore_entries(block_entries, engine_map)
        if isinstance(index, RedisIndex):
            assert (block_entries, engine_map) == ([], [])
            assert restored == 0
        else:
            assert {k for k, _ in block_entries} >= {260, 261}
            assert dict(engine_map)[160] == 260
            assert restored == len(block_entries)  # idempotent re-add
            assert set(index.lookup([260, 261])) == {260, 261}


class TestInMemorySpecifics:
    def test_pod_cache_bounded(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=100, pod_cache_size=3))
        pods = [PodEntry(f"pod-{i}", "hbm") for i in range(6)]
        index.add([1], [2], pods)
        resident = index.lookup([2])[2]
        assert len(resident) == 3
        # Most recently added pods survive.
        assert set(resident) == set(pods[3:])

    def test_key_lru_eviction(self):
        # shards=1 pins the exact single-LRU capacity semantics this
        # test asserts; the sharded default bounds capacity per shard
        # (see InMemoryIndexConfig.shards).
        index = InMemoryIndex(InMemoryIndexConfig(size=2, shards=1))
        index.add([1, 2, 3], [11, 12, 13], [POD1])
        # Capacity 2: the oldest request key fell out.
        assert index.lookup([11, 12, 13]) == {12: [POD1], 13: [POD1]}

    def test_per_shard_lru_eviction(self):
        """Sharded capacity: eviction is LRU within each stripe, so
        keys landing on distinct shards never evict each other."""
        index = InMemoryIndex(InMemoryIndexConfig(size=4, shards=4))
        # Keys 0..3 hit four distinct shards (key & 3); per-shard
        # capacity is 1, so a same-shard key (4 -> shard 0) evicts key
        # 0 while the other shards keep theirs.
        index.add([0, 1, 2, 3], [0, 1, 2, 3], [POD1])
        index.add([4], [4], [POD1])
        assert index.lookup([4]) == {4: [POD1]}
        found = index.lookup([1, 2, 3])
        assert found == {1: [POD1], 2: [POD1], 3: [POD1]}
        assert index.lookup([0]) == {}

    def test_empty_podcache_stops_scan(self):
        """A present-but-empty key must cut the lookup early."""
        index = InMemoryIndex(InMemoryIndexConfig(size=100))
        index.add([1, 2], [21, 22], [POD1])
        index.add([3], [23], [POD1])
        # Drain key 22's pods without removing the key.
        index._shard(22).get(22).remove_all([POD1])
        found = index.lookup([21, 22, 23])
        assert found == {21: [POD1]}


    def test_lookup_batched_get_refreshes_recency(self):
        """lookup batches its locking (LRUCache.peek_many, then one
        touch_many for the keys that yielded pods); a looked-up key
        must end as recency-fresh as a per-key get would have left it
        — the next insert evicts an UNTOUCHED key, not the looked-up
        one.  shards=1: the assertion depends on exact global LRU."""
        index = InMemoryIndex(InMemoryIndexConfig(size=2, shards=1))
        index.add([1], [11], [POD1])
        index.add([2], [12], [POD1])
        index.lookup([11])  # refreshes 11; 12 is now the LRU victim
        index.add([3], [13], [POD1])
        assert index.lookup([11, 13]) == {11: [POD1], 13: [POD1]}
        assert index.lookup([11, 12, 13]) == {11: [POD1], 13: [POD1]}


class TestCostAwareSpecifics:
    def test_budget_eviction(self):
        index = CostAwareMemoryIndex(CostAwareIndexConfig(max_cost_bytes=2000))
        for i in range(100):
            index.add([1000 + i], [2000 + i], [POD1])
        assert index.resident_cost_bytes <= 2000
        keys = list(range(2000, 2100))
        found = index.lookup(keys)
        assert 0 < len(found) < 100
        # Most recent keys survive.
        assert 2099 in found


def test_factory_backend_priority(resp_server):
    assert isinstance(new_index(IndexConfig()), InMemoryIndex)
    assert isinstance(
        new_index(IndexConfig(cost_aware_config=CostAwareIndexConfig())),
        CostAwareMemoryIndex,
    )
    assert isinstance(
        new_index(
            IndexConfig(
                in_memory_config=None,
                redis_config=RedisIndexConfig(address=resp_server.address),
            )
        ),
        RedisIndex,
    )
    wrapped = new_index(IndexConfig(enable_metrics=True))
    assert isinstance(wrapped, InstrumentedIndex)
    assert isinstance(wrapped.inner, InMemoryIndex)
