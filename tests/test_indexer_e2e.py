"""End-to-end indexer: KVEvents in -> pod scores out.

The reference's e2e suite (tests/e2e/redis_mock/e2e_test.go) boots the real
indexer with block_size=4 and injects synthetic events; this does the same
with the whole Python stack wired together, sharing one token processor
between the event pool (write path) and the indexer (read path).
"""

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
    ApplyChatTemplateRequest,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import (
    build_transformers_tokenizer,
    save_tokenizer_json,
)

MODEL = "test-model"
BLOCK_SIZE = 4


@pytest.fixture()
def stack(tmp_path):
    tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            kvblock_index_config=IndexConfig(
                in_memory_config=InMemoryIndexConfig(size=10_000)
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.chat_processor.register_tokenizer(
        MODEL, build_transformers_tokenizer()
    )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()
    yield indexer, event_pool
    event_pool.shutdown()
    indexer.shutdown()


def publish_prompt_blocks(
    indexer, event_pool, prompt, pod, medium="hbm", base_hash=0x1000
):
    """Simulate a pod storing every full block of `prompt`'s tokens."""
    encoding = indexer.tokenization_pool._tokenizer.encode(
        prompt, MODEL, True
    )
    tokens = encoding.tokens
    n_blocks = len(tokens) // BLOCK_SIZE
    engine_hashes = [base_hash + i for i in range(n_blocks)]
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=engine_hashes,
                parent_block_hash=None,
                token_ids=tokens[: n_blocks * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                medium=medium,
            )
        ],
    )
    event_pool.add_task(
        Message(
            topic=f"kv@{pod}@{MODEL}",
            payload=batch.encode(),
            pod_identifier=pod,
            model_name=MODEL,
        )
    )
    event_pool.drain()
    return engine_hashes, n_blocks


PROMPT = "the quick brown fox jumps over the lazy dog . " * 8


class TestEndToEnd:
    def test_miss_then_hit(self, stack):
        indexer, event_pool = stack
        # Filtered pods unknown to the index get explicit zero entries
        # (not silently missing) so planner/ledger/explain agree on
        # the candidate set.
        assert indexer.get_pod_scores(PROMPT, MODEL, ["pod-1"]) == {
            "pod-1": 0.0
        }

        _, n_blocks = publish_prompt_blocks(
            indexer, event_pool, PROMPT, "pod-1"
        )
        scores = indexer.get_pod_scores(PROMPT, MODEL, ["pod-1"])
        assert scores["pod-1"] == pytest.approx(float(n_blocks))

    def test_prefix_reduction(self, stack):
        """A shorter prompt sharing the prefix still hits."""
        indexer, event_pool = stack
        publish_prompt_blocks(indexer, event_pool, PROMPT, "pod-1")
        short = PROMPT[: len(PROMPT) // 2]
        scores = indexer.get_pod_scores(short, MODEL, ["pod-1"])
        assert scores.get("pod-1", 0) > 0

    def test_prefix_expansion_partial_score(self, stack):
        """A longer prompt scores only the stored prefix blocks."""
        indexer, event_pool = stack
        _, n_blocks = publish_prompt_blocks(
            indexer, event_pool, PROMPT, "pod-1"
        )
        longer = PROMPT + "pack my box with five dozen liquor jugs . " * 8
        scores = indexer.get_pod_scores(longer, MODEL, ["pod-1"])
        assert 0 < scores["pod-1"] <= n_blocks

    def test_tier_weighting_prefers_hbm(self, stack):
        indexer, event_pool = stack
        publish_prompt_blocks(
            indexer, event_pool, PROMPT, "pod-hbm", medium="hbm",
            base_hash=0x1000,
        )
        publish_prompt_blocks(
            indexer, event_pool, PROMPT, "pod-host", medium="host",
            base_hash=0x2000,
        )
        scores = indexer.get_pod_scores(
            PROMPT, MODEL, ["pod-hbm", "pod-host"]
        )
        assert scores["pod-hbm"] > scores["pod-host"] > 0

    def test_eviction_clears_scores(self, stack):
        indexer, event_pool = stack
        engine_hashes, _ = publish_prompt_blocks(
            indexer, event_pool, PROMPT, "pod-1"
        )
        batch = EventBatch(
            ts=2.0, events=[BlockRemoved(block_hashes=engine_hashes)]
        )
        event_pool.add_task(
            Message(
                topic=f"kv@pod-1@{MODEL}",
                payload=batch.encode(),
                pod_identifier="pod-1",
                model_name=MODEL,
            )
        )
        event_pool.drain()
        # Evicted chain scores zero; the filtered pod stays listed.
        assert indexer.get_pod_scores(PROMPT, MODEL, ["pod-1"]) == {
            "pod-1": 0.0
        }

    def test_pod_filter(self, stack):
        indexer, event_pool = stack
        publish_prompt_blocks(indexer, event_pool, PROMPT, "pod-1")
        scores = indexer.get_pod_scores(PROMPT, MODEL, ["other-pod"])
        # The holder is filtered out; the unknown requested pod gets
        # an explicit zero entry rather than vanishing.
        assert scores == {"other-pod": 0.0}

    def test_chat_completions_flow(self, stack):
        indexer, event_pool = stack
        render_req = ApplyChatTemplateRequest(
            conversation=[
                {"role": "system", "content": "you are a helpful assistant ."},
                {"role": "user", "content": "hello world"},
            ]
        )
        # Render once to learn the exact prompt the engine would see, and
        # simulate the engine having stored those blocks.
        rendered = indexer.chat_processor.apply_chat_template(
            MODEL, render_req
        )
        publish_prompt_blocks(indexer, event_pool, rendered, "pod-chat")
        scores = indexer.get_pod_scores(
            "", MODEL, ["pod-chat"], render_req=render_req
        )
        assert scores.get("pod-chat", 0) > 0

    def test_long_prompt(self, stack):
        indexer, event_pool = stack
        long_prompt = PROMPT * 12  # ~1000 tokens
        _, n_blocks = publish_prompt_blocks(
            indexer, event_pool, long_prompt, "pod-long"
        )
        assert n_blocks > 100
        scores = indexer.get_pod_scores(long_prompt, MODEL, ["pod-long"])
        assert scores["pod-long"] == pytest.approx(float(n_blocks))
