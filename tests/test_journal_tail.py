"""The journal follow API: torn tails, rotation, compaction, bounds.

``persistence.journal.tail`` is the enabling primitive for replication
followers (docs/replication.md): these tests pin the follow contract —
a torn tail in the ACTIVE segment holds (and the record is returned
once whole), a sealed segment's corruption abandons the rest of that
segment only, rotation and compaction are followed seamlessly, and a
bounded call resumes exactly where it stopped.
"""

import os
import struct
import zlib

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.persistence.journal import (
    OP_ADD,
    OP_PURGE,
    Journal,
    JournalRecord,
    TailPosition,
    list_segments,
    tail,
)

POD = PodEntry("pod-a", "hbm")

_RECORD_HEADER = struct.Struct(">II")


def _record(i: int, seq: int = 0) -> JournalRecord:
    return JournalRecord(
        op=OP_ADD,
        pod_identifier="pod-a",
        seq=seq,
        ts_ns=1,
        engine_keys=[1000 + i],
        request_keys=[2000 + i],
        entries=[POD],
    )


def _frame(record: JournalRecord) -> bytes:
    body = record.encode()
    return (
        _RECORD_HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF)
        + body
    )


def _append_raw(directory: str, data: bytes) -> str:
    """Append bytes to the newest segment file directly (simulating a
    writer whose append is partially visible)."""
    segments = list_segments(directory)
    path = segments[-1][1]
    with open(path, "ab") as handle:
        handle.write(data)
    return path


class TestTailBasics:
    def test_follow_from_start_and_resume(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [2], [POD])
        journal.record_add("pod-a", 2, [3], [4], [POD])

        records, position = tail(str(tmp_path))
        assert [r.seq for r in records] == [1, 2]

        journal.record_evict("pod-a", 3, [1], [POD])
        more, position2 = tail(str(tmp_path), position)
        assert len(more) == 1 and more[0].seq == 3
        # Idle poll: nothing new, position stable.
        empty, position3 = tail(str(tmp_path), position2)
        assert empty == [] and position3 == position2
        journal.close()

    def test_empty_directory(self, tmp_path):
        records, position = tail(str(tmp_path))
        assert records == [] and position == TailPosition(0, 0)

    def test_boundary_start_skips_covered_segments(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [2], [POD])
        boundary, watermarks, _ = journal.snapshot_boundary()
        journal.record_add("pod-a", 2, [3], [4], [POD])

        records, _ = tail(str(tmp_path), TailPosition(boundary, 0))
        assert [r.seq for r in records] == [2]
        assert watermarks == {"pod-a": 1}
        journal.close()

    def test_max_records_resumes_mid_segment(self, tmp_path):
        journal = Journal(str(tmp_path))
        for i in range(5):
            journal.record_add("pod-a", i + 1, [i], [i], [POD])
        first, position = tail(str(tmp_path), max_records=2)
        assert [r.seq for r in first] == [1, 2]
        rest, _ = tail(str(tmp_path), position)
        assert [r.seq for r in rest] == [3, 4, 5]
        journal.close()

    def test_purge_records_flow_through(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [2], [POD])
        journal.record_purge("pod-a")
        records, _ = tail(str(tmp_path))
        assert [r.op for r in records] == [OP_ADD, OP_PURGE]
        assert records[1].pod_identifier == "pod-a"
        journal.close()


class TestTornTails:
    def test_active_torn_tail_holds_then_completes(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [2], [POD])
        journal.close()

        frame = _frame(_record(7))
        _append_raw(str(tmp_path), frame[: len(frame) - 5])

        records, position = tail(str(tmp_path))
        assert len(records) == 1  # the whole record only
        held = position

        # Writer finishes the append: the SAME position now yields it.
        _append_raw(str(tmp_path), frame[len(frame) - 5:])
        more, position2 = tail(str(tmp_path), held)
        assert len(more) == 1
        assert more[0].engine_keys == [1007]
        assert position2.offset > held.offset

    def test_crc_corruption_in_active_segment_holds(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [2], [POD])
        journal.close()
        frame = bytearray(_frame(_record(8)))
        frame[-1] ^= 0xFF  # body corrupted, CRC now mismatches
        _append_raw(str(tmp_path), bytes(frame))

        records, position = tail(str(tmp_path))
        assert len(records) == 1
        again, position2 = tail(str(tmp_path), position)
        assert again == [] and position2 == position

    def test_sealed_torn_tail_abandons_segment(self, tmp_path):
        """A higher-id segment exists: the torn record can never
        complete, so the follower moves on (stop-don't-skip applies to
        the REST of the sealed segment, not the whole journal)."""
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [2], [POD])
        journal.close()
        frame = _frame(_record(9))
        _append_raw(str(tmp_path), frame[: len(frame) - 3])

        # A fresh Journal seals the torn segment by starting a new one.
        journal2 = Journal(str(tmp_path))
        journal2.record_add("pod-a", 2, [5], [6], [POD])

        records, position = tail(str(tmp_path))
        assert [r.seq for r in records] == [1, 2]
        # Cursor sits in the NEW segment now.
        assert position.segment_id == list_segments(str(tmp_path))[-1][0]
        journal2.close()

    def test_undecodable_but_whole_record_is_skipped(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [2], [POD])
        journal.close()
        body = b"\x00"  # valid CBOR int, wrong record shape
        _append_raw(
            str(tmp_path),
            _RECORD_HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF)
            + body,
        )
        _append_raw(str(tmp_path), _frame(_record(3, seq=2)))
        records, _ = tail(str(tmp_path))
        # The garbage record is skipped (it will never change); the
        # good one behind it still arrives.
        assert [r.seq for r in records] == [1, 2]


class TestRotationAndCompaction:
    def test_rotation_mid_follow(self, tmp_path):
        journal = Journal(str(tmp_path), segment_max_bytes=1)
        journal.record_add("pod-a", 1, [1], [2], [POD])
        records, position = tail(str(tmp_path))
        assert len(records) == 1
        # Every append rotates at this size: new records land in new
        # segment files; the cursor follows.
        journal.record_add("pod-a", 2, [3], [4], [POD])
        journal.record_add("pod-a", 3, [5], [6], [POD])
        more, position2 = tail(str(tmp_path), position)
        assert [r.seq for r in more] == [2, 3]
        assert position2.segment_id > position.segment_id
        journal.close()

    def test_compaction_of_cursor_segment_jumps_forward(self, tmp_path):
        journal = Journal(str(tmp_path), segment_max_bytes=1)
        journal.record_add("pod-a", 1, [1], [2], [POD])
        _, position = tail(str(tmp_path))
        journal.record_add("pod-a", 2, [3], [4], [POD])
        # Compact everything below the newest segment — including the
        # segment the cursor points into.
        newest = list_segments(str(tmp_path))[-1][0]
        removed = journal.compact_before(newest)
        assert removed >= 1
        records, position2 = tail(str(tmp_path), position)
        assert [r.seq for r in records] == [2]
        assert position2.segment_id >= newest
        journal.close()

    def test_gap_in_segment_ids_is_followed(self, tmp_path):
        journal = Journal(str(tmp_path), segment_max_bytes=1)
        for seq in (1, 2, 3):
            journal.record_add("pod-a", seq, [seq], [seq], [POD])
        segments = list_segments(str(tmp_path))
        # Remove a MIDDLE segment (manual compaction hole).
        os.unlink(segments[1][1])
        records, _ = tail(str(tmp_path))
        assert [r.seq for r in records] == [1, 3]
        journal.close()
