"""KVEvents codec + ingestion pool tests (fleet simulated by synthetic
events, per the reference's test strategy)."""

import msgpack
import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import InMemoryIndexConfig
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    EventBatch,
    EventDecodeError,
    decode_event,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
    fnv1a_32,
)

MODEL = "m"
POD = "pod-1"


def make_pool(concurrency=2, block_size=4):
    index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=block_size))
    pool = Pool(index, db, PoolConfig(concurrency=concurrency))
    pool.start()
    return pool, index, db


def deliver(pool, *events, pod=POD, model=MODEL):
    batch = EventBatch(ts=1.0, events=list(events))
    pool.add_task(
        Message(
            topic=f"kv@{pod}@{model}",
            payload=batch.encode(),
            pod_identifier=pod,
            model_name=model,
        )
    )
    pool.drain()


class TestCodec:
    def test_batch_roundtrip(self):
        stored = BlockStored(
            block_hashes=[1, 2],
            parent_block_hash=None,
            token_ids=[5, 6, 7, 8],
            block_size=4,
            medium="hbm",
        )
        batch = EventBatch(ts=123.5, events=[stored], data_parallel_rank=3)
        decoded = decode_event_batch(batch.encode())
        assert decoded.ts == 123.5
        assert decoded.data_parallel_rank == 3
        event = decode_event(decoded.events[0])
        assert isinstance(event, BlockStored)
        assert event.block_hashes == [1, 2]
        assert event.token_ids == [5, 6, 7, 8]
        assert event.medium == "hbm"
        assert event.lora_name is None

    def test_legacy_event_without_optional_fields(self):
        # Old publishers omit lora_id/medium/lora_name entirely.
        raw = ["BlockStored", [9], None, [1, 2, 3, 4], 4]
        event = decode_event(raw)
        assert event.medium is None and event.lora_id is None

    def test_batch_without_dp_rank(self):
        payload = msgpack.packb([1.0, []])
        batch = decode_event_batch(payload)
        assert batch.data_parallel_rank is None

    def test_bytes_hashes_preserved(self):
        digest = bytes(range(32))
        raw = ["BlockStored", [digest], digest, [1], 1]
        event = decode_event(raw)
        assert event.block_hashes == [digest]

    def test_block_removed_roundtrip(self):
        decoded = decode_event_batch(
            EventBatch(ts=0.0, events=[BlockRemoved([7], medium="host")]).encode()
        )
        event = decode_event(decoded.events[0])
        assert isinstance(event, BlockRemoved)
        assert event.medium == "host"

    def test_all_blocks_cleared(self):
        assert isinstance(decode_event(["AllBlocksCleared"]), AllBlocksCleared)

    def test_malformed_inputs(self):
        with pytest.raises(EventDecodeError):
            decode_event_batch(b"\xc1garbage")
        with pytest.raises(EventDecodeError):
            decode_event_batch(msgpack.packb("not a batch"))
        with pytest.raises(EventDecodeError):
            decode_event(["UnknownTag", 1])
        with pytest.raises(EventDecodeError):
            decode_event(["BlockStored", [1]])  # too few fields


class TestPoolDigest:
    def test_block_stored_indexes_request_keys(self):
        pool, index, db = make_pool()
        tokens = [1, 2, 3, 4, 5, 6, 7, 8]
        deliver(
            pool,
            BlockStored(
                block_hashes=[0xA, 0xB],
                parent_block_hash=None,
                token_ids=tokens,
                block_size=4,
            ),
        )
        request_keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, MODEL
        )
        found = index.lookup(request_keys)
        assert set(found) == set(request_keys)
        assert found[request_keys[0]] == [PodEntry(POD, "hbm")]
        assert index.get_request_key(0xA) == request_keys[0]
        pool.shutdown()

    def test_parent_chaining_across_events(self):
        pool, index, db = make_pool()
        tokens = list(range(16))
        deliver(
            pool,
            BlockStored(
                block_hashes=[0x1, 0x2],
                parent_block_hash=None,
                token_ids=tokens[:8],
                block_size=4,
            ),
        )
        deliver(
            pool,
            BlockStored(
                block_hashes=[0x3, 0x4],
                parent_block_hash=0x2,
                token_ids=tokens[8:],
                block_size=4,
            ),
        )
        expected = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        found = index.lookup(expected)
        assert set(found) == set(expected), "chained event must extend prefix"
        pool.shutdown()

    def test_unknown_parent_drops_event(self):
        pool, index, db = make_pool()
        deliver(
            pool,
            BlockStored(
                block_hashes=[0x9],
                parent_block_hash=0xDEAD,
                token_ids=[1, 2, 3, 4],
                block_size=4,
            ),
        )
        with pytest.raises(KeyError):
            index.get_request_key(0x9)
        pool.shutdown()

    def test_medium_and_lora(self):
        pool, index, db = make_pool()
        tokens = [1, 2, 3, 4]
        deliver(
            pool,
            BlockStored(
                block_hashes=[0x1],
                parent_block_hash=None,
                token_ids=tokens,
                block_size=4,
                medium="HOST",
                lora_name="my-lora",
            ),
        )
        lora_keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, "my-lora"
        )
        base_keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        assert index.lookup(lora_keys)[lora_keys[0]] == [
            PodEntry(POD, "host")
        ]
        assert not index.lookup(base_keys)
        pool.shutdown()

    def test_block_removed_evicts(self):
        pool, index, db = make_pool()
        tokens = [1, 2, 3, 4]
        deliver(
            pool,
            BlockStored(
                block_hashes=[0x1],
                parent_block_hash=None,
                token_ids=tokens,
                block_size=4,
            ),
        )
        deliver(pool, BlockRemoved(block_hashes=[0x1]))
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        assert not index.lookup(keys)
        pool.shutdown()

    def test_sha256_byte_hashes_and_parent(self):
        pool, index, db = make_pool()
        digest_a = bytes([0xAA]) * 32
        digest_b = bytes([0xBB]) * 32
        deliver(
            pool,
            BlockStored(
                block_hashes=[digest_a],
                parent_block_hash=None,
                token_ids=[1, 2, 3, 4],
                block_size=4,
            ),
        )
        deliver(
            pool,
            BlockStored(
                block_hashes=[digest_b],
                parent_block_hash=digest_a,
                token_ids=[5, 6, 7, 8],
                block_size=4,
            ),
        )
        expected = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, list(range(1, 9)), MODEL
        )
        assert set(index.lookup(expected)) == set(expected)
        pool.shutdown()

    def test_poison_pill_dropped(self):
        pool, index, _ = make_pool()
        pool.add_task(
            Message(
                topic="kv@pod-1@m",
                payload=b"\xc1 not msgpack",
                pod_identifier=POD,
                model_name=MODEL,
            )
        )
        pool.drain()  # must not wedge the worker
        deliver(
            pool,
            BlockStored(
                block_hashes=[0x5],
                parent_block_hash=None,
                token_ids=[1, 2, 3, 4],
                block_size=4,
            ),
        )
        assert index.get_request_key(0x5)
        pool.shutdown()

    def test_all_blocks_cleared_noop(self):
        pool, index, db = make_pool()
        deliver(
            pool,
            BlockStored(
                block_hashes=[0x1],
                parent_block_hash=None,
                token_ids=[1, 2, 3, 4],
                block_size=4,
            ),
        )
        deliver(pool, AllBlocksCleared())
        keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, [1, 2, 3, 4], MODEL
        )
        assert index.lookup(keys), "AllBlocksCleared must not clear the index"
        pool.shutdown()


def test_shard_selection_is_stable():
    assert fnv1a_32(b"pod-1") == fnv1a_32(b"pod-1")
    assert fnv1a_32(b"pod-1") != fnv1a_32(b"pod-2")


class TestBoundedQueues:
    """Flooding one shard must shed oldest messages, never grow unbounded
    (reference shards over bounded workqueues, pool.go:134-173)."""

    @staticmethod
    def _dropped_total() -> float:
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        total = 0.0
        for metric in METRICS.kvevents_dropped.collect():
            for sample in metric.samples:
                if sample.name.endswith("_total"):
                    total += sample.value
        return total

    def _message(self, i: int) -> Message:
        batch = EventBatch(
            ts=float(i),
            events=[
                BlockStored(
                    block_hashes=[i + 1],
                    parent_block_hash=None,
                    token_ids=[1, 2, 3, 4],
                    block_size=4,
                )
            ],
        )
        return Message(
            topic=f"kv@{POD}@{MODEL}",
            payload=batch.encode(),
            pod_identifier=POD,  # one pod => one shard
            model_name=MODEL,
        )

    def test_flood_is_bounded_and_counted(self):
        depth = 8
        index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        # NOT started: the single shard fills and must shed, not block.
        pool = Pool(
            index, db, PoolConfig(concurrency=1, max_queue_depth=depth)
        )
        before = self._dropped_total()
        flood = 3 * depth
        for i in range(flood):
            pool.add_task(self._message(i))
        assert pool._queues[0].qsize() == depth
        assert self._dropped_total() - before == flood - depth
        # The survivors are the NEWEST messages, still in order.  With
        # lock-free pre-decode (the default) the payload was released
        # at enqueue; the decoded batch rides the message instead.
        queued = pool._queues[0].snapshot()
        timestamps = [
            (
                m.decoded
                if m.decoded is not None
                else decode_event_batch(m.payload)
            ).ts
            for m in queued
        ]
        assert timestamps == [float(i) for i in range(flood - depth, flood)]
        # Draining after start processes exactly the survivors.
        pool.start()
        pool.drain()
        assert index.get_request_key(flood)  # newest survived
        with pytest.raises(KeyError):
            index.get_request_key(1)  # oldest was shed
        pool.shutdown()

    def test_shutdown_with_full_queue_does_not_block(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = Pool(index, db, PoolConfig(concurrency=1, max_queue_depth=2))
        pool.start()
        pool.drain()
        # Wedge by never starting a second pool; fill its queue, then
        # shutdown must still complete promptly.
        wedged = Pool(
            index, db, PoolConfig(concurrency=1, max_queue_depth=2)
        )
        for i in range(4):
            wedged.add_task(self._message(i))
        wedged._started = True  # simulate started-but-stuck workers
        wedged._threads = []
        wedged.shutdown()  # must not deadlock closing the shard queues
        assert wedged._queues[0]._closed
        # A post-shutdown put is rejected (and counted), never queued.
        wedged.add_task(self._message(99))
        assert wedged._queues[0].qsize() == 2
        pool.shutdown()

    def test_invalid_depth_rejected(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=10))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        with pytest.raises(ValueError):
            Pool(index, db, PoolConfig(concurrency=1, max_queue_depth=0))
