"""Seeded structural fuzz of the KVEvents wire decoder + ingestion pool.

The event stream arrives from the network (ZMQ pub/sub); any pod can send
arbitrary bytes.  Two totality invariants, stronger than the example-based
malformed-input tests in test_kvevents.py:

1. The decoder is *total*: for any payload it either returns a batch or
   raises ``EventDecodeError`` — never any other exception type (a raw
   ``TypeError``/``IndexError`` escaping the codec would kill a pool
   worker thread instead of being counted as a poison pill).
2. The pool survives any storm: garbage payloads — random structures,
   mutated valid batches, type-confused tagged unions — are dropped
   per-event/per-message, and valid events delivered afterwards still
   index correctly (reference behavior: poison pills dropped, never
   retried, pool.go:206-215).

All randomness is seeded: failures reproduce exactly.
"""

import random

import msgpack
import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    EMPTY_BLOCK_HASH,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import InMemoryIndexConfig
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockStored,
    EventBatch,
    EventDecodeError,
    decode_event,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import Message, Pool, PoolConfig

MODEL = "m"


def _random_value(rng: random.Random, depth: int = 0):
    """A random msgpack-encodable value, weighted toward the shapes the
    codec actually inspects (lists with string heads)."""
    kinds = ["int", "str", "bytes", "none", "float", "bool", "list", "dict"]
    if depth >= 3:
        kinds = kinds[:6]
    kind = rng.choice(kinds)
    if kind == "int":
        return rng.randint(-(2**63), 2**64 - 1)
    if kind == "str":
        return rng.choice(
            ["BlockStored", "BlockRemoved", "AllBlocksCleared", "x", ""]
        )
    if kind == "bytes":
        return rng.randbytes(rng.randint(0, 12))
    if kind == "none":
        return None
    if kind == "float":
        # Non-finite values matter: int(float("inf")) raises
        # OverflowError, a distinct escape path from TypeError/ValueError.
        return rng.choice(
            [rng.random() * 1e9, float("inf"), float("-inf"), float("nan")]
        )
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "list":
        return [
            _random_value(rng, depth + 1) for _ in range(rng.randint(0, 5))
        ]
    return {
        str(i): _random_value(rng, depth + 1)
        for i in range(rng.randint(0, 3))
    }


def _assert_total(payload: bytes):
    try:
        decode_event_batch(payload)
    except EventDecodeError:
        pass  # the one sanctioned failure mode


class TestDecoderTotality:
    def test_random_structures(self):
        rng = random.Random(0)
        for _ in range(300):
            _assert_total(msgpack.packb(_random_value(rng)))

    def test_random_raw_bytes(self):
        rng = random.Random(1)
        for _ in range(300):
            _assert_total(rng.randbytes(rng.randint(0, 64)))

    def test_mutated_valid_batches(self):
        """Bit flips / truncations / insertions of a real encoding."""
        rng = random.Random(2)
        valid = EventBatch(
            ts=1.0,
            events=[
                BlockStored(
                    block_hashes=[0xAB, 0xCD],
                    parent_block_hash=None,
                    token_ids=list(range(8)),
                    block_size=4,
                    medium="hbm",
                )
            ],
            data_parallel_rank=1,
        ).encode()
        for _ in range(300):
            buf = bytearray(valid)
            for _ in range(rng.randint(1, 4)):
                op = rng.choice(["flip", "trunc", "insert"])
                if op == "flip" and buf:
                    i = rng.randrange(len(buf))
                    buf[i] ^= 1 << rng.randrange(8)
                elif op == "trunc" and buf:
                    del buf[rng.randrange(len(buf)):]
                else:
                    buf.insert(
                        rng.randrange(len(buf) + 1), rng.randrange(256)
                    )
            _assert_total(bytes(buf))

    def test_type_confused_tagged_unions(self):
        """Well-formed batch framing around events whose fields have the
        wrong types — the decoder may accept or reject, but only with
        EventDecodeError."""
        rng = random.Random(3)
        for _ in range(300):
            event = [rng.choice(
                ["BlockStored", "BlockRemoved", "AllBlocksCleared"]
            )] + [_random_value(rng) for _ in range(rng.randint(0, 8))]
            _assert_total(
                msgpack.packb([1.0, [event], rng.choice([None, 0, 1])])
            )
            try:
                decode_event(event)
            except EventDecodeError:
                pass

    def test_nonfinite_batch_ts_rejected(self):
        """A batch whose ts is nan/inf decodes without error into a
        timestamp that poisons downstream ordering/latency math — it
        must be rejected outright, not merely tolerated."""
        for bad in (float("inf"), float("-inf"), float("nan")):
            with pytest.raises(EventDecodeError):
                decode_event_batch(msgpack.packb([bad, []]))

    def test_nonfinite_numeric_fields(self):
        """int(float('inf')) raises OverflowError — a third escape path
        beyond TypeError/ValueError; pin it explicitly."""
        for bad in (float("inf"), float("-inf"), float("nan")):
            _assert_total(msgpack.packb([1.0, [], bad]))  # dp_rank
            _assert_total(
                msgpack.packb(
                    [1.0, [["BlockStored", [1], None, [1, 2], bad]], None]
                )
            )
            try:
                decode_event(["BlockStored", [1], None, [1, 2], bad])
            except EventDecodeError:
                pass

    def test_random_tagged_unions(self):
        """decode_event itself is total over arbitrary structures."""
        rng = random.Random(5)
        for _ in range(300):
            try:
                decode_event(_random_value(rng))
            except EventDecodeError:
                pass


class TestEngineHashTotality:
    def test_normalizer_raises_only_type_value_errors(self):
        """pool.digest treats TypeError/ValueError from the hash
        normalizer as per-event poison; nothing else may escape."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            engine_hash_to_uint64,
        )

        rng = random.Random(6)
        cases = [_random_value(rng) for _ in range(300)] + [
            True,
            b"",
            float("inf"),
            float("nan"),
            2**200,
            -(2**200),
        ]
        for raw in cases:
            try:
                value = engine_hash_to_uint64(raw)
            except (TypeError, ValueError):
                continue
            assert 0 <= value < 2**64


class TestPoolSurvivesStorm:
    def test_garbage_storm_then_valid_events(self):
        rng = random.Random(4)
        index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = Pool(index, db, PoolConfig(concurrency=2))
        pool.start()
        try:
            payloads = []
            for _ in range(100):
                payloads.append(msgpack.packb(_random_value(rng)))
                payloads.append(rng.randbytes(rng.randint(0, 48)))
                event = ["BlockStored"] + [
                    _random_value(rng) for _ in range(rng.randint(0, 8))
                ]
                payloads.append(msgpack.packb([1.0, [event], None]))
            for i, payload in enumerate(payloads):
                pod = f"pod-{i % 4}"
                pool.add_task(
                    Message(
                        topic=f"kv@{pod}@{MODEL}",
                        payload=payload,
                        pod_identifier=pod,
                        model_name=MODEL,
                    )
                )
            pool.drain()  # storm fully digested, no wedged worker

            # Workers still index valid events after the storm.
            tokens = [1, 2, 3, 4]
            batch = EventBatch(
                ts=2.0,
                events=[
                    BlockStored(
                        block_hashes=[0x77],
                        parent_block_hash=None,
                        token_ids=tokens,
                        block_size=4,
                    )
                ],
            )
            pool.add_task(
                Message(
                    topic="kv@pod-0@" + MODEL,
                    payload=batch.encode(),
                    pod_identifier="pod-0",
                    model_name=MODEL,
                )
            )
            pool.drain()
            keys = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, MODEL
            )
            hits = index.lookup(keys)
            assert hits and "pod-0" in {
                e.pod_identifier for pods in hits.values() for e in pods
            }
        finally:
            pool.shutdown()
