"""kvlint (hack/kvlint) — the project-invariant static analyzer.

Each rule gets at least one positive fixture (the violation is
reported) and one negative fixture (the compliant twin passes); the
CLI contract (``path:line: RULE: message``, exit 0/1) is pinned so
``make kvlint`` output stays machine-parseable; and the tree itself
must be clean — the same invocation CI runs.
"""

import json
import os
import re
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hack.kvlint import check_file  # noqa: E402


def lint(tmp_path, code, name="fixture.py", rules=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    return check_file(str(path), rules)


def rule_ids(findings):
    return [f.rule for f in findings]


class TestKV001LockDiscipline:
    GOOD = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}  # guarded-by: _lock

            def get(self, key):
                with self._lock:
                    return self._data.get(key)

            def _purge_locked(self):
                self._data.clear()
    """

    def test_locked_access_passes(self, tmp_path):
        assert lint(tmp_path, self.GOOD) == []

    def test_unlocked_read_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def peek(self, key):
                return self._data.get(key)
        """,
        )
        assert rule_ids(findings) == ["KV001"]
        assert "_lock" in findings[0].message

    def test_unlocked_write_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def poke(self, key, value):
                self._data[key] = value
        """,
        )
        assert rule_ids(findings) == ["KV001"]

    def test_caller_locked_suffix_and_mark(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def _sweep_locked(self):
                self._data.clear()

            def reset(self):  # kvlint: caller-locked
                self._data.clear()
        """,
        )
        assert findings == []

    def test_closure_does_not_inherit_lock(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def escape(self):
                with self._lock:
                    def cb():
                        return self._data
                    return cb
        """,
        )
        assert rule_ids(findings) == ["KV001"]

    def test_inline_suppression(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def peek(self):
                return self._data  # kvlint: disable=KV001
        """,
        )
        assert findings == []

    def test_condition_guard(self, tmp_path):
        """`with self._cond:` satisfies a guarded-by: _cond attr."""
        findings = lint(
            tmp_path,
            """
            import threading

            class Budget:
                def __init__(self):
                    self._in_flight = 0  # guarded-by: _cond
                    self._cond = threading.Condition()

                def release(self, n):
                    with self._cond:
                        self._in_flight -= n

                def leak(self):
                    return self._in_flight
            """,
        )
        assert rule_ids(findings) == ["KV001"]
        assert "_cond" in findings[0].message


class TestKV002TracerSafety:
    def test_branch_on_traced_param_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
            """,
            name="ops/fixture.py",
        )
        assert rule_ids(findings) == ["KV002"]

    def test_static_and_shape_branches_pass(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("flag",))
            def f(x, flag=False):
                if flag:
                    return x * 2
                if x.shape[0] > 4:
                    return x
                n = len(x)
                if n > 2:
                    return x
                return x + 1
            """,
            name="ops/fixture.py",
        )
        assert findings == []

    def test_pallas_kernel_via_partial(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import functools
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref, *, chunk):
                if chunk > 4:
                    o_ref[...] = x_ref[...]
                t = x_ref[...]
                if t[0] > 0:
                    o_ref[...] = t

            def run(x):
                kernel = functools.partial(_kernel, chunk=8)
                return pl.pallas_call(kernel, out_shape=x)(x)
            """,
            name="ops/fixture.py",
        )
        assert len(findings) == 1  # only the traced-ref branch
        assert findings[0].rule == "KV002"

    def test_host_random_and_time_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import random
            import time
            import jax

            @jax.jit
            def f(x):
                return x * random.random() + time.time()
            """,
            name="models/fixture.py",
        )
        assert rule_ids(findings) == ["KV002", "KV002"]

    def test_out_of_scope_files_ignored(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
            """,
            name="api/fixture.py",
        )
        assert findings == []

    def test_plain_python_not_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def config_check(n):
                if n > 0:
                    return True
                return bool(n)
            """,
            name="ops/fixture.py",
        )
        assert findings == []


class TestKV003CanonicalSerialization:
    def test_msgpack_in_persistence_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import msgpack

            def save(doc):
                return msgpack.packb(doc)
            """,
            name="persistence/fixture.py",
        )
        assert "KV003" in rule_ids(findings)
        assert "cbor_canonical" in findings[0].message

    def test_msgpack_on_the_wire_allowed(self, tmp_path):
        """kvevents/ owns the msgpack wire format (vLLM contract)."""
        findings = lint(
            tmp_path,
            """
            import msgpack

            def decode(payload):
                return msgpack.unpackb(payload)
            """,
            name="kvevents/fixture.py",
        )
        assert findings == []

    def test_pickle_banned_everywhere(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import pickle

            def load(blob):
                return pickle.loads(blob)
            """,
            name="api/fixture.py",
        )
        assert rule_ids(findings) == ["KV003", "KV003"]

    def test_cbor_canonical_module_exempt(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import json

            def debug_dump(doc):
                return json.dumps(doc)
            """,
            name="kvcache/kvblock/cbor_canonical.py",
        )
        assert findings == []


class TestKV004BlockingInAsync:
    def test_sleep_in_async_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import time

            async def handler():
                time.sleep(1)
            """,
        )
        assert rule_ids(findings) == ["KV004"]
        assert "asyncio.sleep" in findings[0].message

    def test_async_sleep_passes(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import asyncio

            async def handler():
                await asyncio.sleep(1)
            """,
        )
        assert findings == []

    def test_sync_socket_and_open_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            async def handler(sock):
                data = sock.recv(1024)
                with open("/tmp/x") as f:
                    return f.read(), data
            """,
        )
        assert sorted(rule_ids(findings)) == ["KV004", "KV004"]

    def test_sync_function_not_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import time

            def worker():
                time.sleep(1)
            """,
        )
        assert findings == []


class TestKV005SwallowedErrors:
    def test_bare_except_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def loop():
                try:
                    work()
                except:
                    pass
            """,
        )
        assert rule_ids(findings) == ["KV005"]
        assert "bare" in findings[0].message

    def test_silent_broad_except_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def loop():
                try:
                    work()
                except Exception:
                    pass
            """,
        )
        assert rule_ids(findings) == ["KV005"]

    def test_logged_broad_except_passes(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def loop():
                try:
                    work()
                except Exception:
                    logger.exception("work failed; continuing")
            """,
        )
        assert findings == []

    def test_narrow_swallow_passes(self, tmp_path):
        """`except queue.Full: pass` is control flow, not error hiding."""
        findings = lint(
            tmp_path,
            """
            import queue

            def push(q, item):
                try:
                    q.put_nowait(item)
                except queue.Full:
                    pass
            """,
        )
        assert findings == []

    def test_del_exempt(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            class Engine:
                def __del__(self):
                    try:
                        self.close()
                    except Exception:
                        pass
            """,
        )
        assert findings == []


def run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "hack.kvlint", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
    )


class TestCLIContract:
    """`path:line: RULE: message` on stdout, exit 0/1 — pinned so the
    Makefile/CI/pre-commit wiring and editors can parse it forever."""

    OUTPUT_RE = re.compile(r"^[^:]+:\d+: KV\d{3}: .+$")

    def test_clean_tree_exits_zero(self):
        proc = run_cli("llm_d_kv_cache_manager_tpu")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert proc.stdout == ""

    def test_violation_output_format(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "def f():\n"
            "    try:\n"
            "        pass\n"
            "    except:\n"
            "        pass\n"
        )
        proc = run_cli("--no-baseline", str(bad))
        assert proc.returncode == 1
        lines = proc.stdout.strip().splitlines()
        assert lines, proc.stderr
        for line in lines:
            assert self.OUTPUT_RE.match(line), line

    def test_seeded_guarded_by_violation_fails(self, tmp_path):
        """Acceptance: an unlocked write to a guarded field in the real
        tree makes the lint fail (the rule has teeth end to end)."""
        src = os.path.join(
            REPO, "llm_d_kv_cache_manager_tpu", "persistence", "journal.py"
        )
        with open(src) as handle:
            code = handle.read()
        seeded = code.replace(
            "    def close(self) -> None:",
            "    def poke(self) -> None:\n"
            "        self._segment_bytes = 0\n"
            "\n"
            "    def close(self) -> None:",
        )
        assert seeded != code
        bad = tmp_path / "journal_seeded.py"
        bad.write_text(seeded)
        proc = run_cli("--no-baseline", str(bad))
        assert proc.returncode == 1
        assert "KV001" in proc.stdout

    def test_rule_filter(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import time\n"
            "async def f():\n"
            "    time.sleep(1)\n"
            "    try:\n"
            "        pass\n"
            "    except:\n"
            "        pass\n"
        )
        proc = run_cli("--no-baseline", "--rules", "KV004", str(bad))
        assert proc.returncode == 1
        assert "KV004" in proc.stdout and "KV005" not in proc.stdout


class TestBaselineWorkflow:
    def test_baselined_finding_suppressed_and_stale_reported(
        self, tmp_path
    ):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "def f():\n"
            "    try:\n"
            "        pass\n"
            "    except:\n"
            "        pass\n"
        )
        baseline = tmp_path / "baseline.txt"
        proc = run_cli(
            "--baseline", str(baseline), "--write-baseline", str(bad)
        )
        assert proc.returncode == 0
        assert baseline.exists()

        proc = run_cli("--baseline", str(baseline), str(bad))
        assert proc.returncode == 0, proc.stdout + proc.stderr

        # fix the violation -> the baseline entry is reported stale
        bad.write_text("def f():\n    return 1\n")
        proc = run_cli("--baseline", str(baseline), str(bad))
        assert proc.returncode == 0
        assert "stale baseline entry" in proc.stderr

    def test_repo_baseline_is_empty(self):
        """The shipped baseline carries no grandfathered findings —
        new violations must be fixed or justified inline, not hidden."""
        path = os.path.join(REPO, "hack", "kvlint", "baseline.txt")
        with open(path) as handle:
            entries = [
                line
                for line in handle
                if line.strip() and not line.startswith("#")
            ]
        assert entries == []


# ---------------------------------------------------------------------------
# Whole-program pass (PR 5): project model + KV006/KV007/KV008
# ---------------------------------------------------------------------------

from hack.kvlint import check_paths  # noqa: E402
from hack.kvlint.model import build_model  # noqa: E402
from hack.kvlint import _parse  # noqa: E402

# Defaults are deliberately minimal AND self-consistent (no documented
# knob or exact metric that a fixture would then fail to read/register
# — the whole-program drift checks cut both ways); tests that need a
# documented surface pass their own markdown.
CONFIG_MD = """\
# Configuration

| Env var | Default | Meaning |
|---|---|---|
"""

KNOB_CONFIG_MD = CONFIG_MD + "| `MY_KNOB` | 1 | a documented knob |\n"

OBS_MD = """\
# Observability

Spans: `tokenize`, `score`.

## Metrics inventory

| metric | labels | meaning |
|---|---|---|
| `persistence_*` | varies | a wildcard family |
"""


def project(tmp_path, files, config_md=CONFIG_MD, obs_md=OBS_MD):
    """Materialize a synthetic project (docs/ + pkg/) and return the
    package path — analyzed directly under the root, so the
    whole-program doc checks arm exactly like the CI invocation."""
    root = tmp_path / "proj"
    (root / "docs").mkdir(parents=True)
    (root / "docs" / "configuration.md").write_text(config_md)
    (root / "docs" / "observability.md").write_text(obs_md)
    pkg = root / "pkg"
    pkg.mkdir()
    for name, code in files.items():
        path = pkg / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(code))
    return pkg


def lint_project(tmp_path, files, rules=None, **docs):
    pkg = project(tmp_path, files, **docs)
    return check_paths([str(pkg)], rules)


class TestProjectModel:
    """Phase 1: the cross-file symbol table the project rules consume."""

    def test_env_reads_through_module_helper(self, tmp_path):
        pkg = project(
            tmp_path,
            {
                "cfg.py": """
                    import os

                    def _env_int(name, default):
                        return int(os.environ.get(name, default))

                    RING = _env_int("RING_SIZE", 256)
                    DIRECT = os.environ["DIRECT_KNOB"]
                    ALSO = os.getenv("GETENV_KNOB")
                """
            },
        )
        sources = [_parse(str(pkg / "cfg.py"))]
        model = build_model(sources, [str(pkg)])
        names = {read.name for read in model.env_reads}
        assert {"RING_SIZE", "DIRECT_KNOB", "GETENV_KNOB"} <= names

    def test_metric_name_resolution_through_fstring(self, tmp_path):
        pkg = project(
            tmp_path,
            {
                "metrics.py": """
                    _NS = "kvtpu"

                    class Counter:
                        def __init__(self, name, doc):
                            pass

                    C = Counter(f"{_NS}_x_total", "doc")
                """
            },
        )
        sources = [_parse(str(pkg / "metrics.py"))]
        model = build_model(sources, [str(pkg)])
        assert [r.name for r in model.metric_registrations] == [
            "kvtpu_x_total"
        ]

    def test_attr_typing_and_subclass_widening(self, tmp_path):
        pkg = project(
            tmp_path,
            {
                "a.py": """
                    import threading

                    class Base:
                        pass

                    class Impl(Base):
                        def __init__(self):
                            self._lock = threading.Lock()

                        def op(self):
                            with self._lock:
                                pass

                    class Holder:
                        def __init__(self, backend: Base):
                            self._backend = backend

                        def go(self):
                            self._backend.op()
                """
            },
        )
        sources = [_parse(str(pkg / "a.py"))]
        model = build_model(sources, [str(pkg)])
        holder = model.classes["Holder"]
        call = holder.methods["go"].calls[0]
        targets = {
            cls.name for cls, _ in model.resolve_call(holder, call)
        }
        # An attr typed as the base resolves to the subclass that
        # defines the method — the documented over-approximation.
        assert targets == {"Impl"}

    def test_docs_surface_parsed(self, tmp_path):
        obs = OBS_MD + "| `x_total` | — | things |\n"
        pkg = project(
            tmp_path,
            {"empty.py": ""},
            config_md=KNOB_CONFIG_MD,
            obs_md=obs,
        )
        sources = [_parse(str(pkg / "empty.py"))]
        model = build_model(sources, [str(pkg)])
        assert model.whole_program
        assert "MY_KNOB" in model.docs.knobs
        assert "x_total" in model.docs.metrics
        assert "persistence_" in model.docs.metric_wildcards
        assert {"tokenize", "score"} <= model.docs.stages


CYCLE_FIXTURE = {
    "a.py": """
        import threading

        class A:
            def __init__(self, b: "B"):
                self._lock = threading.Lock()
                self._b = b

            def bump(self):
                with self._lock:
                    pass

            def kick(self):
                with self._lock:
                    self._b.poke()
    """,
    "b.py": """
        import threading

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._a = None

            def adopt(self, a: "A"):
                self._a = a

            def poke(self):
                with self._lock:
                    self._a.bump()
    """,
}


class TestKV006LockOrder:
    def test_planted_cycle_reported(self, tmp_path):
        findings = lint_project(
            tmp_path, CYCLE_FIXTURE, rules=("KV006",)
        )
        assert findings and set(rule_ids(findings)) == {"KV006"}
        cycles = [f for f in findings if "cycle" in f.message]
        assert len(cycles) == 1
        assert "A._lock" in cycles[0].message
        assert "B._lock" in cycles[0].message

    def test_one_direction_passes(self, tmp_path):
        files = dict(CYCLE_FIXTURE)
        # Break the cycle: B.poke no longer calls back into A.
        files["b.py"] = files["b.py"].replace("self._a.bump()", "pass")
        assert lint_project(tmp_path, files, rules=("KV006",)) == []

    def test_declared_order_contradiction(self, tmp_path):
        files = {
            "x.py": """
                import threading

                # kvlint: lock-order: X._lock < Y._lock

                class X:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def bump(self):
                        with self._lock:
                            pass

                class Y:
                    def __init__(self, x: X):
                        self._lock = threading.Lock()
                        self._x = x

                    def poke(self):
                        with self._lock:
                            self._x.bump()
            """
        }
        findings = lint_project(tmp_path, files, rules=("KV006",))
        assert rule_ids(findings) == ["KV006"]
        assert "contradicting the declared lock order" in findings[0].message

    def test_multi_instance_nesting_needs_ascending(self, tmp_path):
        files = {
            "shard.py": """
                import threading

                class Shard:
                    def __init__(self, peer: "Shard"):
                        self._lock = threading.Lock()
                        self._peer = peer

                    def grab(self):
                        with self._lock:
                            pass

                    def cascade(self):
                        with self._lock:
                            self._peer.grab()
            """
        }
        findings = lint_project(tmp_path, files, rules=("KV006",))
        assert rule_ids(findings) == ["KV006"]
        assert "another instance" in findings[0].message

    def test_ascending_declaration_accepts_nesting(self, tmp_path):
        files = {
            "shard.py": """
                import threading

                # kvlint: lock-order: Shard._lock ascending

                class Shard:
                    def __init__(self, peer: "Shard"):
                        self._lock = threading.Lock()
                        self._peer = peer

                    def grab(self):
                        with self._lock:
                            pass

                    def cascade(self):
                        with self._lock:
                            self._peer.grab()
            """
        }
        assert lint_project(tmp_path, files, rules=("KV006",)) == []

    def test_lexical_nesting_consistent_passes(self, tmp_path):
        files = {
            "n.py": """
                import threading

                class N:
                    def __init__(self):
                        self._outer = threading.Lock()
                        self._inner = threading.Lock()

                    def a(self):
                        with self._outer:
                            with self._inner:
                                pass

                    def b(self):
                        with self._outer:
                            with self._inner:
                                pass
            """
        }
        assert lint_project(tmp_path, files, rules=("KV006",)) == []

    def test_lexical_nesting_inverted_cycle(self, tmp_path):
        files = {
            "n.py": """
                import threading

                class N:
                    def __init__(self):
                        self._outer = threading.Lock()
                        self._inner = threading.Lock()

                    def a(self):
                        with self._outer:
                            with self._inner:
                                pass

                    def b(self):
                        with self._inner:
                            with self._outer:
                                pass
            """
        }
        findings = lint_project(tmp_path, files, rules=("KV006",))
        assert rule_ids(findings) == ["KV006"]
        assert "cycle" in findings[0].message

    def test_multi_item_with_inverted_cycle(self, tmp_path):
        # `with a, b:` nests left to right exactly like the nested
        # form; an inversion written this way must still be a cycle.
        files = {
            "m.py": """
                import threading

                class M:
                    def __init__(self):
                        self._outer = threading.Lock()
                        self._inner = threading.Lock()

                    def a(self):
                        with self._outer, self._inner:
                            pass

                    def b(self):
                        with self._inner, self._outer:
                            pass
            """
        }
        findings = lint_project(tmp_path, files, rules=("KV006",))
        assert rule_ids(findings) == ["KV006"]
        assert "cycle" in findings[0].message

    def test_multi_item_with_consistent_order_passes(self, tmp_path):
        files = {
            "m.py": """
                import threading

                class M:
                    def __init__(self):
                        self._outer = threading.Lock()
                        self._inner = threading.Lock()

                    def a(self):
                        with self._outer, self._inner:
                            pass

                    def b(self):
                        with self._outer:
                            with self._inner:
                                pass
            """
        }
        assert lint_project(tmp_path, files, rules=("KV006",)) == []

    def test_module_level_lock_cycle(self, tmp_path):
        # Module-level functions acquire module locks by bare name;
        # their nesting must feed the graph like any method's.
        files = {
            "g.py": """
                import threading

                _reg_lock = threading.Lock()
                _build_lock = threading.Lock()

                def get():
                    with _reg_lock:
                        with _build_lock:
                            pass

                def rebuild():
                    with _build_lock:
                        with _reg_lock:
                            pass
            """
        }
        findings = lint_project(tmp_path, files, rules=("KV006",))
        assert rule_ids(findings) == ["KV006"]
        assert "cycle" in findings[0].message
        assert "module:" in findings[0].message

    def test_same_named_module_locks_stay_distinct(self, tmp_path):
        # Two `__init__.py` files, each with its own `_a`/`_b` pair
        # nested in opposite directions.  Stem-derived module owners
        # would merge them onto one node pair and invent a cycle that
        # exists in no program; path-derived owners keep them apart.
        files = {
            "alpha/__init__.py": """
                import threading

                _a = threading.Lock()
                _b = threading.Lock()

                def use():
                    with _a:
                        with _b:
                            pass
            """,
            "beta/__init__.py": """
                import threading

                _a = threading.Lock()
                _b = threading.Lock()

                def use():
                    with _b:
                        with _a:
                            pass
            """,
        }
        assert lint_project(tmp_path, files, rules=("KV006",)) == []


class TestKV007ContractDrift:
    def test_undocumented_knob_reported(self, tmp_path):
        findings = lint_project(
            tmp_path,
            {
                "cfg.py": """
                    import os
                    GOOD = os.environ.get("MY_KNOB")
                    BAD = os.environ.get("SECRET_KNOB")
                """
            },
            rules=("KV007",),
            config_md=KNOB_CONFIG_MD,
        )
        assert [f.rule for f in findings] == ["KV007"]
        assert "SECRET_KNOB" in findings[0].message
        assert "MY_KNOB" not in findings[0].message

    def test_doc_only_knob_reported(self, tmp_path):
        config = KNOB_CONFIG_MD + "| `GHOST_KNOB` | — | reads nowhere |\n"
        findings = lint_project(
            tmp_path,
            {
                "cfg.py": """
                    import os
                    GOOD = os.environ.get("MY_KNOB")
                """
            },
            rules=("KV007",),
            config_md=config,
        )
        assert [f.rule for f in findings] == ["KV007"]
        assert "GHOST_KNOB" in findings[0].message
        assert findings[0].path.endswith("configuration.md")

    def test_duplicate_metric_registration(self, tmp_path):
        obs = OBS_MD + "| `x_total` | — | things |\n"
        findings = lint_project(
            tmp_path,
            {
                "m.py": """
                    class Counter:
                        def __init__(self, name, doc):
                            pass

                    A = Counter("kvtpu_x_total", "doc")
                    B = Counter("kvtpu_x_total", "doc")
                """
            },
            rules=("KV007",),
            obs_md=obs,
        )
        assert [f.rule for f in findings] == ["KV007"]
        assert "more than once" in findings[0].message

    def test_undocumented_metric_reported(self, tmp_path):
        findings = lint_project(
            tmp_path,
            {
                "m.py": """
                    class Counter:
                        def __init__(self, name, doc):
                            pass

                    A = Counter("kvtpu_mystery_total", "doc")
                """
            },
            rules=("KV007",),
        )
        assert [f.rule for f in findings] == ["KV007"]
        assert "mystery_total" in findings[0].message

    def test_wildcard_row_covers_family(self, tmp_path):
        assert (
            lint_project(
                tmp_path,
                {
                    "m.py": """
                        class Gauge:
                            def __init__(self, name, doc):
                                pass

                        A = Gauge("kvtpu_persistence_bytes", "doc")
                    """
                },
                rules=("KV007",),
            )
            == []
        )

    def test_documented_metric_never_registered(self, tmp_path):
        obs = OBS_MD + "| `ghost_total` | — | never registered |\n"
        findings = lint_project(
            tmp_path, {"empty.py": ""}, rules=("KV007",), obs_md=obs
        )
        assert [f.rule for f in findings] == ["KV007"]
        assert "ghost_total" in findings[0].message
        assert findings[0].path.endswith("observability.md")

    def test_counter_total_suffix_equivalence(self, tmp_path):
        # Counters register without `_total`; the docs show the
        # exposition name.  Not drift.
        obs = OBS_MD + "| `z_total` | — | things |\n"
        assert (
            lint_project(
                tmp_path,
                {
                    "m.py": """
                        class Counter:
                            def __init__(self, name, doc):
                                pass

                        A = Counter("kvtpu_z", "doc")
                    """
                },
                rules=("KV007",),
                obs_md=obs,
            )
            == []
        )

    def test_stage_vocabulary_drift(self, tmp_path):
        findings = lint_project(
            tmp_path,
            {
                "t.py": """
                    def span(name):
                        pass

                    def work():
                        span("tokenize")
                        span("bogus.stage")
                """
            },
            rules=("KV007",),
        )
        assert [f.rule for f in findings] == ["KV007"]
        assert "bogus.stage" in findings[0].message


class TestKV008ResourceDiscipline:
    def test_leaked_thread_on_self(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class W:
                def start(self):
                    self._t = threading.Thread(target=self.run)
                    self._t.start()
            """,
            rules=("KV008",),
        )
        assert rule_ids(findings) == ["KV008"]
        assert "_t" in findings[0].message

    def test_closer_method_passes(self, tmp_path):
        assert (
            lint(
                tmp_path,
                """
                import threading

                class W:
                    def start(self):
                        self._t = threading.Thread(target=self.run)
                        self._t.start()

                    def stop(self):
                        self._t.join()
                """,
                rules=("KV008",),
            )
            == []
        )

    def test_closer_reachable_through_call_chain(self, tmp_path):
        assert (
            lint(
                tmp_path,
                """
                import threading

                class W:
                    def start(self):
                        self._t = threading.Thread(target=self.run)

                    def close(self):
                        self._halt()

                    def _halt(self):
                        self._t.join()
                """,
                rules=("KV008",),
            )
            == []
        )

    def test_local_assigned_to_self_uses_attr_closer(self, tmp_path):
        assert (
            lint(
                tmp_path,
                """
                import socket

                class C:
                    def connect(self):
                        sock = socket.socket()
                        self._sock = sock

                    def close(self):
                        self._sock.close()
                """,
                rules=("KV008",),
            )
            == []
        )

    def test_returned_local_transfers_ownership(self, tmp_path):
        assert (
            lint(
                tmp_path,
                """
                import socket

                class C:
                    def open_socket(self):
                        sock = socket.socket()
                        return sock
                """,
                rules=("KV008",),
            )
            == []
        )

    def test_purely_local_without_cleanup_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            def kick(run):
                t = threading.Thread(target=run)
                t.start()
            """,
            rules=("KV008",),
        )
        # Module-level function, not a class method: out of scope.
        assert findings == []
        findings = lint(
            tmp_path,
            """
            import threading

            class K:
                def kick(self, run):
                    t = threading.Thread(target=run)
                    t.start()
            """,
            rules=("KV008",),
        )
        assert rule_ids(findings) == ["KV008"]

    def test_unrelated_join_does_not_mask_leak(self, tmp_path):
        # Cleanup calls are receiver-checked: ", ".join(parts) is
        # string formatting, not thread cleanup.
        findings = lint(
            tmp_path,
            """
            import threading

            class K:
                def kick(self, run, parts):
                    t = threading.Thread(target=run)
                    t.start()
                    self._label = ", ".join(parts)
            """,
            rules=("KV008",),
        )
        assert rule_ids(findings) == ["KV008"]
        assert "thread" in findings[0].message

    def test_join_on_the_local_passes(self, tmp_path):
        assert (
            lint(
                tmp_path,
                """
                import threading

                class K:
                    def run_sync(self, run):
                        t = threading.Thread(target=run)
                        t.start()
                        t.join()
                """,
                rules=("KV008",),
            )
            == []
        )

    def test_stop_event_does_not_exempt_sockets(self, tmp_path):
        # The stop-event factory shape bounds a worker *loop*; it says
        # nothing about a socket created alongside it.
        findings = lint(
            tmp_path,
            """
            import socket
            import threading

            class K:
                def kick(self, work):
                    stop = threading.Event()
                    conn = socket.socket()

                    def loop():
                        while not stop.wait(1):
                            work()

                    t = threading.Thread(target=loop)
                    t.start()
                    return stop
            """,
            rules=("KV008",),
        )
        assert rule_ids(findings) == ["KV008"]
        assert "socket" in findings[0].message

    def test_stop_event_factory_shape_passes(self, tmp_path):
        assert (
            lint(
                tmp_path,
                """
                import threading

                class K:
                    def kick(self, work):
                        stop = threading.Event()

                        def loop():
                            while not stop.wait(1):
                                work()

                        t = threading.Thread(target=loop)
                        t.start()
                        return stop
                """,
                rules=("KV008",),
            )
            == []
        )

    def test_appended_to_self_list_needs_closer(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class P:
                def spawn(self):
                    t = threading.Thread(target=self.run)
                    t.start()
                    self._threads.append(t)
            """,
            rules=("KV008",),
        )
        assert rule_ids(findings) == ["KV008"]
        assert (
            lint(
                tmp_path,
                """
                import threading

                class P:
                    def spawn(self):
                        t = threading.Thread(target=self.run)
                        t.start()
                        self._threads.append(t)

                    def shutdown(self):
                        for t in self._threads:
                            t.join()
                """,
                rules=("KV008",),
            )
            == []
        )

    def test_suppression(self, tmp_path):
        assert (
            lint(
                tmp_path,
                """
                import threading

                class W:
                    def start(self):
                        self._t = threading.Thread(target=self.run)  # kvlint: disable=KV008
                """,
                rules=("KV008",),
            )
            == []
        )


class TestWholeProgramCLI:
    def test_planted_cycle_via_cli_format(self, tmp_path):
        pkg = project(tmp_path, CYCLE_FIXTURE)
        proc = run_cli("--no-baseline", "--rules", "KV006", str(pkg))
        assert proc.returncode == 1
        lines = proc.stdout.strip().splitlines()
        assert lines
        for line in lines:
            assert TestCLIContract.OUTPUT_RE.match(line), line
        assert any("KV006" in line for line in lines)

    def test_planted_undocumented_knob_via_cli(self, tmp_path):
        pkg = project(
            tmp_path,
            {
                "cfg.py": """
                    import os
                    BAD = os.environ.get("SECRET_KNOB")
                """
            },
        )
        proc = run_cli("--no-baseline", "--rules", "KV007", str(pkg))
        assert proc.returncode == 1
        assert "KV007" in proc.stdout
        assert "SECRET_KNOB" in proc.stdout


class TestBaselineRulesScoping:
    def test_scoped_write_preserves_other_rules_entries(self, tmp_path):
        """--rules KV005 --write-baseline must not truncate KV008
        entries the scoped run never recomputed (regression)."""
        bad = tmp_path / "bad.py"
        bad.write_text(
            textwrap.dedent(
                """
                import threading

                class W:
                    def start(self):
                        self._t = threading.Thread(target=self.run)

                def f():
                    try:
                        pass
                    except:
                        pass
                """
            )
        )
        baseline = tmp_path / "baseline.txt"
        proc = run_cli(
            "--baseline", str(baseline), "--write-baseline", str(bad)
        )
        assert proc.returncode == 0
        full = baseline.read_text()
        assert "KV005" in full and "KV008" in full

        # Scoped rewrite: only KV005 entries may be regenerated.
        proc = run_cli(
            "--baseline",
            str(baseline),
            "--rules",
            "KV005",
            "--write-baseline",
            str(bad),
        )
        assert proc.returncode == 0
        scoped = baseline.read_text()
        assert "KV005" in scoped and "KV008" in scoped

        # And the combined baseline still grandfathers everything.
        proc = run_cli("--baseline", str(baseline), str(bad))
        assert proc.returncode == 0, proc.stdout + proc.stderr

class TestKV009Atomicity:
    """Check-then-act: a guarded read in one acquisition feeding a
    write in a *separate* acquisition of the same lock."""

    BUGGY = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0  # guarded-by: _lock

            def bump(self):
                with self._lock:
                    current = self._count
                with self._lock:
                    self._count = current + 1
    """

    def test_split_acquisition_flagged(self, tmp_path):
        findings = lint(tmp_path, self.BUGGY, rules=["KV009"])
        assert rule_ids(findings) == ["KV009"]
        assert "_count" in findings[0].message
        assert "separate acquisition" in findings[0].message

    def test_merged_critical_section_passes(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0  # guarded-by: _lock

                def bump(self):
                    with self._lock:
                        current = self._count
                        self._count = current + 1
            """,
            rules=["KV009"],
        )
        assert findings == []

    def test_atomic_ok_mark_suppresses(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0  # guarded-by: _lock

                def bump(self):
                    with self._lock:
                        current = self._count
                    with self._lock:
                        # re-decided under the lock
                        self._count = current + 1  # kvlint: atomic-ok
            """,
            rules=["KV009"],
        )
        assert findings == []

    def test_different_locks_not_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class Split:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()
                    self._a = 0  # guarded-by: _a_lock
                    self._b = 0  # guarded-by: _b_lock

                def move(self):
                    with self._a_lock:
                        value = self._a
                    with self._b_lock:
                        self._b = value
            """,
            rules=["KV009"],
        )
        assert findings == []

    def test_reentrant_nesting_is_one_acquisition(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class Reentrant:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._count = 0  # guarded-by: _lock

                def bump(self):
                    with self._lock:
                        current = self._count
                        with self._lock:
                            self._count = current + 1
            """,
            rules=["KV009"],
        )
        assert findings == []

    def test_mutator_call_counts_as_write(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class Queue:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock

                def requeue(self):
                    with self._lock:
                        head = self._items[0]
                    with self._lock:
                        self._items.append(head)
            """,
            rules=["KV009"],
        )
        assert rule_ids(findings) == ["KV009"]


class TestKV010GilDependence:
    """Unguarded mutation of shared state on a lock-owning class must
    justify itself with `# gil-atomic: <why>`."""

    BUGGY = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}  # guarded-by: _lock
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                if self._thread is None:
                    return
    """

    def test_unguarded_shared_write_flagged(self, tmp_path):
        findings = lint(tmp_path, self.BUGGY, rules=["KV010"])
        assert rule_ids(findings) == ["KV010"]
        assert "_thread" in findings[0].message
        assert "gil-atomic" in findings[0].message

    def test_gil_atomic_annotation_passes(self, tmp_path):
        findings = lint(
            tmp_path,
            self.BUGGY.replace(
                "self._thread = threading.Thread(target=self._run)",
                "self._thread = threading.Thread("
                "target=self._run)  # gil-atomic: lifecycle ref",
            ),
            rules=["KV010"],
        )
        assert findings == []

    def test_locked_write_passes(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._data = {}  # guarded-by: _lock
                    self._thread = None

                def start(self):
                    with self._lock:
                        self._thread = threading.Thread(target=self._run)

                def _run(self):
                    if self._thread is None:
                        return
            """,
            rules=["KV010"],
        )
        assert findings == []

    def test_lockless_class_exempt(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class PlainBox:
                def __init__(self):
                    self._value = None

                def set(self, value):
                    self._value = value

                def get(self):
                    return self._value
            """,
            rules=["KV010"],
        )
        assert findings == []

    def test_unshared_attr_exempt(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class OneMethod:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._data = {}  # guarded-by: _lock
                    self._scratch = 0

                def work(self):
                    self._scratch = 1
            """,
            rules=["KV010"],
        )
        assert findings == []

    def test_sync_primitive_attr_exempt(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import threading

            class Stoppable:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._data = {}  # guarded-by: _lock
                    self._stop = threading.Event()

                def stop(self):
                    self._stop.set()

                def reset(self):
                    self._stop.clear()
            """,
            rules=["KV010"],
        )
        assert findings == []


MANIFEST_FIXTURE = {
    "cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}  # guarded-by: _lock

            def get(self, key):
                with self._lock:
                    return self._data.get(key)

            def _purge_locked(self):
                self._data.clear()
    """
}


class TestRaceguardManifest:
    """--emit-manifest / --check-manifest: phase 1's guarded-by model
    exported byte-deterministically and staleness-pinned."""

    def test_emit_to_stdout_deterministic(self, tmp_path):
        pkg = project(tmp_path, MANIFEST_FIXTURE)
        first = run_cli("--emit-manifest", "-", str(pkg))
        second = run_cli("--emit-manifest", "-", str(pkg))
        assert first.returncode == 0, first.stderr
        assert first.stdout == second.stdout
        manifest = json.loads(first.stdout)
        assert manifest["version"] == 1
        (key, entry), = manifest["classes"].items()
        assert key == "pkg.cache:Cache"
        assert entry["guarded"] == {"_data": "_lock"}
        assert entry["locks"] == ["_lock"]
        assert entry["caller_locked"] == ["_purge_locked"]

    def test_checked_in_manifest_matches_tree(self):
        """The staleness pin CI relies on: the committed manifest is
        regenerated from the committed annotations."""
        proc = run_cli("llm_d_kv_cache_manager_tpu", "--check-manifest")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_annotation_change_without_regen_fails(self, tmp_path):
        pkg = project(tmp_path, MANIFEST_FIXTURE)
        proc = run_cli(str(pkg), "--emit-manifest")
        assert proc.returncode == 0, proc.stderr
        proc = run_cli("--check-manifest", str(pkg))
        assert proc.returncode == 0, proc.stdout + proc.stderr

        # Re-annotate under a different lock without regenerating.
        cache = pkg / "cache.py"
        cache.write_text(
            cache.read_text().replace(
                "# guarded-by: _lock", "# guarded-by: _other_lock"
            )
        )
        proc = run_cli("--check-manifest", str(pkg))
        assert proc.returncode == 1
        assert "stale" in proc.stderr
        assert "pkg.cache:Cache" in proc.stderr

    def test_gil_inventory_emitter(self, tmp_path):
        pkg = project(
            tmp_path,
            {
                "engine.py": """
                    import threading

                    class Engine:
                        def __init__(self):
                            self._lock = threading.Lock()
                            self._data = {}  # guarded-by: _lock
                            self._thread = None

                        def start(self):
                            self._thread = threading.Thread(
                                target=self._run
                            )  # gil-atomic: lifecycle ref

                        def _run(self):
                            if self._thread is None:
                                return
                """
            },
        )
        proc = run_cli("--emit-gil-inventory", "-", str(pkg))
        assert proc.returncode == 0, proc.stderr
        inventory = json.loads(proc.stdout)
        assert inventory["version"] == 1
        (site,) = inventory["sites"]
        assert site["class"] == "Engine"
        assert site["attr"] == "_thread"
        assert site["why"] == "lifecycle ref"


class TestParallelParse:
    """--jobs N: parallel parsing must be byte-identical to
    sequential, findings in the same order."""

    def test_jobs_output_identical(self, tmp_path):
        files = {}
        for index in range(6):
            files[f"mod_{index}.py"] = f"""
                import threading

                class C{index}:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._data = {{}}  # guarded-by: _lock

                    def peek(self):
                        return self._data.get("x")

                def f{index}():
                    try:
                        pass
                    except:
                        pass
            """
        pkg = project(tmp_path, files)
        sequential = run_cli("--no-baseline", str(pkg))
        parallel = run_cli("--no-baseline", "--jobs", "4", str(pkg))
        assert sequential.returncode == 1
        assert parallel.returncode == 1
        assert sequential.stdout == parallel.stdout
        assert sequential.stdout.count("KV001") == 6
        assert sequential.stdout.count("KV005") == 6
