"""kvlint (hack/kvlint) — the project-invariant static analyzer.

Each rule gets at least one positive fixture (the violation is
reported) and one negative fixture (the compliant twin passes); the
CLI contract (``path:line: RULE: message``, exit 0/1) is pinned so
``make kvlint`` output stays machine-parseable; and the tree itself
must be clean — the same invocation CI runs.
"""

import os
import re
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hack.kvlint import check_file  # noqa: E402


def lint(tmp_path, code, name="fixture.py", rules=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    return check_file(str(path), rules)


def rule_ids(findings):
    return [f.rule for f in findings]


class TestKV001LockDiscipline:
    GOOD = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}  # guarded-by: _lock

            def get(self, key):
                with self._lock:
                    return self._data.get(key)

            def _purge_locked(self):
                self._data.clear()
    """

    def test_locked_access_passes(self, tmp_path):
        assert lint(tmp_path, self.GOOD) == []

    def test_unlocked_read_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def peek(self, key):
                return self._data.get(key)
        """,
        )
        assert rule_ids(findings) == ["KV001"]
        assert "_lock" in findings[0].message

    def test_unlocked_write_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def poke(self, key, value):
                self._data[key] = value
        """,
        )
        assert rule_ids(findings) == ["KV001"]

    def test_caller_locked_suffix_and_mark(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def _sweep_locked(self):
                self._data.clear()

            def reset(self):  # kvlint: caller-locked
                self._data.clear()
        """,
        )
        assert findings == []

    def test_closure_does_not_inherit_lock(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def escape(self):
                with self._lock:
                    def cb():
                        return self._data
                    return cb
        """,
        )
        assert rule_ids(findings) == ["KV001"]

    def test_inline_suppression(self, tmp_path):
        findings = lint(
            tmp_path,
            self.GOOD
            + """
            def peek(self):
                return self._data  # kvlint: disable=KV001
        """,
        )
        assert findings == []

    def test_condition_guard(self, tmp_path):
        """`with self._cond:` satisfies a guarded-by: _cond attr."""
        findings = lint(
            tmp_path,
            """
            import threading

            class Budget:
                def __init__(self):
                    self._in_flight = 0  # guarded-by: _cond
                    self._cond = threading.Condition()

                def release(self, n):
                    with self._cond:
                        self._in_flight -= n

                def leak(self):
                    return self._in_flight
            """,
        )
        assert rule_ids(findings) == ["KV001"]
        assert "_cond" in findings[0].message


class TestKV002TracerSafety:
    def test_branch_on_traced_param_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
            """,
            name="ops/fixture.py",
        )
        assert rule_ids(findings) == ["KV002"]

    def test_static_and_shape_branches_pass(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("flag",))
            def f(x, flag=False):
                if flag:
                    return x * 2
                if x.shape[0] > 4:
                    return x
                n = len(x)
                if n > 2:
                    return x
                return x + 1
            """,
            name="ops/fixture.py",
        )
        assert findings == []

    def test_pallas_kernel_via_partial(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import functools
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref, *, chunk):
                if chunk > 4:
                    o_ref[...] = x_ref[...]
                t = x_ref[...]
                if t[0] > 0:
                    o_ref[...] = t

            def run(x):
                kernel = functools.partial(_kernel, chunk=8)
                return pl.pallas_call(kernel, out_shape=x)(x)
            """,
            name="ops/fixture.py",
        )
        assert len(findings) == 1  # only the traced-ref branch
        assert findings[0].rule == "KV002"

    def test_host_random_and_time_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import random
            import time
            import jax

            @jax.jit
            def f(x):
                return x * random.random() + time.time()
            """,
            name="models/fixture.py",
        )
        assert rule_ids(findings) == ["KV002", "KV002"]

    def test_out_of_scope_files_ignored(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
            """,
            name="api/fixture.py",
        )
        assert findings == []

    def test_plain_python_not_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def config_check(n):
                if n > 0:
                    return True
                return bool(n)
            """,
            name="ops/fixture.py",
        )
        assert findings == []


class TestKV003CanonicalSerialization:
    def test_msgpack_in_persistence_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import msgpack

            def save(doc):
                return msgpack.packb(doc)
            """,
            name="persistence/fixture.py",
        )
        assert "KV003" in rule_ids(findings)
        assert "cbor_canonical" in findings[0].message

    def test_msgpack_on_the_wire_allowed(self, tmp_path):
        """kvevents/ owns the msgpack wire format (vLLM contract)."""
        findings = lint(
            tmp_path,
            """
            import msgpack

            def decode(payload):
                return msgpack.unpackb(payload)
            """,
            name="kvevents/fixture.py",
        )
        assert findings == []

    def test_pickle_banned_everywhere(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import pickle

            def load(blob):
                return pickle.loads(blob)
            """,
            name="api/fixture.py",
        )
        assert rule_ids(findings) == ["KV003", "KV003"]

    def test_cbor_canonical_module_exempt(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import json

            def debug_dump(doc):
                return json.dumps(doc)
            """,
            name="kvcache/kvblock/cbor_canonical.py",
        )
        assert findings == []


class TestKV004BlockingInAsync:
    def test_sleep_in_async_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import time

            async def handler():
                time.sleep(1)
            """,
        )
        assert rule_ids(findings) == ["KV004"]
        assert "asyncio.sleep" in findings[0].message

    def test_async_sleep_passes(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import asyncio

            async def handler():
                await asyncio.sleep(1)
            """,
        )
        assert findings == []

    def test_sync_socket_and_open_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            async def handler(sock):
                data = sock.recv(1024)
                with open("/tmp/x") as f:
                    return f.read(), data
            """,
        )
        assert sorted(rule_ids(findings)) == ["KV004", "KV004"]

    def test_sync_function_not_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import time

            def worker():
                time.sleep(1)
            """,
        )
        assert findings == []


class TestKV005SwallowedErrors:
    def test_bare_except_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def loop():
                try:
                    work()
                except:
                    pass
            """,
        )
        assert rule_ids(findings) == ["KV005"]
        assert "bare" in findings[0].message

    def test_silent_broad_except_flagged(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def loop():
                try:
                    work()
                except Exception:
                    pass
            """,
        )
        assert rule_ids(findings) == ["KV005"]

    def test_logged_broad_except_passes(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def loop():
                try:
                    work()
                except Exception:
                    logger.exception("work failed; continuing")
            """,
        )
        assert findings == []

    def test_narrow_swallow_passes(self, tmp_path):
        """`except queue.Full: pass` is control flow, not error hiding."""
        findings = lint(
            tmp_path,
            """
            import queue

            def push(q, item):
                try:
                    q.put_nowait(item)
                except queue.Full:
                    pass
            """,
        )
        assert findings == []

    def test_del_exempt(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            class Engine:
                def __del__(self):
                    try:
                        self.close()
                    except Exception:
                        pass
            """,
        )
        assert findings == []


def run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "hack.kvlint", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
    )


class TestCLIContract:
    """`path:line: RULE: message` on stdout, exit 0/1 — pinned so the
    Makefile/CI/pre-commit wiring and editors can parse it forever."""

    OUTPUT_RE = re.compile(r"^[^:]+:\d+: KV\d{3}: .+$")

    def test_clean_tree_exits_zero(self):
        proc = run_cli("llm_d_kv_cache_manager_tpu")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert proc.stdout == ""

    def test_violation_output_format(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "def f():\n"
            "    try:\n"
            "        pass\n"
            "    except:\n"
            "        pass\n"
        )
        proc = run_cli("--no-baseline", str(bad))
        assert proc.returncode == 1
        lines = proc.stdout.strip().splitlines()
        assert lines, proc.stderr
        for line in lines:
            assert self.OUTPUT_RE.match(line), line

    def test_seeded_guarded_by_violation_fails(self, tmp_path):
        """Acceptance: an unlocked write to a guarded field in the real
        tree makes the lint fail (the rule has teeth end to end)."""
        src = os.path.join(
            REPO, "llm_d_kv_cache_manager_tpu", "persistence", "journal.py"
        )
        with open(src) as handle:
            code = handle.read()
        seeded = code.replace(
            "    def close(self) -> None:",
            "    def poke(self) -> None:\n"
            "        self._segment_bytes = 0\n"
            "\n"
            "    def close(self) -> None:",
        )
        assert seeded != code
        bad = tmp_path / "journal_seeded.py"
        bad.write_text(seeded)
        proc = run_cli("--no-baseline", str(bad))
        assert proc.returncode == 1
        assert "KV001" in proc.stdout

    def test_rule_filter(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import time\n"
            "async def f():\n"
            "    time.sleep(1)\n"
            "    try:\n"
            "        pass\n"
            "    except:\n"
            "        pass\n"
        )
        proc = run_cli("--no-baseline", "--rules", "KV004", str(bad))
        assert proc.returncode == 1
        assert "KV004" in proc.stdout and "KV005" not in proc.stdout


class TestBaselineWorkflow:
    def test_baselined_finding_suppressed_and_stale_reported(
        self, tmp_path
    ):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "def f():\n"
            "    try:\n"
            "        pass\n"
            "    except:\n"
            "        pass\n"
        )
        baseline = tmp_path / "baseline.txt"
        proc = run_cli(
            "--baseline", str(baseline), "--write-baseline", str(bad)
        )
        assert proc.returncode == 0
        assert baseline.exists()

        proc = run_cli("--baseline", str(baseline), str(bad))
        assert proc.returncode == 0, proc.stdout + proc.stderr

        # fix the violation -> the baseline entry is reported stale
        bad.write_text("def f():\n    return 1\n")
        proc = run_cli("--baseline", str(baseline), str(bad))
        assert proc.returncode == 0
        assert "stale baseline entry" in proc.stderr

    def test_repo_baseline_is_empty(self):
        """The shipped baseline carries no grandfathered findings —
        new violations must be fixed or justified inline, not hidden."""
        path = os.path.join(REPO, "hack", "kvlint", "baseline.txt")
        with open(path) as handle:
            entries = [
                line
                for line in handle
                if line.strip() and not line.startswith("#")
            ]
        assert entries == []
