"""Flagship model tests: dense vs paged serving-path equivalence, ring
attention vs dense attention, and the sharded train step.

Runs on the virtual 8-device CPU platform (conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.ops.attention import causal_gqa_attention
from llm_d_kv_cache_manager_tpu.ops.ring_attention import ring_attention
from llm_d_kv_cache_manager_tpu.parallel.mesh import MeshPlan, make_mesh

CFG = llama.LlamaConfig(
    vocab_size=128,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    block_size=4,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def test_forward_shapes(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 128)
    logits = llama.forward(params, tokens, CFG)
    assert logits.shape == (2, 12, 128)
    assert bool(jnp.isfinite(logits).all())


def test_paged_prefill_matches_dense(params):
    B, T = 2, 8
    tokens = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, 128)
    nb = T // CFG.block_size
    kv_pool = jnp.zeros(
        (CFG.n_layers, 16, 2, CFG.block_size, CFG.n_kv_heads, CFG.head_dim),
        jnp.float32,
    )
    table = jnp.arange(B * nb, dtype=jnp.int32).reshape(B, nb)
    paged_logits, kv_pool = llama.prefill_paged(
        params, tokens, kv_pool, table, CFG
    )
    dense_logits = llama.forward(params, tokens, CFG)
    np.testing.assert_allclose(
        np.asarray(paged_logits), np.asarray(dense_logits), rtol=2e-4, atol=2e-4
    )
    assert float(jnp.abs(kv_pool).sum()) > 0  # blocks were written


def test_prefill_continue_matches_dense(params):
    """Prefill a prefix, then continue with the suffix from the cached
    pool; suffix logits must match one dense pass over the whole
    prompt, and the suffix blocks must land in the pool."""
    B, T, P = 2, 12, 8
    tokens = jax.random.randint(jax.random.PRNGKey(7), (B, T), 0, 128)
    nb = T // CFG.block_size + 1  # one spare block for the decode step
    kv_pool = jnp.zeros(
        (CFG.n_layers, 16, 2, CFG.block_size, CFG.n_kv_heads, CFG.head_dim),
        jnp.float32,
    )
    table = jnp.arange(B * nb, dtype=jnp.int32).reshape(B, nb)
    _, kv_pool = llama.prefill_paged(
        params, tokens[:, :P], kv_pool, table[:, : P // CFG.block_size], CFG
    )
    cont_logits, kv_pool = llama.prefill_continue(
        params, tokens[:, P:], kv_pool, table, P, CFG
    )
    dense_logits = llama.forward(params, tokens, CFG)[:, P:]
    np.testing.assert_allclose(
        np.asarray(cont_logits), np.asarray(dense_logits), rtol=2e-4, atol=2e-4
    )
    # Decode on top of the continued pool agrees with dense too.
    next_tok = jnp.argmax(cont_logits[:, -1], -1)
    ctx = jnp.full((B,), T + 1, jnp.int32)
    dec_logits, _ = llama.decode_step(
        params, next_tok, kv_pool, table, ctx, CFG
    )
    seq = jnp.concatenate([tokens, next_tok[:, None]], axis=1)
    dense_last = llama.forward(params, seq, CFG)[:, -1]
    np.testing.assert_allclose(
        np.asarray(dec_logits), np.asarray(dense_last), rtol=2e-4, atol=2e-4
    )


def test_paged_decode_matches_dense(params):
    """Prefill a prompt, decode a few tokens, check each decode logit
    equals the dense forward over the growing sequence."""
    B, T = 2, 8
    max_blocks = 4
    rng = jax.random.PRNGKey(3)
    tokens = jax.random.randint(rng, (B, T), 0, 128)
    kv_pool = jnp.zeros(
        (CFG.n_layers, 32, 2, CFG.block_size, CFG.n_kv_heads, CFG.head_dim),
        jnp.float32,
    )
    table = jnp.arange(B * max_blocks, dtype=jnp.int32).reshape(B, max_blocks)
    logits, kv_pool = llama.prefill_paged(
        params, tokens, kv_pool, table[:, : T // CFG.block_size], CFG
    )

    seq = tokens
    for step in range(3):
        next_tok = jnp.argmax(logits[:, -1] if logits.ndim == 3 else logits, -1)
        seq = jnp.concatenate([seq, next_tok[:, None]], axis=1)
        ctx = jnp.full((B,), seq.shape[1], jnp.int32)
        logits, kv_pool = llama.decode_step(
            params, next_tok, kv_pool, table, ctx, CFG
        )
        dense = llama.forward(params, seq, CFG)[:, -1]
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(dense), rtol=2e-4, atol=2e-4
        )


def test_prefill_chunked_matches_full(params):
    """Chunked prefill (bounded-memory long-prompt path, one compiled
    chunk step with dynamic q_offset) must write the same pool and
    produce the same last-position logits as the one-shot paged
    prefill, and decode must continue off its pool exactly."""
    B, T, C = 2, 32, 8
    tokens = jax.random.randint(
        jax.random.PRNGKey(15), (B, T), 0, CFG.vocab_size
    )
    nb = T // CFG.block_size
    pool = jnp.zeros(
        (
            CFG.n_layers,
            B * nb + B,
            2,
            CFG.block_size,
            CFG.n_kv_heads,
            CFG.head_dim,
        ),
        jnp.float32,
    )
    table = jnp.arange(B * nb, dtype=jnp.int32).reshape(B, nb)

    full_logits, full_pool = llama.prefill_paged(
        params, tokens, pool, table, CFG
    )
    chunk_last, chunk_pool = llama.prefill_chunked(
        params, tokens, jnp.zeros_like(pool), table, CFG, chunk_tokens=C
    )
    np.testing.assert_allclose(
        np.asarray(chunk_last),
        np.asarray(full_logits[:, -1]),
        rtol=2e-4,
        atol=2e-4,
    )
    np.testing.assert_allclose(
        np.asarray(chunk_pool), np.asarray(full_pool), rtol=2e-4, atol=2e-4
    )

    # Decode continues off the chunked pool exactly as off the dense
    # forward (the serving handoff).
    extra = jnp.arange(B, dtype=jnp.int32)[:, None] + B * nb
    table_d = jnp.concatenate([table, extra], axis=1)
    nxt = jnp.argmax(chunk_last, -1)
    seq = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    ctx = jnp.full((B,), T + 1, jnp.int32)
    logits, _ = llama.decode_step(
        params, nxt, chunk_pool, table_d, ctx, CFG
    )
    dense = llama.forward(params, seq, CFG)[:, -1]
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense), rtol=2e-4, atol=2e-4
    )

    # Ragged lengths: prompts padded up to a chunk multiple must get
    # their logits at the TRUE last position, never a pad position —
    # and sequences ending in different chunks both resolve.
    seq_len = jnp.asarray([T - C - 3, T - 1], jnp.int32)
    ragged_last, _ = llama.prefill_chunked(
        params,
        tokens,
        jnp.zeros_like(pool),
        table,
        CFG,
        chunk_tokens=C,
        seq_len=seq_len,
    )
    for b in range(B):
        expect = llama.forward(
            params, tokens[b : b + 1, : int(seq_len[b])], CFG
        )[0, -1]
        np.testing.assert_allclose(
            np.asarray(ragged_last[b]),
            np.asarray(expect),
            rtol=2e-4,
            atol=2e-4,
            err_msg=f"sequence {b}",
        )


def test_ring_attention_matches_dense():
    mesh = make_mesh(MeshPlan(dp=2, sp=4))
    B, T, H, D = 2, 16, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, H, D), jnp.float32)
    ring = ring_attention(q, k, v, mesh)
    dense = causal_gqa_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_ring_attention_gqa_heads():
    mesh = make_mesh(MeshPlan(dp=1, sp=4), devices=jax.devices()[:4])
    B, T, H, Hkv, D = 1, 8, 4, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    ring = ring_attention(q, k, v, mesh, batch_axis=None)
    dense = causal_gqa_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_ring_attention_8way_long_sequence():
    """Full 8-device ring (sp=8): seven ppermute rotations, longer
    sequence than the ring width so each chunk carries several
    positions — the long-context prefill configuration."""
    mesh = make_mesh(MeshPlan(dp=1, sp=8))
    B, T, H, Hkv, D = 1, 128, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    ring = ring_attention(q, k, v, mesh, batch_axis=None)
    dense = causal_gqa_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_stripe_unstripe_roundtrip():
    from llm_d_kv_cache_manager_tpu.ops.ring_attention import (
        stripe,
        unstripe,
    )

    x = jnp.arange(2 * 24 * 3).reshape(2, 24, 3)
    for ring in (2, 4, 8):
        y = unstripe(stripe(x, ring), ring)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    # The layout really interleaves: chunk 0 of a ring-4 stripe holds
    # tokens 0, 4, 8, ...
    s = stripe(x, 4)
    np.testing.assert_array_equal(
        np.asarray(s[:, : 24 // 4]), np.asarray(x[:, ::4])
    )


def test_striped_ring_matches_dense():
    """The load-balanced layout must stay exact: stripe -> ring ->
    unstripe equals dense causal attention (8-way ring, GQA heads)."""
    mesh = make_mesh(MeshPlan(dp=1, sp=8))
    B, T, H, Hkv, D = 1, 64, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    ring = ring_attention(
        q, k, v, mesh, batch_axis=None, striped=True
    )
    dense = causal_gqa_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_flash_ring_matches_dense_both_layouts():
    """The mask-aware flash body (ops/ring_flash_pallas.py, interpret
    mode on CPU) must be exact in BOTH layouts: its per-step partials
    stop at the causal diagonal (striped) or skip fully-masked steps
    (contiguous), and the log-sum-exp merge reassembles the full
    softmax."""
    mesh = make_mesh(MeshPlan(dp=1, sp=8))
    B, T, H, Hkv, D = 1, 64, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(21), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    dense = causal_gqa_attention(q, k, v)
    for striped in (False, True):
        ring = ring_attention(
            q, k, v, mesh,
            batch_axis=None,
            striped=striped,
            impl="flash",
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(ring),
            np.asarray(dense),
            rtol=1e-5,
            atol=1e-5,
            err_msg=f"striped={striped}",
        )


def test_flash_partial_merge_is_flash_attention():
    """Splitting K/V in two, computing flash partials, and merging must
    equal one full-softmax pass (the flash-decoding identity the ring
    steps rely on)."""
    from llm_d_kv_cache_manager_tpu.ops.ring_flash_pallas import (
        flash_partial,
        merge_partials,
        neutral_partial,
        normalize_partial,
    )

    B, T, H, Hkv, D = 1, 32, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(22), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    half = T // 2
    state = merge_partials(
        neutral_partial(q),
        flash_partial(
            q, k[:, :half], v[:, :half],
            causal_offset=None, interpret=True,
        ),
    )
    state = merge_partials(
        state,
        flash_partial(
            q, k[:, half:], v[:, half:],
            causal_offset=None, interpret=True,
        ),
    )
    acc, _, l = state
    merged = normalize_partial(acc, l, q.dtype)
    full = flash_partial(q, k, v, causal_offset=None, interpret=True)
    expected = normalize_partial(full[0], full[2], q.dtype)
    np.testing.assert_allclose(
        np.asarray(merged), np.asarray(expected), rtol=1e-5, atol=1e-5
    )


def test_forward_striped_flash_ring_matches_dense():
    """forward(sp_mesh=..., ring_striped=True, ring_impl="flash") —
    the VERDICT-r4 'striped is unreachable from the model' gap — must
    match the plain dense forward: stripe at entry, balanced flash
    ring per layer, unstripe before logits."""
    cfg = llama.LlamaConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=176,
        dtype="float32",
    )
    params = llama.init_params(jax.random.PRNGKey(23), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(24), (2, 32), 0, 256)
    mesh = make_mesh(MeshPlan(dp=1, sp=8))
    base = llama.forward(params, tokens, cfg)
    for kwargs in (
        dict(ring_striped=True),
        dict(ring_striped=True, ring_impl="flash", ring_interpret=True),
    ):
        out = llama.forward(params, tokens, cfg, sp_mesh=mesh, **kwargs)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(base),
            rtol=5e-4,
            atol=5e-4,
            err_msg=str(kwargs),
        )


def test_ring_attention_bf16_serving_dtype():
    """bf16 inputs (the serving dtype): accumulators are f32 inside, so
    the ring must agree with a dense f32 reference within bf16
    round-off."""
    mesh = make_mesh(MeshPlan(dp=2, sp=4))
    B, T, H, D = 2, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q32 = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k32 = jax.random.normal(ks[1], (B, T, H, D), jnp.float32)
    v32 = jax.random.normal(ks[2], (B, T, H, D), jnp.float32)
    ring = ring_attention(
        q32.astype(jnp.bfloat16),
        k32.astype(jnp.bfloat16),
        v32.astype(jnp.bfloat16),
        mesh,
    )
    assert ring.dtype == jnp.bfloat16
    dense = causal_gqa_attention(q32, k32, v32)
    np.testing.assert_allclose(
        np.asarray(ring, np.float32),
        np.asarray(dense),
        rtol=0.05,
        atol=0.05,
    )


def test_forward_sp_mesh_matches_dense(params):
    """The wired long-context path: forward(sp_mesh=...) runs every
    layer's attention as a ring over sp and must agree with the plain
    dense forward."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshPlan(dp=2, sp=4))
    B, T = 2, 32
    tokens = jax.random.randint(jax.random.PRNGKey(9), (B, T), 0, CFG.vocab_size)
    tokens_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P("dp", "sp"))
    )
    ring_logits = jax.jit(
        lambda p, t: llama.forward(p, t, CFG, sp_mesh=mesh)
    )(params, tokens_sharded)
    dense = llama.forward(params, tokens, CFG)
    np.testing.assert_allclose(
        np.asarray(ring_logits),
        np.asarray(dense),
        rtol=2e-4,
        atol=2e-4,
    )


def test_forward_sp_tp_mesh_matches_dense(params):
    """tp x sp composition: params head-sharded over tp, sequence over
    sp — the ring runs per head-shard (no per-layer all-gather of
    q/k/v) and must still match the dense forward."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshPlan(dp=1, tp=2, sp=2), jax.devices()[:4])
    pspecs = llama.param_pspecs(CFG)
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(10), (2, 32), 0, CFG.vocab_size
    )
    tokens_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P(None, "sp"))
    )
    ring_logits = jax.jit(
        lambda p, t: llama.forward(p, t, CFG, sp_mesh=mesh)
    )(sharded, tokens_sharded)
    dense = llama.forward(params, tokens, CFG)
    np.testing.assert_allclose(
        np.asarray(ring_logits),
        np.asarray(dense),
        rtol=2e-4,
        atol=2e-4,
    )


def test_forward_sp_tp_mesh_flash_striped_matches_dense(params):
    """The tp x sp composition must hold for the mask-aware flash body
    too: per head-shard the partial kernel sees H/tp q-heads and
    Hkv/tp kv-heads (GQA group count preserved), the striped layout
    rides the same sp sharding, and the result still matches dense."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshPlan(dp=1, tp=2, sp=2), jax.devices()[:4])
    pspecs = llama.param_pspecs(CFG)
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(10), (2, 32), 0, CFG.vocab_size
    )
    tokens_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P(None, "sp"))
    )
    dense = llama.forward(params, tokens, CFG)
    for striped in (False, True):
        ring_logits = jax.jit(
            lambda p, t, s=striped: llama.forward(
                p,
                t,
                CFG,
                sp_mesh=mesh,
                ring_striped=s,
                ring_impl="flash",
                ring_interpret=True,
            )
        )(sharded, tokens_sharded)
        np.testing.assert_allclose(
            np.asarray(ring_logits),
            np.asarray(dense),
            rtol=2e-4,
            atol=2e-4,
            err_msg=f"striped={striped}",
        )


def test_sharded_train_step_params_stay_finite(params):
    """Regression: under combined sp x tp sharding, the old
    slice-to-[B, T-1] loss made XLA pad the short sequence shard and
    the padded-lane softmax backward wrote NaN into the target token's
    embedding row — invisible to the loss (computed pre-update).  Every
    post-step param must be finite, and the sharded loss must equal the
    unsharded one."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshPlan(dp=1, tp=2, sp=2), jax.devices()[:4])
    pspecs = llama.param_pspecs(CFG)
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    )
    optimizer = llama.make_optimizer()
    opt_state = optimizer.init(sharded)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 32), 0, CFG.vocab_size)
    tokens_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P("dp", "sp"))
    )
    step = jax.jit(
        lambda p, o, t: llama.train_step(p, o, t, CFG, optimizer)
    )
    new_params, _, loss = step(sharded, opt_state, tokens_sharded)
    for leaf in jax.tree.leaves(new_params):
        assert bool(jnp.all(jnp.isfinite(leaf)))
    unsharded_loss = llama.loss_fn(params, tokens, CFG)
    np.testing.assert_allclose(
        float(loss), float(unsharded_loss), rtol=1e-5
    )


def test_train_step_runs_and_improves(params):
    optimizer = llama.make_optimizer(1e-2)
    p = jax.tree.map(lambda x: x, params)
    opt_state = optimizer.init(p)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 16), 0, 128)
    first = None
    for _ in range(5):
        p, opt_state, loss = llama.train_step(
            p, opt_state, tokens, CFG, optimizer
        )
        first = first if first is not None else float(loss)
    assert float(loss) < first
