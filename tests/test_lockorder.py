"""Runtime lock-order watchdog (utils/lockorder.py) — the dynamic half
of kvlint KV006.

These tests pin the watchdog's contract: identity passthrough when
disabled (the production path never pays for it), and a
LockOrderViolation — not a deadlock — for every class of ordering bug
the static rule reasons about: pair inversion, unranked or descending
same-name nesting, and same-instance re-acquisition of a non-reentrant
lock.  Declarations are module-global, so each test builds its own
names and restores the registries on the way out.
"""

import threading

import pytest

from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.lockorder import (
    LockOrderViolation,
    TrackedLock,
)


@pytest.fixture(autouse=True)
def armed_watchdog():
    """Enable the watchdog and snapshot/restore the declaration
    registries so tests can declare throwaway orders without wiping the
    import-time declarations the rest of the suite relies on."""
    previous = lockorder.enable(True)
    pairs = set(lockorder._ordered_pairs)
    ascending = set(lockorder._ascending)
    try:
        yield
    finally:
        lockorder.enable(previous)
        lockorder._ordered_pairs.clear()
        lockorder._ordered_pairs.update(pairs)
        lockorder._ascending.clear()
        lockorder._ascending.update(ascending)


class TestGating:
    def test_disabled_returns_lock_unchanged(self):
        lockorder.enable(False)
        lock = threading.Lock()
        assert lockorder.tracked(lock, "X._lock") is lock

    def test_enabled_wraps(self):
        lock = lockorder.tracked(threading.Lock(), "X._lock")
        assert isinstance(lock, TrackedLock)
        assert lock.name == "X._lock"

    def test_wrapper_proxies_context_manager_and_locked(self):
        lock = lockorder.tracked(threading.Lock(), "X._lock")
        with lock:
            assert lock.locked()
        assert not lock.locked()


class TestPairOrder:
    def test_declared_direction_passes(self):
        lockorder.declare_order("T.outer", "T.inner")
        outer = lockorder.tracked(threading.Lock(), "T.outer")
        inner = lockorder.tracked(threading.Lock(), "T.inner")
        with outer:
            with inner:
                assert [name for name, _ in lockorder.held()] == [
                    "T.outer",
                    "T.inner",
                ]
        assert lockorder.held() == []

    def test_inversion_raises(self):
        lockorder.declare_order("T.outer", "T.inner")
        outer = lockorder.tracked(threading.Lock(), "T.outer")
        inner = lockorder.tracked(threading.Lock(), "T.inner")
        with inner:
            with pytest.raises(LockOrderViolation, match="declared order"):
                outer.acquire()
        # The failed acquire must not leave a phantom hold behind.
        assert lockorder.held() == []

    def test_violation_is_assertion_error(self):
        # Storm tests assert-on-failure; the watchdog must feed that.
        assert issubclass(LockOrderViolation, AssertionError)


class TestAscending:
    def test_ascending_ranks_pass(self):
        lockorder.declare_ascending("T.shard")
        shards = [
            lockorder.tracked(threading.Lock(), "T.shard", rank=i)
            for i in range(4)
        ]
        with shards[0], shards[2], shards[3]:
            pass

    def test_descending_ranks_raise(self):
        lockorder.declare_ascending("T.shard")
        lo = lockorder.tracked(threading.Lock(), "T.shard", rank=1)
        hi = lockorder.tracked(threading.Lock(), "T.shard", rank=2)
        with hi:
            with pytest.raises(LockOrderViolation, match="ascending"):
                lo.acquire()

    def test_equal_rank_raises(self):
        lockorder.declare_ascending("T.shard")
        a = lockorder.tracked(threading.Lock(), "T.shard", rank=1)
        b = lockorder.tracked(threading.Lock(), "T.shard", rank=1)
        with a:
            with pytest.raises(LockOrderViolation):
                b.acquire()

    def test_unranked_nesting_raises(self):
        lockorder.declare_ascending("T.shard")
        a = lockorder.tracked(threading.Lock(), "T.shard")
        b = lockorder.tracked(threading.Lock(), "T.shard")
        with a:
            with pytest.raises(LockOrderViolation):
                b.acquire()

    def test_undeclared_same_name_nesting_raises(self):
        a = lockorder.tracked(threading.Lock(), "T.undeclared", rank=0)
        b = lockorder.tracked(threading.Lock(), "T.undeclared", rank=1)
        with a:
            with pytest.raises(LockOrderViolation, match="ascending"):
                b.acquire()


class TestReacquisition:
    def test_plain_lock_self_reacquire_raises(self):
        lock = lockorder.tracked(threading.Lock(), "T.lock")
        with lock:
            with pytest.raises(
                LockOrderViolation, match="self-deadlocks"
            ):
                lock.acquire()

    def test_rlock_reenters_freely(self):
        lock = lockorder.tracked(threading.RLock(), "T.rlock")
        with lock:
            with lock:
                pass
        assert lockorder.held() == []

    def test_condition_wait_notify_flow(self):
        cond = lockorder.tracked(threading.Condition(), "T.cond")
        hits = []

        def waiter():
            with cond:
                while not hits:
                    cond.wait(1.0)

        thread = threading.Thread(target=waiter)
        thread.start()
        with cond:
            hits.append(1)
            cond.notify_all()
        thread.join(timeout=5)
        assert not thread.is_alive()


class TestThreadIsolation:
    def test_held_stacks_are_per_thread(self):
        lockorder.declare_order("T.a", "T.b")
        a = lockorder.tracked(threading.Lock(), "T.a")
        b = lockorder.tracked(threading.Lock(), "T.b")
        errors = []

        def other():
            # This thread holds nothing: acquiring b alone is legal
            # even while the main thread holds a.
            try:
                with b:
                    pass
            except LockOrderViolation as exc:  # pragma: no cover
                errors.append(exc)

        with a:
            thread = threading.Thread(target=other)
            thread.start()
            thread.join(timeout=5)
        assert not errors

    def test_storm_catches_planted_inversion(self):
        """End-to-end: threads taking two locks in opposite orders —
        the bug class the storms would only hit as a rare hang — is
        caught deterministically as a violation by whichever thread
        runs the inverted path."""
        lockorder.declare_order("T.first", "T.second")
        first = lockorder.tracked(threading.Lock(), "T.first")
        second = lockorder.tracked(threading.Lock(), "T.second")
        caught = []

        def inverted():
            try:
                with second:
                    with first:
                        pass
            except LockOrderViolation as exc:
                caught.append(exc)

        thread = threading.Thread(target=inverted)
        thread.start()
        thread.join(timeout=5)
        assert len(caught) == 1


class TestProductionDeclarations:
    """The shipped modules' import-time declarations drive real
    structures correctly under the watchdog."""

    def test_sharded_index_cross_shard_ops(self):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            InMemoryIndexConfig,
            PodEntry,
        )

        index = InMemoryIndex(InMemoryIndexConfig(size=64, shards=4))
        pod = PodEntry("pod-a", "hbm")
        keys = list(range(16))
        index.add(keys, keys, [pod])
        index.lookup(keys)
        entries, engine_map = index.dump_entries()
        assert entries
        index.restore_entries(entries, engine_map)
        assert index.purge_pod("pod-a") > 0

    def test_persistence_snapshot_nesting(self, tmp_path):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            InMemoryIndexConfig,
            PodEntry,
        )
        from llm_d_kv_cache_manager_tpu.persistence.recovery import (
            PersistenceConfig,
            PersistenceManager,
        )

        manager = PersistenceManager(
            PersistenceConfig(directory=str(tmp_path))
        )
        index = InMemoryIndex(InMemoryIndexConfig(size=64))
        index.add([1], [1], [PodEntry("pod-a", "hbm")])
        info = manager.snapshot(index)
        assert info.block_keys == 1
        assert manager.status()["snapshot_path"] == info.path
