"""Mesh plans: resolution rules, canonical order, hybrid construction."""

import jax
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshPlan,
    make_hybrid_mesh,
    make_mesh,
)


class TestMeshPlan:
    def test_free_axis_absorbs_remainder(self):
        sizes = MeshPlan(dp=-1, tp=2, sp=2).resolve(8)
        assert sizes["dp"] == 2 and sizes["tp"] == 2 and sizes["sp"] == 2

    def test_exact_product_required_without_free_axis(self):
        assert MeshPlan(dp=2, tp=4).resolve(8)["tp"] == 4
        with pytest.raises(ValueError):
            MeshPlan(dp=2, tp=3).resolve(8)

    def test_indivisible_rejected(self):
        with pytest.raises(ValueError):
            MeshPlan(dp=-1, tp=3).resolve(8)

    def test_two_free_axes_rejected(self):
        with pytest.raises(ValueError):
            MeshPlan(dp=-1, tp=-1).resolve(8)


class TestMakeMesh:
    def test_canonical_axis_order(self):
        mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2), jax.devices()[:8])
        assert mesh.axis_names == AXIS_ORDER
        assert mesh.shape["dp"] == 2
        assert mesh.shape["tp"] == 2

    def test_collective_runs_on_mesh(self):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices()[:8])
        x = jax.device_put(
            jnp.arange(16.0).reshape(8, 2),
            NamedSharding(mesh, P("dp", "tp")),
        )
        total = jax.jit(jnp.sum)(x)
        assert float(total) == float(np.arange(16.0).sum())


class TestHybridMesh:
    def test_single_process_degenerates_to_flat(self):
        """On one host the hybrid mesh merges ici x dcn degrees per
        axis (real multi-host needs jax.distributed.initialize)."""
        mesh = make_hybrid_mesh(
            ici_plan=MeshPlan(dp=1, tp=4, sp=2),
            dcn_plan=MeshPlan(dp=1),
        )
        assert mesh.shape["tp"] == 4
        assert mesh.shape["sp"] == 2
        assert mesh.axis_names == AXIS_ORDER

    def test_defaults_use_all_devices(self):
        mesh = make_hybrid_mesh()
        assert int(np.prod(list(mesh.shape.values()))) == len(
            jax.devices()
        )


class TestMeshContext:
    """activate() / mesh_is_active(): one place decides between
    jax.set_mesh (modern) and the legacy ``with mesh:`` env."""

    def test_inactive_outside_any_context(self):
        from llm_d_kv_cache_manager_tpu.parallel.mesh import mesh_is_active

        assert not mesh_is_active()

    def test_activate_enters_and_exits(self):
        from llm_d_kv_cache_manager_tpu.parallel.mesh import (
            activate,
            mesh_is_active,
        )

        mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices()[:8])
        with activate(mesh):
            assert mesh_is_active()
        assert not mesh_is_active()

    def test_legacy_with_mesh_still_detected(self):
        from llm_d_kv_cache_manager_tpu.parallel.mesh import mesh_is_active

        mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices()[:8])
        with mesh:
            assert mesh_is_active()
        assert not mesh_is_active()

    def test_sharding_constraint_resolves_under_activate(self):
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from llm_d_kv_cache_manager_tpu.parallel.mesh import activate

        mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices()[:8])
        with activate(mesh):
            y = jax.jit(
                lambda v: lax.with_sharding_constraint(v, P("dp", "tp"))
            )(jnp.ones((8, 2)))
        assert float(y.sum()) == 16.0
