"""/metrics endpoint + collector-helper coverage.

Asserts the Prometheus exposition contract through the booted HTTP
service: content type, that a scoring request moves
``index_lookup_requests``, that a traced request materializes the
``kvtpu_stage_latency_seconds`` histogram with the expected stage
label values, and that ``tokenization_latency`` carries sub-millisecond
buckets.  Also pins the ``counter_total``/``gauge_value`` helpers that
the metrics beat relies on (the old ``collect()[0].samples[0]`` read
crashed on labeled counters).
"""

from __future__ import annotations

import json
import logging
import re
import tempfile
import threading
import time
import urllib.request

import pytest
from prometheus_client import CollectorRegistry, Counter, Gauge

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    MAX_LABEL_LEN,
    METRICS,
    counter_total,
    gauge_total,
    gauge_value,
    install_gc_metrics,
    safe_label,
    start_metrics_logging,
    uninstall_gc_metrics,
    update_process_metrics,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json

MODEL = "test-model"
BLOCK_SIZE = 4
PROMPT = "the quick brown fox jumps over the lazy dog . " * 8
SAMPLED_TP = "00-" + "1f" * 16 + "-" + "2d" * 8 + "-01"


@pytest.fixture()
def service():
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            # InstrumentedIndex wrapper: lookups feed the counters.
            kvblock_index_config=IndexConfig(enable_metrics=True),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
            # Composite tokenizer via auto-discovery so the real
            # tokenization_latency{tokenizer=...} path is exercised.
            local_tokenizers_dir=tokenizer_dir,
        )
    )
    indexer.run()
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    yield base
    server.shutdown()
    indexer.shutdown()


def fetch_metrics(base):
    with urllib.request.urlopen(base + "/metrics", timeout=10) as response:
        return response.headers.get("Content-Type"), response.read().decode()


def score(base, headers=None):
    request = urllib.request.Request(
        base + "/score_completions",
        data=json.dumps({"prompt": PROMPT, "model": MODEL}).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=10) as response:
        return json.load(response)


def sample_value(text, name, label_substr=""):
    """Sum of exposition samples matching name (+ label substring)."""
    total, seen = 0.0, False
    for line in text.splitlines():
        if line.startswith(name) and label_substr in line:
            total += float(line.rsplit(" ", 1)[1])
            seen = True
    return total if seen else None


class TestMetricsEndpoint:
    def test_exposition_content_type(self, service):
        content_type, _ = fetch_metrics(service)
        assert content_type.startswith("text/plain")
        assert "version=0.0.4" in content_type

    def test_scoring_request_moves_lookup_counter(self, service):
        name = "kvtpu_kvcache_index_lookup_requests_total"
        _, before_text = fetch_metrics(service)
        before = sample_value(before_text, name) or 0.0
        score(service)
        _, after_text = fetch_metrics(service)
        assert sample_value(after_text, name) == before + 1

    def test_stage_histogram_appears_with_stage_labels(self, service):
        name = "kvtpu_stage_latency_seconds_count"
        _, before_text = fetch_metrics(service)
        before = {
            stage: sample_value(before_text, name, f'stage="{stage}"')
            or 0.0
            for stage in ("tokenize", "hash_blocks", "index_lookup", "score")
        }
        # A sampled traceparent forces the trace that feeds the
        # histogram regardless of TRACE_SAMPLE_RATE.
        score(service, headers={"traceparent": SAMPLED_TP})
        _, after_text = fetch_metrics(service)
        for stage, prior in before.items():
            observed = sample_value(after_text, name, f'stage="{stage}"')
            assert observed == prior + 1, stage

    def test_tokenization_latency_has_sub_ms_buckets(self, service):
        score(service)
        _, text = fetch_metrics(service)
        bucket_lines = [
            line
            for line in text.splitlines()
            if line.startswith("kvtpu_tokenization_latency_seconds_bucket")
        ]
        assert bucket_lines, "histogram never observed"
        les = {
            re.search(r'le="([^"]+)"', line).group(1)
            for line in bucket_lines
        }
        # Sub-millisecond resolution (Prometheus defaults start at 5ms).
        assert {"5e-05", "0.0001", "0.00025", "0.0005", "0.001"} <= les


class TestExpositionHardening:
    """Label values are wire input on the pod-labeled families: the
    text format's escaping (backslash, double-quote, newline) must
    round-trip through the real exposition path, and scrapes must be
    consistent under concurrent writes."""

    def test_label_values_escaped_per_text_format(self, service):
        # Through the process-global registry the service actually
        # exposes: a hostile pod name exercising all three escaped
        # characters.  safe_label (the wire-ingestion guard) passes
        # printable backslash/quote through untouched, so the
        # exposition layer is what must escape them.
        hostile = 'pod"quote\\back'
        assert safe_label(hostile) == hostile
        METRICS.kvevents_pod_shed.labels(pod=safe_label(hostile)).inc()
        _, text = fetch_metrics(service)
        # Prometheus text format: \ -> \\ then " -> \" inside quotes.
        assert 'pod="pod\\"quote\\\\back"' in text

    def test_newline_label_escaped_at_exposition(self):
        # Escaping contract pinned at the library boundary: a raw
        # newline in a label value (safe_label strips these from wire
        # input, but embedders can label with anything) must come out
        # as the two-character escape, never a literal line break that
        # corrupts the exposition.
        from prometheus_client import generate_latest

        registry = CollectorRegistry()
        counter = Counter("t_esc", "d.", ("who",), registry=registry)
        counter.labels(who="a\nb").inc()
        text = generate_latest(registry).decode()
        assert 'who="a\\nb"' in text
        sample_lines = [
            line for line in text.splitlines() if line.startswith("t_esc")
        ]
        assert all("a\\nb" in line for line in sample_lines if "who" in line)

    def test_safe_label_bounds_and_sanitizes(self):
        assert safe_label("pod-7") == "pod-7"
        cleaned = safe_label("a\x00b\x1fc\x7fd")
        assert "\x00" not in cleaned and "\x7f" not in cleaned
        assert cleaned == "a�b�c�d"
        long = safe_label("x" * 1000)
        assert len(long) == MAX_LABEL_LEN
        assert long.endswith("…")

    def test_concurrent_scrape_vs_write_contract(self, service):
        """Scrapes while labeled families churn must always parse: every
        sample line is name{labels} value, no torn lines, no duplicate
        HELP/TYPE per family — the contract a Prometheus server relies
        on."""
        stop = threading.Event()
        errors = []

        def writer(i):
            n = 0
            while not stop.is_set():
                METRICS.kvevents_pod_shed.labels(pod=f"w{i}-pod{n % 7}").inc()
                METRICS.kvevents_pod_backlog.labels(
                    pod=f"w{i}-pod{n % 7}"
                ).set(n)
                METRICS.kvevents_dropped.labels(reason="queue_full").inc()
                n += 1

        threads = [
            threading.Thread(target=writer, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        try:
            line_re = re.compile(
                r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? '
                r"[-+0-9.eEinfNa]+$"
            )
            for _ in range(10):
                _, text = fetch_metrics(service)
                seen_help = set()
                for line in text.splitlines():
                    if not line:
                        continue
                    if line.startswith("# HELP "):
                        name = line.split(" ", 3)[2]
                        if name in seen_help:
                            errors.append(f"duplicate HELP for {name}")
                        seen_help.add(name)
                        continue
                    if line.startswith("#"):
                        continue
                    if not line_re.match(line):
                        errors.append(f"unparseable sample line: {line!r}")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
        assert not errors, errors[:5]


class TestCollectorHelpers:
    def test_counter_total_sums_labeled_counter(self):
        registry = CollectorRegistry()
        counter = Counter(
            "t_dropped", "d.", ("reason",), registry=registry
        )
        assert counter_total(counter) == 0.0  # no children yet
        counter.labels(reason="a").inc(2)
        counter.labels(reason="b").inc(3)
        assert counter_total(counter) == 5.0

    def test_counter_total_unlabeled(self):
        registry = CollectorRegistry()
        counter = Counter("t_plain", "d.", registry=registry)
        counter.inc(4)
        assert counter_total(counter) == 4.0

    def test_gauge_value(self):
        registry = CollectorRegistry()
        gauge = Gauge("t_gauge", "d.", registry=registry)
        assert gauge_value(gauge) == 0.0
        gauge.set(17)
        assert gauge_value(gauge) == 17.0

    def test_beat_survives_labeled_counters_and_reports_drops(self):
        """The beat line must not crash on the labeled kvevents_dropped
        counter (the bug this satellite fixes) and must include the
        dropped-events and journal-lag fields."""
        METRICS.kvevents_dropped.labels(reason="queue_full").inc()
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = Capture()
        beat_logger = logging.getLogger("kvtpu.metrics")
        beat_logger.addHandler(handler)
        stop = start_metrics_logging(0.05)
        try:
            deadline = time.time() + 5
            while not records and time.time() < deadline:
                time.sleep(0.02)
        finally:
            stop.set()
            beat_logger.removeHandler(handler)
        assert records, "beat never fired"
        assert "dropped_events=" in records[0]
        assert "journal_lag=" in records[0]
        # The process block rides the same line (ISSUE 14: the leak
        # telltales climb minutes before anything else degrades).
        assert "rss_mb=" in records[0]
        assert "threads=" in records[0]
        assert "gc=" in records[0]

    def test_gauge_total_sums_labeled_gauge(self):
        registry = CollectorRegistry()
        gauge = Gauge("t_backlog", "d.", ("pod",), registry=registry)
        assert gauge_total(gauge) == 0.0
        gauge.labels(pod="a").set(3)
        gauge.labels(pod="b").set(4)
        assert gauge_total(gauge) == 7.0


class TestProcessRuntimeMetrics:
    def test_update_sets_gauges(self):
        values = update_process_metrics()
        # Linux CI/dev boxes have /proc; the gauges mirror the dict.
        assert values["rss_bytes"] > 0
        assert values["open_fds"] > 0
        assert values["threads"] >= 1
        assert gauge_value(METRICS.process_rss) == values["rss_bytes"]
        assert gauge_value(METRICS.process_threads) == values["threads"]

    def test_gc_callbacks_count_collections(self):
        import gc

        assert install_gc_metrics()
        assert install_gc_metrics()  # idempotent
        try:
            before = counter_total(METRICS.gc_collections)
            pause_before = METRICS.gc_pause.collect()[0].samples
            gc.collect()
            after = counter_total(METRICS.gc_collections)
            assert after > before
            # The pause histogram observed the pass (its _count grew).
            def hist_count(samples):
                return sum(
                    s.value
                    for s in samples
                    if s.name.endswith("_count")
                )

            assert hist_count(
                METRICS.gc_pause.collect()[0].samples
            ) > hist_count(pause_before)
            # Generation label rides the forced full collection.
            text = METRICS.exposition().decode()
            assert 'kvtpu_gc_collections_total{gen="2"}' in text
        finally:
            uninstall_gc_metrics()

    def test_process_gauges_exposed(self):
        update_process_metrics()
        text = METRICS.exposition().decode()
        assert "kvtpu_process_rss_bytes" in text
        assert "kvtpu_process_open_fds" in text
        assert "kvtpu_process_threads" in text
