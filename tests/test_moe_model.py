"""MoE model family: routing invariants, dense equivalence, ep sharding."""

import jax
import jax.numpy as jnp
import numpy as np

from llm_d_kv_cache_manager_tpu.models import moe
from llm_d_kv_cache_manager_tpu.parallel.mesh import MeshPlan, make_mesh

CFG = moe.MoEConfig(
    vocab_size=512,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    n_experts=4,
    top_k=2,
)


def test_forward_shapes_and_finite():
    params = moe.init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)
    logits, aux = jax.jit(
        lambda p, t: moe.forward(p, t, CFG, use_flash=False)
    )(params, tokens)
    assert logits.shape == (2, 16, 512)
    assert bool(jnp.all(jnp.isfinite(logits)))
    assert float(aux) > 0  # balanced routing gives aux ~= 1


def test_forward_ring_matches_dense():
    """Long-context prefill for the MoE family: ring attention over an
    sp mesh (contiguous layout; striped is llama-only because MoE
    capacity routing is token-order-sensitive) must match the dense
    forward — einsum body and mask-aware flash body both.

    RESOLVED (was xfail): per-layer activation diffs localized the
    divergence to layer 1's expert MLP under bf16 — ring attention
    from identical input is bit-exact and router top-k picks never
    flip; the ring's different reduction order just rounds the last
    bf16 ulp of the attention output, and the expert MLP amplifies
    that ulp layer over layer (~19% of logits by layer 2).  Not a
    handoff bug: numerical-equivalence belongs in f32, exactly like
    llama's multi-layer ring test (dtype="float32" there too); the
    bf16 serving dtype keeps its own round-off-tolerance coverage in
    test_llama_model.py::test_ring_attention_bf16_serving_dtype."""
    cfg = moe.MoEConfig(
        vocab_size=512,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        n_experts=4,
        top_k=2,
        dtype="float32",
    )
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 512)
    mesh = make_mesh(MeshPlan(dp=1, sp=8), devices=jax.devices()[:8])
    dense, dense_aux = moe.forward(params, tokens, cfg, use_flash=False)
    for impl, interpret in (("einsum", False), ("flash", True)):
        logits, aux = jax.jit(
            lambda p, t, i=impl, ip=interpret: moe.forward(
                p, t, cfg, sp_mesh=mesh, ring_impl=i, ring_interpret=ip
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(logits),
            np.asarray(dense),
            rtol=2e-4,
            atol=2e-4,
            err_msg=impl,
        )
        np.testing.assert_allclose(
            float(aux), float(dense_aux), rtol=1e-5
        )


class TestRouting:
    def setup_method(self):
        rng = np.random.default_rng(0)
        self.x = jnp.asarray(
            rng.standard_normal((32, CFG.d_model)), jnp.float32
        )
        self.router = jnp.asarray(
            rng.standard_normal((CFG.d_model, CFG.n_experts)), jnp.float32
        )

    def test_dispatch_capacity_respected(self):
        dispatch, combine, _ = moe._route(self.x, self.router, CFG)
        S = self.x.shape[0]
        C = CFG.capacity(S)
        assert dispatch.shape == (S, CFG.n_experts, C)
        # No expert slot double-booked.
        per_slot = np.asarray(dispatch.sum(axis=0))
        assert per_slot.max() <= 1.0 + 1e-6
        # Each token dispatched at most top_k times.
        per_token = np.asarray(dispatch.sum(axis=(1, 2)))
        assert per_token.max() <= CFG.top_k + 1e-6

    def test_combine_weights_normalized(self):
        dispatch, combine, _ = moe._route(self.x, self.router, CFG)
        weights = np.asarray(combine.sum(axis=(1, 2)))
        # Tokens with no drops combine to ~1; dropped contributions only
        # ever reduce the total.
        assert weights.max() <= 1.0 + 1e-5
        assert (weights > 0.99).mean() > 0.5

    def test_capacity_one_drops_overflow(self):
        tight = moe.MoEConfig(
            d_model=CFG.d_model,
            n_experts=CFG.n_experts,
            top_k=1,
            capacity_factor=0.25,
        )
        dispatch, _, _ = moe._route(self.x, self.router, tight)
        C = tight.capacity(self.x.shape[0])
        assert float(dispatch.sum(axis=0).max()) <= 1.0 + 1e-6
        assert dispatch.shape[-1] == C
        # Overflowing tokens really are dropped.
        assert float(dispatch.sum()) < self.x.shape[0]


def test_single_expert_equals_dense_mlp():
    """top_k = n_experts = 1 with ample capacity reduces the routed
    layer to the plain gated MLP of the dense model."""
    cfg = moe.MoEConfig(
        d_model=32, d_ff=64, n_experts=1, top_k=1, capacity_factor=2.0
    )
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 8, 32)), jnp.float32)
    lp = {
        "router": jnp.zeros((32, 1), jnp.float32),
        "w_gate": jnp.asarray(
            rng.standard_normal((1, 32, 64)) * 0.1, jnp.float32
        ),
        "w_up": jnp.asarray(
            rng.standard_normal((1, 32, 64)) * 0.1, jnp.float32
        ),
        "w_down": jnp.asarray(
            rng.standard_normal((1, 64, 32)) * 0.1, jnp.float32
        ),
    }
    out, aux = moe._moe_mlp(x, lp, cfg)
    gate = jnp.einsum("btd,df->btf", x, lp["w_gate"][0])
    up = jnp.einsum("btd,df->btf", x, lp["w_up"][0])
    dense = jnp.einsum(
        "btf,fd->btd", jax.nn.silu(gate) * up, lp["w_down"][0]
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=1e-4, atol=1e-4
    )


def test_sharded_sp_train_step_finite_and_loss_matches():
    """Regression twin of llama's: under a mesh combining sp with
    another axis, every post-step param stays finite and the sharded
    loss matches the unsharded one (shift-and-mask keeps all shapes
    evenly sharded).  Tolerance is looser than llama's exact match:
    the routed dispatch/combine einsums accumulate in a different
    order across devices (~1e-4 rel), the same scale the dense-vs-
    paged comparisons tolerate."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshPlan(dp=1, ep=2, sp=2), jax.devices()[:4])
    params = moe.init_params(jax.random.PRNGKey(0), CFG)
    optimizer = moe.make_optimizer()
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, 32), 0, CFG.vocab_size
    )
    pspecs = moe.param_pspecs(CFG)
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    )
    opt_state = optimizer.init(sharded)
    tokens_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P("dp", "sp"))
    )
    with mesh:
        step = jax.jit(
            lambda p, o, t: moe.train_step(p, o, t, CFG, optimizer)
        )
        new_params, _, loss = step(sharded, opt_state, tokens_sharded)
    for leaf in jax.tree.leaves(new_params):
        assert bool(jnp.all(jnp.isfinite(leaf)))
    unsharded_loss = moe.loss_fn(params, tokens, CFG)
    np.testing.assert_allclose(
        float(loss), float(unsharded_loss), rtol=1e-3
    )


def test_sharded_train_step_dp_ep_tp():
    """One real train step over an 8-device dp=2 x ep=2 x tp=2 mesh with
    the model's PartitionSpecs — the ep axis carrying actual experts."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshPlan(dp=2, ep=2, tp=2), jax.devices()[:8])
    params = moe.init_params(jax.random.PRNGKey(0), CFG)
    optimizer = moe.make_optimizer()
    opt_state = optimizer.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, 512)

    with mesh:
        pspecs = moe.param_pspecs(CFG)
        params = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
        )
        tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
        step = jax.jit(
            lambda p, o, t: moe.train_step(p, o, t, CFG, optimizer)
        )
        params, opt_state, loss = step(params, opt_state, tokens)
        params, opt_state, loss = step(params, opt_state, tokens)
    assert bool(jnp.isfinite(loss))
