"""Native engine tests: hash parity (C++ vs Python oracle) and offload
job roundtrips for both the native and fallback engines."""

import os

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.native import get_library
from llm_d_kv_cache_manager_tpu.native.engine import (
    JobStatus,
    OffloadEngine,
    native_hash_chain,
)

needs_native = pytest.mark.skipif(
    get_library() is None, reason="native library unavailable"
)


@needs_native
class TestNativeHashParity:
    def test_fnv_parity(self):
        lib = get_library()
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            fnv1a_64,
        )

        for data in (b"", b"a", b"foobar", bytes(range(256))):
            assert lib.kvtpu_fnv1a64(data, len(data)) == fnv1a_64(data)

    @pytest.mark.parametrize("block_size", [1, 4, 16, 256])
    @pytest.mark.parametrize("seed", ["", "42"])
    def test_chain_parity_vs_python(self, block_size, seed):
        """C++ chain must equal the pure-Python oracle bit for bit."""
        config = TokenProcessorConfig(block_size=block_size, hash_seed=seed)
        python_db = ChunkedTokenDatabase(config, use_native=False)
        assert python_db._native_chain is None

        rng = np.random.default_rng(7)
        tokens = [int(t) for t in rng.integers(0, 2**32, size=1000)]
        expected = python_db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, "model"
        )
        parent = python_db.model_init_hash("model")
        native = native_hash_chain(parent, tokens, block_size)
        assert native == expected

    def test_native_wired_into_token_processor(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=16))
        assert db._native_chain is not None
        oracle = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=16), use_native=False
        )
        tokens = list(range(160))
        assert db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, "m"
        ) == oracle.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m")

    def test_chain_parity_with_parent(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=8))
        oracle = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=8), use_native=False
        )
        tokens = list(range(64))
        parent = 0xDEADBEEF12345678
        assert db.tokens_to_kv_block_keys(
            parent, tokens, "m"
        ) == oracle.tokens_to_kv_block_keys(parent, tokens, "m")


@pytest.fixture(params=["native", "python"])
def engine(request, monkeypatch):
    if request.param == "native":
        if get_library() is None:
            pytest.skip("native library unavailable")
        eng = OffloadEngine(n_threads=2)
        assert eng.is_native
    else:
        monkeypatch.setenv("KVTPU_DISABLE_NATIVE", "1")
        eng = OffloadEngine(n_threads=2)
        assert not eng.is_native
    yield eng
    eng.close()


class TestOffloadEngine:
    def test_store_load_roundtrip(self, engine, tmp_path):
        rng = np.random.default_rng(3)
        blocks = [
            rng.integers(0, 255, size=(2, 16, 8), dtype=np.uint8)
            for _ in range(5)
        ]
        paths = [str(tmp_path / f"{i:02x}" / f"block_{i}.bin") for i in range(5)]
        engine.store(1, paths, blocks, skip_existing=True)
        assert engine.wait(1) == JobStatus.SUCCEEDED
        for path in paths:
            assert os.path.exists(path)

        out = [np.zeros_like(b) for b in blocks]
        engine.load(2, paths, out)
        assert engine.wait(2) == JobStatus.SUCCEEDED
        for original, loaded in zip(blocks, out):
            np.testing.assert_array_equal(original, loaded)

    def test_get_finished_harvests_once(self, engine, tmp_path):
        data = np.arange(64, dtype=np.uint8)
        engine.store(10, [str(tmp_path / "a.bin")], [data])
        status = engine.wait(10)
        assert status == JobStatus.SUCCEEDED
        # wait() consumed the job; nothing left to harvest.
        assert engine.get_finished() == []

    def test_get_finished_polling(self, engine, tmp_path):
        data = np.arange(128, dtype=np.uint8)
        engine.store(20, [str(tmp_path / "b.bin")], [data])
        import time

        deadline = time.monotonic() + 10
        finished = []
        while time.monotonic() < deadline and not finished:
            finished = engine.get_finished()
            time.sleep(0.01)
        assert finished == [(20, JobStatus.SUCCEEDED)]

    def test_load_missing_file_fails(self, engine, tmp_path):
        out = np.zeros(64, dtype=np.uint8)
        engine.load(30, [str(tmp_path / "missing.bin")], [out])
        assert engine.wait(30) == JobStatus.FAILED

    def test_load_size_mismatch_fails(self, engine, tmp_path):
        path = tmp_path / "short.bin"
        path.write_bytes(b"x" * 10)
        out = np.zeros(64, dtype=np.uint8)
        engine.load(31, [str(path)], [out])
        assert engine.wait(31) == JobStatus.FAILED

    def test_skip_existing_dedupe(self, engine, tmp_path):
        path = str(tmp_path / "dedupe.bin")
        first = np.full(32, 1, dtype=np.uint8)
        second = np.full(32, 2, dtype=np.uint8)
        engine.store(40, [path], [first])
        assert engine.wait(40) == JobStatus.SUCCEEDED
        engine.store(41, [path], [second], skip_existing=True)
        assert engine.wait(41) == JobStatus.SUCCEEDED
        # Original content preserved: another pod's write was not clobbered.
        assert open(path, "rb").read() == first.tobytes()

    def test_overwrite_when_not_skipping(self, engine, tmp_path):
        path = str(tmp_path / "clobber.bin")
        first = np.full(32, 1, dtype=np.uint8)
        second = np.full(32, 2, dtype=np.uint8)
        engine.store(50, [path], [first])
        assert engine.wait(50) == JobStatus.SUCCEEDED
        engine.store(51, [path], [second], skip_existing=False)
        assert engine.wait(51) == JobStatus.SUCCEEDED
        assert open(path, "rb").read() == second.tobytes()

    def test_partial_head_load_from_larger_file(self, engine, tmp_path):
        """A partial group reads the head of a full group file."""
        path = tmp_path / "group.bin"
        full = np.arange(64, dtype=np.uint8)
        path.write_bytes(full.tobytes())
        head = np.zeros(32, dtype=np.uint8)
        engine.load(80, [str(path)], [head])
        assert engine.wait(80) == JobStatus.SUCCEEDED
        np.testing.assert_array_equal(head, full[:32])

    def test_partial_store_upgraded_by_full_store(self, engine, tmp_path):
        """skip_existing skips only files covering >= our bytes: a
        partial (head) file is upgraded, never the other way."""
        path = str(tmp_path / "upgrade.bin")
        partial = np.full(16, 1, dtype=np.uint8)
        full = np.full(32, 2, dtype=np.uint8)
        engine.store(81, [path], [partial], skip_existing=True)
        assert engine.wait(81) == JobStatus.SUCCEEDED
        engine.store(82, [path], [full], skip_existing=True)
        assert engine.wait(82) == JobStatus.SUCCEEDED
        assert open(path, "rb").read() == full.tobytes()
        # The reverse: a partial store against a full file is a skip.
        engine.store(83, [path], [partial], skip_existing=True)
        assert engine.wait(83) == JobStatus.SUCCEEDED
        assert open(path, "rb").read() == full.tobytes()

    def test_closed_engine_raises(self, engine, tmp_path):
        engine.close()
        data = np.zeros(8, dtype=np.uint8)
        with pytest.raises(RuntimeError, match="closed"):
            engine.store(90, [str(tmp_path / "x.bin")], [data])
        with pytest.raises(RuntimeError, match="closed"):
            engine.get_finished()
        engine.close()  # idempotent

    def test_wait_unknown_job(self, engine):
        assert engine.wait(999) == JobStatus.UNKNOWN

    def test_empty_job(self, engine):
        engine.store(60, [], [])
        assert engine.wait(60) in (JobStatus.SUCCEEDED, JobStatus.UNKNOWN)

    def test_large_fanout(self, engine, tmp_path):
        blocks = [
            np.full(1024, i % 256, dtype=np.uint8) for i in range(64)
        ]
        paths = [str(tmp_path / f"fan_{i}.bin") for i in range(64)]
        engine.store(70, paths, blocks)
        assert engine.wait(70) == JobStatus.SUCCEEDED
        out = [np.zeros(1024, dtype=np.uint8) for _ in range(64)]
        engine.load(71, paths, out)
        assert engine.wait(71) == JobStatus.SUCCEEDED
        for i in range(64):
            np.testing.assert_array_equal(out[i], blocks[i])


def test_numa_detection_does_not_crash():
    # NUMA topology may or may not exist in the test environment; the
    # engine must construct either way.
    eng = OffloadEngine(n_threads=1, numa_node=0)
    eng.close()
