"""The native format gate must be enforcing, not advisory.

The reference hard-gates native code style in CI
(.github/workflows/ci-pr-checks.yaml:69-89 + hooks/pre-commit.sh).
This repo enforces the same two ways: real clang-format on runners
that have it, and hack/check_native_format.py (the mechanically-
decidable subset of the pinned Google style) everywhere else.  These
tests pin that (a) the tree is clean under the subset gate, (b) the
gate actually rejects violations, and (c) CI runs both steps with no
continue-on-error escape hatch.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "hack", "check_native_format.py")


def run_checker(*args):
    return subprocess.run(
        [sys.executable, CHECKER, *args],
        cwd=REPO,
        capture_output=True,
        text=True,
    )


class TestSubsetGate:
    def test_tree_is_clean(self):
        proc = run_checker()
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_rejects_violations(self, tmp_path):
        bad = tmp_path / "bad.cpp"
        bad.write_text(
            "int main() {\n"
            "\treturn 0;  \n"  # tab + trailing whitespace
            "  int y;\n"
            "   int x;\n"  # 3-space indent after a 2-space line
            "}\n"
            + "// " + "word " * 20 + "\n"  # >80 cols, breakable
        )
        proc = run_checker(str(bad))
        assert proc.returncode == 1
        out = proc.stdout
        assert "tab character" in out
        assert "trailing whitespace" in out
        assert "columns" in out
        assert "not a multiple" in out

    def test_rejects_missing_final_newline(self, tmp_path):
        bad = tmp_path / "bad.hpp"
        bad.write_text("int x;")
        proc = run_checker(str(bad))
        assert proc.returncode == 1
        assert "final newline" in proc.stdout

    def test_accepts_unbreakable_overflow_and_raw_strings(self, tmp_path):
        """clang-format leaves a single unbreakable token over the
        column limit and never edits raw-string contents; the subset
        gate must not fail code the authoritative gate accepts."""
        good = tmp_path / "good.hpp"
        good.write_text(
            '#include "' + "a/" * 45 + 'long_header.hpp"\n'
            'const char* kDoc = R"(\n'
            "\ttab and trailing space inside raw string  \n"
            ')";\n'
        )
        proc = run_checker(str(good))
        assert proc.returncode == 0, proc.stdout

    def test_rejects_early_break_with_wrappable_tail(self, tmp_path):
        """The formerly-documented false negative: an over-limit line
        whose only spaces sit before column 79 is still a violation
        when the overflowing token would fit on its own continuation
        line — clang-format would have wrapped at the early space and
        produced no over-limit line at all."""
        bad = tmp_path / "bad.cpp"
        bad.write_text("  int value = " + "a" * 70 + ";\n")
        proc = run_checker(str(bad))
        assert proc.returncode == 1
        assert "columns" in proc.stdout

    def test_accepts_early_break_with_unwrappable_tail(self, tmp_path):
        """...but when the final token cannot fit under the limit even
        on its own continuation line, clang-format itself leaves it
        overflowing — the gate must keep accepting that output."""
        good = tmp_path / "good.cpp"
        good.write_text("  return " + "a" * 85 + ";\n")
        proc = run_checker(str(good))
        assert proc.returncode == 0, proc.stdout

    def test_accepts_continuation_alignment(self, tmp_path):
        good = tmp_path / "good.cpp"
        good.write_text(
            "void f(int a,\n"
            "       int b) {\n"  # clang-format argument alignment
            "  g(a,\n"
            "    b);\n"
            "}\n"
            "/* block\n"
            " * comment */\n"
            "class C {\n"
            " public:\n"  # Google one-space access label
            "  int x;\n"
            "};\n"
        )
        proc = run_checker(str(good))
        assert proc.returncode == 0, proc.stdout


class TestCIGateIsHard:
    def test_no_continue_on_error_on_format_steps(self):
        """Scoped to the two format steps: an unrelated advisory step
        elsewhere in CI is allowed to use continue-on-error."""
        with open(
            os.path.join(REPO, ".github", "workflows", "ci.yaml")
        ) as handle:
            ci = handle.read()
        steps = ci.split("- name:")
        format_steps = [
            s
            for s in steps
            if "clang-format --dry-run --Werror" in s
            or "check_native_format.py" in s
        ]
        assert len(format_steps) == 2, (
            "expected the clang-format step and the portable subset "
            f"step; found {len(format_steps)}"
        )
        for step in format_steps:
            assert "continue-on-error" not in step
