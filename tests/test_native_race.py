"""Native-engine race detection (SURVEY.md §5: the reference wires no
race detector; this build does).

Builds and runs ``native/src/stress_main.cpp`` — every engine entry
point hammered from concurrent threads — plain and, when the toolchain
supports it, under ThreadSanitizer with ``halt_on_error=1``.  The
harness already earned its keep: it caught get_finished()/wait() both
claiming one completion (fixed in engine.cpp by exactly-once erase).
"""

import os
import shutil
import subprocess

import pytest

from llm_d_kv_cache_manager_tpu.native.build import build_stress

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None and shutil.which("c++") is None,
    reason="no C++ compiler",
)


def _run(binary, tmp_path):
    env = dict(
        os.environ,
        TSAN_OPTIONS="halt_on_error=1",
        KVTPU_STRESS_DIR=str(tmp_path),
    )
    return subprocess.run(
        [binary], env=env, capture_output=True, text=True, timeout=300
    )


def test_stress_plain(tmp_path):
    binary = build_stress(tsan=False)
    result = _run(binary, tmp_path)
    assert result.returncode == 0, result.stderr
    assert "stress ok" in result.stdout


def test_stress_under_tsan(tmp_path):
    try:
        binary = build_stress(tsan=True)
    except RuntimeError as exc:  # toolchain without libtsan
        pytest.skip(f"tsan unavailable: {exc}")
    result = _run(binary, tmp_path)
    assert result.returncode == 0, (
        f"ThreadSanitizer found a race:\n{result.stderr[-4000:]}"
    )
    assert "stress ok" in result.stdout
