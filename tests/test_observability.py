"""Unit + concurrency tests for obs/: tracer, spans, traceparent
parsing, the flight recorder's three retention tiers, and the kvlint
gate over the package.  Uses private Tracer instances (not the global
TRACER) so tests never leak sampling state into each other.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time

import pytest

from llm_d_kv_cache_manager_tpu.obs.recorder import FlightRecorder
from llm_d_kv_cache_manager_tpu.obs.trace import (
    Tracer,
    TracerConfig,
    current_trace,
    format_traceparent,
    parse_traceparent,
    span as obs_span,
    use_trace,
)

SAMPLED_TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
UNSAMPLED_TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-00"


def make_tracer(**overrides) -> Tracer:
    config = TracerConfig(sample_rate=1.0)
    for key, value in overrides.items():
        setattr(config, key, value)
    return Tracer(config)


class TestTraceparent:
    def test_parse_valid_sampled(self):
        parsed = parse_traceparent(SAMPLED_TP)
        assert parsed is not None
        assert parsed.trace_id == "ab" * 16
        assert parsed.span_id == "cd" * 8
        assert parsed.sampled

    def test_parse_valid_unsampled(self):
        parsed = parse_traceparent(UNSAMPLED_TP)
        assert parsed is not None and not parsed.sampled

    def test_parse_is_case_insensitive_and_strips(self):
        parsed = parse_traceparent("  " + SAMPLED_TP.upper() + " ")
        assert parsed is not None and parsed.trace_id == "ab" * 16

    def test_parse_accepts_future_version_with_suffix_fields(self):
        """W3C forward compatibility: higher versions parse by their
        first four fields, ignoring any suffix fields."""
        header = "01-" + "ab" * 16 + "-" + "cd" * 8 + "-01-extrafield"
        parsed = parse_traceparent(header)
        assert parsed == ("ab" * 16, "cd" * 8, True)

    def test_parse_rejects_version_00_with_suffix(self):
        assert parse_traceparent(SAMPLED_TP + "-extrafield") is None

    @pytest.mark.parametrize(
        "header",
        [
            None,
            "",
            "garbage",
            "00-" + "ab" * 16 + "-" + "cd" * 8,  # missing flags
            "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",  # non-hex
            "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # forbidden ver
            "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",  # zero trace id
            "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # zero span id
        ],
    )
    def test_parse_rejects(self, header):
        assert parse_traceparent(header) is None

    def test_format_roundtrip(self):
        header = format_traceparent("ab" * 16, "cd" * 8, sampled=True)
        parsed = parse_traceparent(header)
        assert parsed == ("ab" * 16, "cd" * 8, True)


class TestSampling:
    def test_rate_zero_drops_and_counts(self):
        tracer = make_tracer(sample_rate=0.0)
        assert tracer.start_trace("t") is None
        stats = tracer.stats()
        assert stats["traces_unsampled"] == 1
        assert stats["traces_sampled"] == 0

    def test_rate_one_samples(self):
        tracer = make_tracer()
        assert tracer.start_trace("t") is not None

    def test_sampled_traceparent_forces_at_rate_zero(self):
        tracer = make_tracer(sample_rate=0.0)
        trace = tracer.start_trace("t", traceparent=SAMPLED_TP)
        assert trace is not None
        assert trace.trace_id == "ab" * 16
        assert trace.parent_span_id == "cd" * 8

    def test_unsampled_traceparent_does_not_force(self):
        tracer = make_tracer(sample_rate=0.0)
        assert tracer.start_trace("t", traceparent=UNSAMPLED_TP) is None

    def test_force_flag(self):
        tracer = make_tracer(sample_rate=0.0)
        assert tracer.start_trace("t", force=True) is not None

    def test_configure_live_tunes_rate(self):
        tracer = make_tracer(sample_rate=0.0)
        tracer.configure(sample_rate=1.0)
        assert tracer.start_trace("t") is not None
        with pytest.raises(TypeError):
            tracer.configure(ring_size=5)


class TestTraceSpans:
    def test_span_timing_parents_and_attrs(self):
        tracer = make_tracer()
        trace = tracer.start_trace("req")
        with use_trace(trace):
            with obs_span("tokenize") as s:
                s.set_attr("tokens", 7)
                time.sleep(0.005)
            with obs_span("tokenize.encode", parent="tokenize"):
                pass
        trace.finish()
        view = trace.to_dict()
        assert view["status"] == "ok"
        assert [s["stage"] for s in view["stages"]] == ["tokenize"]
        spans = {s["name"]: s for s in view["spans"]}
        assert spans["tokenize"]["attributes"] == {"tokens": 7}
        assert spans["tokenize"]["duration_ms"] >= 5.0
        assert spans["tokenize.encode"]["parent"] == "tokenize"

    def test_untraced_span_is_null(self):
        assert current_trace() is None
        with obs_span("anything") as s:
            s.set_attr("ignored", 1)  # must not raise

    def test_add_completed_explicit_interval(self):
        tracer = make_tracer()
        trace = tracer.start_trace("req")
        start = time.perf_counter() - 0.05
        trace.add_completed("queue_wait", start)
        trace.finish()
        (stage,) = trace.stage_breakdown()
        assert stage["stage"] == "queue_wait"
        assert stage["duration_ms"] >= 50.0

    def test_span_exception_marks_error(self):
        tracer = make_tracer()
        trace = tracer.start_trace("req")
        with pytest.raises(RuntimeError):
            with use_trace(trace), obs_span("boom"):
                raise RuntimeError("nope")
        trace.finish()
        (span,) = trace.to_dict()["spans"]
        assert span["status"] == "error"
        assert "nope" in span["attributes"]["error"]

    def test_set_error_routes_to_errored_reservoir(self):
        tracer = make_tracer()
        trace = tracer.start_trace("req")
        trace.set_error("poison pill")
        trace.finish()
        assert trace.status == "error"
        assert tracer.recorder.errored() == [trace]

    def test_finish_is_idempotent(self):
        tracer = make_tracer()
        trace = tracer.start_trace("req")
        trace.finish()
        first = trace.duration_s
        trace.finish()
        assert trace.duration_s == first
        assert tracer.recorder.stats()["recorded"] == 1

    def test_finish_feeds_stage_histogram(self):
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        def histogram_count(stage):
            for metric in METRICS.stage_latency.collect():
                for sample in metric.samples:
                    if (
                        sample.name.endswith("_count")
                        and sample.labels.get("stage") == stage
                    ):
                        return sample.value
            return 0.0

        before = histogram_count("uniquestage")
        tracer = make_tracer()
        trace = tracer.start_trace("req")
        with use_trace(trace), obs_span("uniquestage"):
            pass
        trace.finish()
        assert histogram_count("uniquestage") == before + 1

    def test_use_trace_restores_context(self):
        tracer = make_tracer()
        outer = tracer.start_trace("outer")
        inner = tracer.start_trace("inner")
        with use_trace(outer):
            with use_trace(inner):
                assert current_trace() is inner
            assert current_trace() is outer
        assert current_trace() is None


class TestFlightRecorder:
    def test_ring_eviction(self):
        tracer = make_tracer(ring_size=4)
        traces = []
        for i in range(10):
            trace = tracer.start_trace(f"t{i}")
            trace.finish()
            traces.append(trace)
        stats = tracer.recorder.stats()
        assert stats["ring_occupancy"] == 4
        assert stats["recorded"] == 10
        recent = tracer.recorder.recent()
        assert [t.name for t in recent] == ["t9", "t8", "t7", "t6"]
        # Evicted and never slow/errored: unresolvable.
        assert tracer.recorder.get(traces[0].trace_id) is None

    def test_slow_promotion_survives_ring_eviction(self):
        tracer = make_tracer(ring_size=2, slow_threshold_ms=0.0)
        slow_trace = tracer.start_trace("slow")
        time.sleep(0.002)
        slow_trace.finish()
        for i in range(5):
            tracer.start_trace(f"f{i}").finish()
        # Rolled out of the ring, still resolvable via the reservoir.
        assert tracer.recorder.get(slow_trace.trace_id) is slow_trace
        assert slow_trace in tracer.recorder.slow()

    def test_slow_reservoir_keeps_slowest(self):
        recorder = FlightRecorder(
            ring_size=64, slow_keep=2, slow_threshold_ms=0.0
        )

        class Stub:
            def __init__(self, trace_id, duration_s):
                self.trace_id = trace_id
                self.duration_s = duration_s
                self.status = "ok"

        for trace_id, duration in (
            ("a", 0.010), ("b", 0.030), ("c", 0.020), ("d", 0.001),
        ):
            recorder.record(Stub(trace_id, duration))
        assert [t.trace_id for t in recorder.slow()] == ["b", "c"]

    def test_threshold_gates_promotion(self):
        tracer = make_tracer(slow_threshold_ms=10_000.0)
        tracer.start_trace("fast").finish()
        assert tracer.recorder.stats()["slow_retained"] == 0

    def test_clear(self):
        tracer = make_tracer()
        tracer.start_trace("t").finish()
        tracer.reset()
        stats = tracer.stats()
        assert stats["recorded"] == 0
        assert stats["ring_occupancy"] == 0
        assert stats["traces_sampled"] == 0


class TestConcurrency:
    def test_parallel_traced_requests_no_lost_or_duplicated_ids(self):
        """Acceptance gate: the flight-recorder ring under parallel
        traced requests — every trace retrievable, every id unique."""
        tracer = make_tracer(ring_size=1024)
        n_threads, per_thread = 16, 25
        errors = []
        barrier = threading.Barrier(n_threads)

        def worker(worker_index):
            try:
                barrier.wait(timeout=10)
                for i in range(per_thread):
                    trace = tracer.start_trace(
                        f"w{worker_index}.{i}"
                    )
                    with use_trace(trace):
                        with obs_span("stage_a"):
                            pass
                        with obs_span("stage_b"):
                            assert current_trace() is trace
                    trace.finish()
            except Exception as exc:  # pragma: no cover - surfaced below
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(n_threads)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=30)
        assert not errors
        total = n_threads * per_thread
        recent = tracer.recorder.recent(limit=total)
        ids = [t.trace_id for t in recent]
        assert len(ids) == total
        assert len(set(ids)) == total
        stats = tracer.recorder.stats()
        assert stats["recorded"] == total
        assert tracer.stats()["traces_sampled"] == total
        # Every trace got both spans (none torn by concurrency).
        for trace in recent:
            assert len(trace.to_dict()["spans"]) == 2

    def test_cross_thread_span_append(self):
        """Spans appended from a worker thread land on the same trace
        (the tokenization-pool propagation contract)."""
        tracer = make_tracer()
        trace = tracer.start_trace("req")

        def worker():
            trace.add_completed(
                "queue_wait", time.perf_counter() - 0.001
            )
            with trace.span("encode", parent="tokenize"):
                pass

        thread = threading.Thread(target=worker)
        thread.start()
        thread.join(timeout=10)
        trace.finish()
        assert len(trace.to_dict()["spans"]) == 2


class TestKvlintGate:
    def test_obs_package_is_kvlint_clean_without_baseline(self):
        """Acceptance gate: kvlint over obs/ with zero baseline
        entries.  --no-baseline means a future violation cannot hide
        behind a grandfathered entry."""
        result = subprocess.run(
            [
                sys.executable,
                "-m",
                "hack.kvlint",
                "llm_d_kv_cache_manager_tpu/obs",
                "--no-baseline",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert result.returncode == 0, result.stdout + result.stderr
