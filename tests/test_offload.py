"""TPU offload connector tests (CPU-executed: JAX arrays + real files)."""

import os
import time

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import (
    KVCachePool,
    KVCachePoolConfig,
)
from llm_d_kv_cache_manager_tpu.native.engine import JobStatus
from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper
from llm_d_kv_cache_manager_tpu.offload.spec import (
    TPUOffloadConnector,
    TPUOffloadSpec,
)
from llm_d_kv_cache_manager_tpu.offload.worker import (
    group_blocks_per_file,
    host_dtype,
)

POOL_CONFIG = KVCachePoolConfig(
    num_layers=3,
    num_blocks=32,
    block_size=8,
    num_kv_heads=2,
    head_dim=16,
    dtype="bfloat16",
)


def make_connector(tmp_path, pool=None, event_sink=None):
    spec = TPUOffloadSpec(
        shared_storage_path=str(tmp_path),
        model_name="llama-3-8b",
        device_block_size=8,
        offloaded_block_size=16,  # 2 device blocks per file
        threads_per_chip=2,
    )
    pool = pool or KVCachePool(POOL_CONFIG)
    return TPUOffloadConnector(spec, pool, event_sink=event_sink), pool


class TestFileMapper:
    def test_layout(self):
        mapper = FileMapper(
            root_dir="/shared",
            model_name="org/model",
            device_block_size=16,
            blocks_per_file=4,
            tp_size=2,
            pp_size=2,
            pcp_size=1,
            rank=3,
            dtype="bfloat16",
        )
        path = mapper.get_file_name(0xABCDEF0123456789)
        assert path == (
            "/shared/org/model/block_size_16_blocks_per_file_4/"
            "tp_2_pp_size_2_pcp_size_1/rank_3/bfloat16/"
            "abc/de/abcdef0123456789.bin"
        )

    def test_bytes_hash_little_endian(self):
        mapper = FileMapper("/s", "m", 16, 1)
        raw = (0x1122).to_bytes(8, "little")
        assert mapper.get_file_name(raw) == mapper.get_file_name(0x1122)

    def test_negative_wraps_to_uint64(self):
        mapper = FileMapper("/s", "m", 16, 1)
        assert "ffffffffffffffff" in mapper.get_file_name(-1)


class TestGrouping:
    def test_full_groups(self):
        groups = group_blocks_per_file([1, 2], [10, 11, 12, 13], 2)
        assert groups == [(1, [10, 11]), (2, [12, 13])]

    def test_partial_last_group(self):
        """Prefix semantics: the tail group carries the remainder."""
        groups = group_blocks_per_file([1, 2], [11, 12, 13], 2)
        assert groups == [(1, [11, 12]), (2, [13])]

    def test_invalid_split_raises(self):
        with pytest.raises(ValueError):
            group_blocks_per_file([1, 2], [1, 2, 3, 4, 5], 2)
        with pytest.raises(ValueError):
            group_blocks_per_file([1, 2, 3], [1, 2], 2)

    def test_empty(self):
        assert group_blocks_per_file([], [], 4) == []


def fill_pool_blocks(pool, block_ids, seed=0):
    """Write recognizable data into pool blocks; returns host copies."""
    rng = np.random.default_rng(seed)
    c = pool.config
    written = {}
    for block_id in block_ids:
        data = rng.standard_normal(
            (c.num_layers, 2, c.block_size, c.num_kv_heads, c.head_dim)
        ).astype(host_dtype(c.dtype))
        pool.write_block(block_id, data)
        written[block_id] = data
    return written


class TestStoreLoadRoundtrip:
    def test_roundtrip_through_files(self, tmp_path):
        connector, pool = make_connector(tmp_path)
        block_ids = [3, 4, 7, 9]
        written = fill_pool_blocks(pool, block_ids)

        groups = group_blocks_per_file([0xA, 0xB], block_ids, 2)
        connector.store_handler.transfer_async(1, groups)
        assert connector.store_handler.wait(1) == JobStatus.SUCCEEDED

        for file_hash in (0xA, 0xB):
            assert os.path.exists(
                connector.file_mapper.get_file_name(file_hash)
            )

        # Page into a *fresh* pool (simulates another pod or post-restart).
        pool2 = KVCachePool(POOL_CONFIG)
        connector2 = TPUOffloadConnector(connector.spec, pool2)
        target_ids = [20, 21, 22, 23]
        connector2.load_handler.transfer_async(
            2, group_blocks_per_file([0xA, 0xB], target_ids, 2)
        )
        assert connector2.load_handler.wait(2) == JobStatus.SUCCEEDED

        restored = pool2.gather_to_host(target_ids)  # [L, 4, 2, bs, h, d]
        for i, block_id in enumerate(block_ids):
            np.testing.assert_array_equal(
                restored[:, i], written[block_id]
            )
        connector.close()
        connector2.close()

    def test_get_finished_routes_between_handlers(self, tmp_path):
        events = []
        connector, pool = make_connector(
            tmp_path, event_sink=lambda hashes, medium: events.append(
                (tuple(hashes), medium)
            )
        )
        fill_pool_blocks(pool, [0, 1])
        connector.store_handler.transfer_async(
            10, group_blocks_per_file([0xC], [0, 1], 2)
        )
        deadline = time.monotonic() + 10
        finished = []
        while time.monotonic() < deadline and not finished:
            finished = connector.get_finished()
            time.sleep(0.01)
        assert finished == [(10, JobStatus.SUCCEEDED)]
        assert events == [((0xC,), "shared_storage")]

        connector.load_handler.transfer_async(
            11, group_blocks_per_file([0xC], [5, 6], 2)
        )
        deadline = time.monotonic() + 10
        finished = []
        while time.monotonic() < deadline and not finished:
            finished = connector.get_finished()
            time.sleep(0.01)
        assert finished == [(11, JobStatus.SUCCEEDED)]
        # Load scattered into blocks 5,6.
        restored = pool.gather_to_host([5, 6])
        original = pool.gather_to_host([0, 1])
        np.testing.assert_array_equal(restored, original)
        connector.close()

    def test_load_missing_file_fails(self, tmp_path):
        connector, pool = make_connector(tmp_path)
        connector.load_handler.transfer_async(
            20, group_blocks_per_file([0xDEAD], [1, 2], 2)
        )
        assert connector.load_handler.wait(20) == JobStatus.FAILED
        connector.close()

    def test_partial_store_then_full_load(self, tmp_path):
        """A partial tail group stores a head-sized file; the manager
        promises only full groups; a later full store upgrades the
        partial file; partial head loads read coherent bytes."""
        connector, pool = make_connector(tmp_path)
        manager = connector.get_manager()
        fill_pool_blocks(pool, [0, 1, 2])

        # Tail group partial: 0xA full [0,1]; 0xB carries 1 of 2 blocks.
        connector.store_handler.transfer_async(
            30, group_blocks_per_file([0xA, 0xB], [0, 1, 2], 2)
        )
        assert connector.store_handler.wait(30) == JobStatus.SUCCEEDED
        # Size-aware lookup: 0xA full counts; partial 0xB stops the scan.
        assert manager.lookup([0xA, 0xB]) == 1

        # Partial head load of 0xB's resident block works.
        connector.load_handler.transfer_async(
            31, group_blocks_per_file([0xB], [10], 2)
        )
        assert connector.load_handler.wait(31) == JobStatus.SUCCEEDED
        np.testing.assert_array_equal(
            pool.gather_to_host([10]), pool.gather_to_host([2])
        )

        # Full store upgrades the partial file; lookup now promises both.
        connector.store_handler.transfer_async(
            32, group_blocks_per_file([0xA, 0xB], [0, 1, 2, 1], 2)
        )
        assert connector.store_handler.wait(32) == JobStatus.SUCCEEDED
        assert manager.lookup([0xA, 0xB]) == 2
        connector.close()

    def test_pool_external_reference_survives_load(self, tmp_path):
        """The serving loop holds pool.kv across steps; an async load
        completion must not delete that buffer out from under it."""
        connector, pool = make_connector(tmp_path)
        fill_pool_blocks(pool, [0, 1])
        connector.store_handler.transfer_async(
            40, group_blocks_per_file([0xE], [0, 1], 2)
        )
        assert connector.store_handler.wait(40) == JobStatus.SUCCEEDED

        held = pool.kv  # external reference, as prefill/decode take
        connector.load_handler.transfer_async(
            41, group_blocks_per_file([0xE], [4, 5], 2)
        )
        assert connector.load_handler.wait(41) == JobStatus.SUCCEEDED
        # Old buffer still readable (no donation on the async path).
        np.asarray(held)
        connector.close()


class TestRttObserver:
    def test_observer_sees_only_file_bytes(self, tmp_path):
        """The compute-or-load RTT feed must be priced on what the
        engine actually reads from storage: a host-tier-served group
        pairs near-zero io time with its payload, which would collapse
        the advisor's per-byte estimate (review finding, pinned)."""
        from llm_d_kv_cache_manager_tpu.offload.host_tier import (
            HostTierCache,
        )
        from llm_d_kv_cache_manager_tpu.offload.worker import (
            StorageToDeviceHandler,
        )

        connector, pool = make_connector(tmp_path)
        block_ids = [1, 2, 3, 4]
        fill_pool_blocks(pool, block_ids)
        connector.store_handler.transfer_async(
            1, group_blocks_per_file([0xA, 0xB], block_ids, 2)
        )
        assert connector.store_handler.wait(1) == JobStatus.SUCCEEDED

        # Host cache holds ONLY group 0xA; 0xB must come from its file.
        group_a = np.ascontiguousarray(
            np.moveaxis(pool.gather_to_host([1, 2]), 1, 0)
        )
        cache = HostTierCache(1 << 20)
        assert cache.put(0xA, group_a)
        observed = []
        loader = StorageToDeviceHandler(
            pool,
            connector.engine,
            connector.file_mapper,
            host_cache=cache,
            rtt_observer=lambda nbytes, s: observed.append((nbytes, s)),
        )
        loader.transfer_async(
            5, group_blocks_per_file([0xA, 0xB], [20, 21, 22, 23], 2)
        )
        assert loader.wait(5) == JobStatus.SUCCEEDED
        assert len(observed) == 1
        nbytes, seconds = observed[0]
        assert nbytes == group_a.nbytes  # one group's file bytes only
        assert seconds > 0

        # A fully host-served job contributes NO observation.
        group_b = np.ascontiguousarray(
            np.moveaxis(pool.gather_to_host([3, 4]), 1, 0)
        )
        assert cache.put(0xB, group_b)
        loader.transfer_async(
            6, group_blocks_per_file([0xA, 0xB], [24, 25, 26, 27], 2)
        )
        assert loader.wait(6) == JobStatus.SUCCEEDED
        assert len(observed) == 1
        connector.close()


class TestManager:
    def test_lookup_consecutive(self, tmp_path):
        connector, pool = make_connector(tmp_path)
        manager = connector.get_manager()
        fill_pool_blocks(pool, [0, 1, 2, 3])
        connector.store_handler.transfer_async(
            1, group_blocks_per_file([0x1, 0x2], [0, 1, 2, 3], 2)
        )
        assert connector.store_handler.wait(1) == JobStatus.SUCCEEDED

        assert manager.lookup([0x1, 0x2]) == 2
        assert manager.lookup([0x1, 0x2, 0x3]) == 2
        assert manager.lookup([0x3, 0x1, 0x2]) == 0  # gap at the start
        assert manager.lookup([]) == 0

        output = manager.prepare_store([0x5, 0x6])
        assert output.block_hashes_to_store == [0x5, 0x6]
        assert output.block_hashes_evicted == []
        connector.close()

    def test_touch_refreshes_mtime(self, tmp_path):
        connector, pool = make_connector(tmp_path)
        manager = connector.get_manager()
        fill_pool_blocks(pool, [0, 1])
        connector.store_handler.transfer_async(
            1, group_blocks_per_file([0x9], [0, 1], 2)
        )
        assert connector.store_handler.wait(1) == JobStatus.SUCCEEDED
        path = connector.file_mapper.get_file_name(0x9)
        old = time.time() - 3600
        os.utime(path, (old, old))
        manager.touch([0x9])
        assert os.path.getmtime(path) > old + 1800
        manager.touch([0xDEAD])  # missing file: best-effort no-raise
        connector.close()


class TestSpecValidation:
    def test_block_geometry_must_divide(self, tmp_path):
        with pytest.raises(ValueError):
            TPUOffloadSpec(
                shared_storage_path=str(tmp_path),
                model_name="m",
                device_block_size=16,
                offloaded_block_size=24,
            )

    def test_blocks_per_file(self, tmp_path):
        spec = TPUOffloadSpec(
            shared_storage_path=str(tmp_path),
            model_name="m",
            device_block_size=16,
            offloaded_block_size=64,
        )
        assert spec.blocks_per_file == 4
