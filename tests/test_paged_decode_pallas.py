"""Pallas paged-decode kernel vs the XLA gather implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.ops.paged_attention import paged_attention
from llm_d_kv_cache_manager_tpu.ops.paged_decode_pallas import (
    paged_decode_attention_pallas,
)


def make_case(key, B, H, Hkv, D, num_blocks, bs, max_blocks, ctx):
    kq, kkv, kt = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, D), jnp.float32).astype(jnp.bfloat16)
    kv = jax.random.normal(
        kkv, (num_blocks, 2, bs, Hkv, D), jnp.float32
    ).astype(jnp.bfloat16)
    # Unique pool blocks per sequence, pad slots point at block 0.
    tables = []
    used = 1
    for b in range(B):
        n = -(-int(ctx[b]) // bs)
        ids = list(range(used, used + n))
        used += n
        tables.append(ids + [0] * (max_blocks - n))
    table = jnp.asarray(tables, jnp.int32)
    return q, kv, table, jnp.asarray(ctx, jnp.int32)


@pytest.mark.parametrize(
    "B,H,Hkv,D,max_blocks,ctx",
    [
        (1, 8, 4, 64, 8, [64]),  # exact block multiple
        (2, 8, 2, 64, 8, [61, 33]),  # ragged contexts
        (3, 4, 4, 128, 8, [16, 7, 128]),  # MHA, tiny and full contexts
        (2, 8, 4, 64, 7, [97, 112]),  # max_blocks % BLOCKS_PER_STEP != 0
    ],
)
def test_matches_xla_gather(B, H, Hkv, D, max_blocks, ctx):
    bs = 16
    q, kv, table, ctx_arr = make_case(
        jax.random.PRNGKey(0), B, H, Hkv, D, 64, bs, max_blocks, ctx
    )
    ref = paged_attention(q, kv, table, ctx_arr)
    got = paged_decode_attention_pallas(
        q, kv, table, ctx_arr, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(ref, np.float32),
        rtol=0.05,
        atol=0.05,
    )


@pytest.mark.parametrize("blocks_per_step", [1, 2, 8])
def test_blocks_per_step_variants_match(blocks_per_step):
    """The tile size bench.py sweeps on the chip must be correctness-
    neutral at every value (ragged contexts + non-divisible tables)."""
    bs = 16
    q, kv, table, ctx_arr = make_case(
        jax.random.PRNGKey(2), 2, 8, 4, 64, 64, bs, 7, [97, 33]
    )
    ref = paged_attention(q, kv, table, ctx_arr)
    got = paged_decode_attention_pallas(
        q, kv, table, ctx_arr,
        interpret=True,
        blocks_per_step=blocks_per_step,
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(ref, np.float32),
        rtol=0.05,
        atol=0.05,
    )


def test_mxu_native_variant_matches():
    """The bf16-operand (mxu_native) dot path must agree with the f32
    upcast path within bf16 tolerance; bench.py times both on the chip."""
    bs = 16
    q, kv, table, ctx_arr = make_case(
        jax.random.PRNGKey(3), 2, 8, 4, 64, 64, bs, 7, [97, 33]
    )
    ref = paged_attention(q, kv, table, ctx_arr)
    got = paged_decode_attention_pallas(
        q, kv, table, ctx_arr, interpret=True, mxu_native=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(ref, np.float32),
        rtol=0.05,
        atol=0.05,
    )


def test_context_one_token():
    """ctx=1: only the first slot of the first block is visible."""
    bs = 16
    q, kv, table, ctx_arr = make_case(
        jax.random.PRNGKey(1), 1, 4, 2, 64, 16, bs, 4, [1]
    )
    ref = paged_attention(q, kv, table, ctx_arr)
    got = paged_decode_attention_pallas(
        q, kv, table, ctx_arr, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(ref, np.float32),
        rtol=0.05,
        atol=0.05,
    )
