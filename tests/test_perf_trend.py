"""Perf-trend gate (hack/perf_trend.py; ISSUE 14 satellite).

The acceptance contract directly: the tool passes on the repo's real
BENCH_r01–r06 trajectory, fails on a synthetic regressed artifact,
parses every artifact shape the trajectory contains (parsed /
headline / compact), and skips errored runs as baselines.
"""

from __future__ import annotations

import json
import os

from hack.perf_trend import (
    evaluate,
    extract_headlines,
    load_trajectory,
    main,
)

REPO_ROOT = os.path.join(os.path.dirname(__file__), "..")


def _write(tmp_path, name: str, artifact: dict) -> None:
    (tmp_path / name).write_text(json.dumps(artifact))


class TestExtraction:
    def test_parsed_shape(self):
        headlines = extract_headlines(
            {
                "n": 1,
                "rc": 0,
                "parsed": {
                    "metric": "p50_ttft_speedup_precise_vs_round_robin",
                    "value": 4.457,
                    "unit": "x",
                },
            }
        )
        assert headlines == {"ttft.speedup": 4.457}

    def test_headline_regime_shape(self):
        headlines = extract_headlines(
            {
                "n": 6,
                "rc": 0,
                "headline": {
                    "regime": "event_storm",
                    "apply_msgs_per_sec": 519.1,
                    "consistency": 1.0,
                },
            }
        )
        assert headlines == {
            "event_storm.apply_sps": 519.1,
            "event_storm.consistency": 1.0,
        }

    def test_compact_shape_with_blocks(self):
        headlines = extract_headlines(
            {
                "n": 7,
                "rc": 0,
                "compact": {
                    "metric": "p50_ttft_speedup_precise_vs_round_robin",
                    "value": 4.0,
                    "read_path": {
                        "warm_sps": 2800.0,
                        "cold_sps": 90.0,
                        "mixed_sps": 170.0,
                    },
                    "event_storm": {
                        "apply_sps": 6000.0,
                        "consistency": 1.0,
                    },
                    "replica_scaleout": {
                        "single_sps": 2500.0,
                        "cluster3_sps": 400.0,
                    },
                },
            }
        )
        assert headlines["ttft.speedup"] == 4.0
        assert headlines["read_path.warm_sps"] == 2800.0
        assert headlines["event_storm.apply_sps"] == 6000.0
        assert headlines["replica_scaleout.cluster3_sps"] == 400.0

    def test_full_regime_cells_shape(self):
        headlines = extract_headlines(
            {
                "rc": 0,
                "read_path": {
                    "warm_multi_turn": {"scores_per_sec": 2843.5}
                },
                "replica_scaleout": {
                    "single": {"scores_per_sec": 2000.0},
                    "cluster_3_replicas": {"scores_per_sec": 300.0},
                },
                "event_storm": {
                    "consolidated_pollers_1": {
                        "apply_msgs_per_sec": 519.1
                    },
                    "gap_storm": {"post_resync_consistency": 1.0},
                },
            }
        )
        assert headlines["read_path.warm_sps"] == 2843.5
        assert headlines["replica_scaleout.single_sps"] == 2000.0
        assert headlines["event_storm.apply_sps"] == 519.1
        assert headlines["event_storm.consistency"] == 1.0

    def test_errored_artifact_yields_nothing(self):
        assert (
            extract_headlines(
                {
                    "n": 4,
                    "rc": 0,
                    "parsed": {
                        "metric": "p50_ttft_speedup_precise",
                        "value": 0.0,
                        "error": "device unavailable",
                    },
                }
            )
            == {}
        )
        assert extract_headlines({"n": 9, "rc": 1}) == {}


class TestGate:
    def test_passes_on_real_trajectory(self):
        assert main(["--dir", REPO_ROOT]) == 0

    def test_real_trajectory_has_headlines(self):
        runs = load_trajectory(REPO_ROOT)
        assert len(runs) >= 6
        measured = {
            key for _, _, headlines in runs for key in headlines
        }
        assert "ttft.speedup" in measured
        assert "event_storm.apply_sps" in measured

    def test_fails_on_synthetic_regression(self, tmp_path):
        _write(
            tmp_path,
            "BENCH_r01.json",
            {
                "n": 1,
                "rc": 0,
                "headline": {
                    "regime": "event_storm",
                    "apply_msgs_per_sec": 500.0,
                },
            },
        )
        _write(
            tmp_path,
            "BENCH_r02.json",
            {
                "n": 2,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 400.0}},
            },
        )
        assert main(["--dir", str(tmp_path)]) == 1

    def test_within_threshold_passes(self, tmp_path):
        _write(
            tmp_path,
            "BENCH_r01.json",
            {
                "n": 1,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 500.0}},
            },
        )
        _write(
            tmp_path,
            "BENCH_r02.json",
            {
                "n": 2,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 460.0}},
            },
        )
        assert main(["--dir", str(tmp_path)]) == 0

    def test_errored_run_never_baselines(self, tmp_path):
        # r2 is errored — the r3 value compares against r1, and a
        # regression vs r1 still fails even with the errored run in
        # between.
        _write(
            tmp_path,
            "BENCH_r01.json",
            {
                "n": 1,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 500.0}},
            },
        )
        _write(tmp_path, "BENCH_r02.json", {"n": 2, "rc": 1})
        _write(
            tmp_path,
            "BENCH_r03.json",
            {
                "n": 3,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 100.0}},
            },
        )
        assert main(["--dir", str(tmp_path)]) == 1

    def test_headline_absent_from_newest_not_compared(self, tmp_path):
        _write(
            tmp_path,
            "BENCH_r01.json",
            {
                "n": 1,
                "rc": 0,
                "compact": {"read_path": {"warm_sps": 9000.0}},
            },
        )
        _write(
            tmp_path,
            "BENCH_r02.json",
            {
                "n": 2,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 100.0}},
            },
        )
        assert main(["--dir", str(tmp_path)]) == 0

    def test_unreadable_artifact_skipped(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text("{not json")
        _write(
            tmp_path,
            "BENCH_r02.json",
            {
                "n": 2,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 100.0}},
            },
        )
        assert main(["--dir", str(tmp_path)]) == 0

    def test_empty_directory_passes(self, tmp_path):
        assert main(["--dir", str(tmp_path)]) == 0

    def test_custom_threshold(self, tmp_path):
        _write(
            tmp_path,
            "BENCH_r01.json",
            {
                "n": 1,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 500.0}},
            },
        )
        _write(
            tmp_path,
            "BENCH_r02.json",
            {
                "n": 2,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 460.0}},
            },
        )
        # 8% drop: inside the default gate, outside a 5% one.
        assert main(["--dir", str(tmp_path)]) == 0
        assert (
            main(["--dir", str(tmp_path), "--threshold", "0.05"]) == 1
        )

    def test_table_marks_regression(self, tmp_path):
        _write(
            tmp_path,
            "BENCH_r01.json",
            {
                "n": 1,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 500.0}},
            },
        )
        _write(
            tmp_path,
            "BENCH_r02.json",
            {
                "n": 2,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 100.0}},
            },
        )
        runs = load_trajectory(str(tmp_path))
        lines, regressions = evaluate(runs, 0.10)
        assert regressions and "event_storm.apply_sps" in regressions[0]
        assert any("REGRESSED" in line for line in lines)


class TestMultichipDisplay:
    """ISSUE 15 satellite: MULTICHIP_r*.json folded into the trend
    table — display-only, never gated."""

    def test_extracts_status_and_devices(self):
        from hack.perf_trend import extract_multichip

        assert extract_multichip(
            {"n_devices": 8, "rc": 0, "ok": True, "tail": ""}
        ) == {"status": "ok", "n_devices": 8}
        assert extract_multichip({"rc": 1, "tail": "boom"})["status"] == (
            "FAIL(rc=1)"
        )
        assert extract_multichip({"skipped": True})["status"] == "skipped"

    def test_extracts_numeric_throughput_fields(self):
        from hack.perf_trend import extract_multichip

        facts = extract_multichip(
            {
                "n_devices": 4,
                "rc": 0,
                "staged_mb_s": 123.4,
                "host_offload": {"lanes_best_mb_s": 456.0},
                "tail": "staged offload dry run ok on 4 chips",
            }
        )
        assert facts["staged_mb_s"] == 123.4
        assert facts["lanes_best_mb_s"] == 456.0
        assert facts["staged_offload"] == "ok"

    def test_display_lines_and_never_gated(self, tmp_path):
        from hack.perf_trend import (
            load_multichip_trajectory,
            main,
            multichip_lines,
        )

        _write(
            tmp_path,
            "BENCH_r01.json",
            {
                "n": 1,
                "rc": 0,
                "compact": {"event_storm": {"apply_sps": 500.0}},
            },
        )
        _write(
            tmp_path,
            "MULTICHIP_r01.json",
            {"n_devices": 8, "rc": 1, "tail": "exploded"},
        )
        _write(
            tmp_path,
            "MULTICHIP_r02.json",
            {"n_devices": 8, "rc": 0, "staged_mb_s": 99.5, "tail": ""},
        )
        runs = load_multichip_trajectory(str(tmp_path))
        assert [n for n, _, _ in runs] == [1, 2]
        lines = multichip_lines(runs)
        assert any("FAIL(rc=1)" in line for line in lines)
        assert any("staged_mb_s=99.500" in line for line in lines)
        # A failing MULTICHIP artifact never fails the gate.
        assert main(["--dir", str(tmp_path)]) == 0

    def test_real_trajectory_parses(self):
        from hack.perf_trend import load_multichip_trajectory

        runs = load_multichip_trajectory(REPO_ROOT)
        assert len(runs) >= 5
        assert all("status" in facts for _, _, facts in runs)

    def test_unreadable_multichip_skipped(self, tmp_path):
        from hack.perf_trend import load_multichip_trajectory

        (tmp_path / "MULTICHIP_r01.json").write_text("{nope")
        assert load_multichip_trajectory(str(tmp_path)) == []


class TestWhatIfGate:
    """ISSUE 18 tentpole: WHATIF_r*.json capacity trajectory + the
    live reference A/B, gated like bench headlines."""

    def _whatif(self, n, hit_rate=0.75, parity=1.0):
        return {
            "run": n,
            "rc": 0,
            "headlines": {
                "whatif.hit_rate": hit_rate,
                "whatif.recorded_parity": parity,
                "whatif.ab_hit_parity": parity,
            },
        }

    def test_extract_shapes(self):
        from hack.perf_trend import extract_whatif

        assert extract_whatif(self._whatif(1))["whatif.hit_rate"] == 0.75
        assert extract_whatif({"rc": 1, "headlines": {"x": 1.0}}) == {}
        assert extract_whatif({"rc": 0, "headlines": "nope"}) == {}
        # Non-positive and non-numeric values never become baselines.
        assert (
            extract_whatif(
                {"rc": 0, "headlines": {"a": 0.0, "b": "x", "c": 2.0}}
            )
            == {"c": 2.0}
        )

    def test_real_trajectory_parses(self):
        from hack.perf_trend import load_whatif_trajectory

        runs = load_whatif_trajectory(REPO_ROOT)
        assert len(runs) >= 1
        assert "whatif.hit_rate" in runs[-1][2]
        assert runs[-1][2]["whatif.recorded_parity"] == 1.0

    def test_trajectory_regression_fails(self, tmp_path):
        _write(tmp_path, "WHATIF_r01.json", self._whatif(1, hit_rate=0.80))
        _write(tmp_path, "WHATIF_r02.json", self._whatif(2, hit_rate=0.40))
        assert (
            main(["--dir", str(tmp_path), "--skip-whatif"]) == 1
        )

    def test_trajectory_within_threshold_passes(self, tmp_path):
        _write(tmp_path, "WHATIF_r01.json", self._whatif(1, hit_rate=0.80))
        _write(tmp_path, "WHATIF_r02.json", self._whatif(2, hit_rate=0.75))
        assert (
            main(["--dir", str(tmp_path), "--skip-whatif"]) == 0
        )

    def test_no_artifacts_means_no_whatif_gate(self, tmp_path):
        from hack.perf_trend import whatif_evaluate

        assert whatif_evaluate([], 0.10, "/nope", False) == ([], [])

    def test_skip_live_still_gates_trajectory(self, tmp_path):
        from hack.perf_trend import load_whatif_trajectory, whatif_evaluate

        _write(tmp_path, "WHATIF_r01.json", self._whatif(1, hit_rate=0.80))
        _write(tmp_path, "WHATIF_r02.json", self._whatif(2, hit_rate=0.40))
        runs = load_whatif_trajectory(str(tmp_path))
        lines, regressions = whatif_evaluate(runs, 0.10, "/nope", True)
        assert any("--skip-whatif" in line for line in lines)
        assert regressions and "whatif.hit_rate" in regressions[0]

    def test_missing_reference_skips_live_cleanly(self, tmp_path):
        from hack.perf_trend import load_whatif_trajectory, whatif_evaluate

        _write(tmp_path, "WHATIF_r01.json", self._whatif(1))
        runs = load_whatif_trajectory(str(tmp_path))
        lines, regressions = whatif_evaluate(
            runs, 0.10, str(tmp_path / "nope.cbor"), False
        )
        assert any("no reference capture" in line for line in lines)
        assert regressions == []

    def test_live_check_fails_inflated_baseline(self, tmp_path):
        """A recorded baseline the live engine can no longer meet is
        a capacity regression — the exact planted case the smoke
        drives through the CLI, here in-process."""
        from hack.perf_trend import load_whatif_trajectory, whatif_evaluate

        reference = os.path.join(
            REPO_ROOT, "tests", "testdata", "whatif_reference.cbor"
        )
        _write(tmp_path, "WHATIF_r01.json", self._whatif(1, hit_rate=0.99))
        runs = load_whatif_trajectory(str(tmp_path))
        lines, regressions = whatif_evaluate(runs, 0.10, reference, False)
        assert any("live reference A/B" in line for line in lines)
        assert any("whatif.hit_rate (live)" in r for r in regressions)
        # The parity headlines match the planted artifact exactly, so
        # only the inflated one regresses.
        assert len(regressions) == 1
