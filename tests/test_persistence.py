"""Crash-recovery suite for the persistence subsystem.

Pins the guarantees docs/persistence.md promises: snapshot publish is
atomic under a killed writer (a partial tmp file is never loaded), a
journal with a truncated final record replays up to the last valid
record, and a full snapshot+replay round-trip restores identical
``lookup()`` results across the in-process backends.
"""

import os

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    PodEntry,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    CborDecodeError,
    decode_canonical,
    encode_canonical,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    CostAwareIndexConfig,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (
    InstrumentedIndex,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.persistence import (
    Journal,
    PersistenceConfig,
    PersistenceManager,
    recover,
)
from llm_d_kv_cache_manager_tpu.persistence.journal import (
    iter_journal,
    list_segments,
)
from llm_d_kv_cache_manager_tpu.persistence.snapshot import (
    SnapshotError,
    load_latest_snapshot,
    read_snapshot,
    write_snapshot,
)

POD_A = PodEntry("pod-a", "hbm")
POD_B = PodEntry("pod-b", "host")


def make_index(kind: str):
    if kind == "in_memory":
        return InMemoryIndex(InMemoryIndexConfig(size=10_000))
    if kind == "cost_aware":
        return CostAwareMemoryIndex(
            CostAwareIndexConfig(max_cost_bytes=64 * 1024 * 1024)
        )
    raise ValueError(kind)


def populate(index) -> list:
    """A small but non-trivial state: two pods, two tiers, a chain."""
    index.add([1, 2, 3], [11, 12, 13], [POD_A])
    index.add([2, 3], [12, 13], [POD_B])
    index.add([4], [14], [PodEntry("pod-a", "host")])
    index.evict(4, [PodEntry("pod-a", "host")])
    return [11, 12, 13, 14, 99]  # 14 evicted, 99 never present


class TestCborDecoder:
    def test_roundtrip(self):
        doc = [0, -5, 2**64 - 1, "pod", b"\x00\xff", [True, None, []]]
        assert decode_canonical(encode_canonical(doc)) == doc

    def test_truncation_raises(self):
        data = encode_canonical([1, [2, 3], "abc"])
        for cut in range(1, len(data)):
            with pytest.raises(CborDecodeError):
                decode_canonical(data[:cut])

    def test_trailing_garbage_raises(self):
        with pytest.raises(CborDecodeError):
            decode_canonical(encode_canonical([1]) + b"\x00")


@pytest.mark.parametrize("kind", ["in_memory", "cost_aware"])
class TestSnapshotRoundTrip:
    def test_dump_restore_identical_lookup(self, kind, tmp_path):
        source = make_index(kind)
        keys = populate(source)
        block_entries, engine_map = source.dump_entries()
        write_snapshot(str(tmp_path), {"pod-a": 7}, block_entries, engine_map)

        restored = make_index(kind)
        info, entries, emap = load_latest_snapshot(str(tmp_path))
        restored.restore_entries(entries, emap)
        assert restored.lookup(keys) == source.lookup(keys)
        assert info.watermarks == {"pod-a": 7}
        # Engine-key mappings survive too (parent resolution after
        # recovery depends on them).
        assert restored.get_request_key(1) == 11

    def test_restore_respects_capacity_bounds(self, kind, tmp_path):
        source = make_index(kind)
        for i in range(50):
            source.add([1000 + i], [2000 + i], [POD_A])
        block_entries, engine_map = source.dump_entries()
        if kind == "in_memory":
            bounded = InMemoryIndex(InMemoryIndexConfig(size=10))
        else:
            # Budget for roughly a handful of keys.
            bounded = CostAwareMemoryIndex(
                CostAwareIndexConfig(max_cost_bytes=2000)
            )
        bounded.restore_entries(block_entries, engine_map)
        found = bounded.lookup([2000 + i for i in range(50)])
        assert 0 < len(found) < 50
        # LRU-first dump order: the NEWEST keys are the survivors.
        assert 2049 in found


class TestSnapshotAtomicity:
    def test_partial_tmp_file_never_loaded(self, tmp_path):
        """A writer killed before the rename leaves only a .tmp file;
        the loader must not even consider it."""
        index = make_index("in_memory")
        populate(index)
        entries, emap = index.dump_entries()
        info = write_snapshot(str(tmp_path), {}, entries, emap)
        # Simulate a killed second writer: a half-written tmp file.
        torn = os.path.join(
            str(tmp_path), "snapshot-99999999999999999999.snap.tmp.123.4"
        )
        with open(torn, "wb") as handle:
            handle.write(b"KVTPUSNP\x00\x01partial")
        loaded_info, _, _ = load_latest_snapshot(str(tmp_path))
        assert loaded_info.path == info.path

    def test_torn_published_file_falls_back_to_previous(self, tmp_path):
        index = make_index("in_memory")
        populate(index)
        entries, emap = index.dump_entries()
        good = write_snapshot(str(tmp_path), {}, entries, emap)
        newer = write_snapshot(
            str(tmp_path), {}, entries, emap, retain=5
        )
        # Truncate the newer snapshot mid-body (torn write on a
        # non-atomic filesystem / disk corruption).
        size = os.path.getsize(newer.path)
        with open(newer.path, "r+b") as handle:
            handle.truncate(size - 10)
        with pytest.raises(SnapshotError):
            read_snapshot(newer.path)
        loaded_info, loaded_entries, _ = load_latest_snapshot(
            str(tmp_path)
        )
        assert loaded_info.path == good.path
        assert len(loaded_entries) == len(entries)

    def test_bad_magic_and_version_rejected(self, tmp_path):
        path = os.path.join(str(tmp_path), "snapshot-1.snap")
        with open(path, "wb") as handle:
            handle.write(b"NOTASNAP" + b"\x00" * 14)
        with pytest.raises(SnapshotError, match="magic"):
            read_snapshot(path)
        assert load_latest_snapshot(str(tmp_path)) is None

    def test_retention_prunes_old_snapshots(self, tmp_path):
        index = make_index("in_memory")
        populate(index)
        entries, emap = index.dump_entries()
        for _ in range(4):
            write_snapshot(str(tmp_path), {}, entries, emap, retain=2)
        remaining = [
            name
            for name in os.listdir(str(tmp_path))
            if name.endswith(".snap")
        ]
        assert len(remaining) == 2


class TestJournal:
    def test_torn_final_record_replays_prefix(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [11], [POD_A])
        journal.record_add("pod-a", 2, [2], [12], [POD_A])
        journal.record_evict("pod-a", 3, [1], [POD_A])
        journal.close()
        (_, path), = list_segments(str(tmp_path))
        # Tear the tail mid-record: every prefix length must yield
        # exactly the records whose framing fully survived.
        full = open(path, "rb").read()
        with open(path, "r+b") as handle:
            handle.truncate(len(full) - 7)
        records = list(iter_journal(str(tmp_path)))
        assert [r.seq for r in records] == [1, 2]

    def test_corrupt_crc_stops_replay(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 1, [1], [11], [POD_A])
        journal.record_add("pod-a", 2, [2], [12], [POD_A])
        journal.close()
        (_, path), = list_segments(str(tmp_path))
        data = bytearray(open(path, "rb").read())
        data[-1] ^= 0xFF  # flip a byte in the LAST record's body
        with open(path, "wb") as handle:
            handle.write(bytes(data))
        records = list(iter_journal(str(tmp_path)))
        assert [r.seq for r in records] == [1]

    def test_rotation_and_fresh_segment_on_reopen(self, tmp_path):
        journal = Journal(str(tmp_path), segment_max_bytes=128)
        for i in range(10):
            journal.record_add("pod-a", i + 1, [i], [100 + i], [POD_A])
        journal.close()
        first_count = len(list_segments(str(tmp_path)))
        assert first_count > 1  # rotation happened
        # A new Journal never appends to a possibly-torn tail segment.
        journal2 = Journal(str(tmp_path), segment_max_bytes=128)
        journal2.record_add("pod-a", 11, [10], [110], [POD_A])
        journal2.close()
        assert len(list_segments(str(tmp_path))) == first_count + 1
        assert [r.seq for r in iter_journal(str(tmp_path))] == list(
            range(1, 12)
        )

    def test_watermarks_track_max_seq_per_pod(self, tmp_path):
        journal = Journal(str(tmp_path))
        journal.record_add("pod-a", 5, [1], [11], [POD_A])
        journal.record_add("pod-b", 2, [2], [12], [POD_B])
        journal.record_evict("pod-a", 7, [1], [POD_A])
        assert journal.watermarks() == {"pod-a": 7, "pod-b": 2}
        journal.close()


class TestRecovery:
    def test_cold_start_reports_cold(self, tmp_path):
        index = make_index("in_memory")
        report = recover(
            index, PersistenceConfig(directory=str(tmp_path))
        )
        assert report.status == "cold"
        assert report.block_keys_restored == 0

    @pytest.mark.parametrize("kind", ["in_memory", "cost_aware"])
    def test_snapshot_plus_replay_round_trip(self, kind, tmp_path):
        """The acceptance round trip: snapshot at a boundary, more
        traffic journaled after it, recovery = snapshot + tail."""
        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        source = make_index(kind)
        keys = populate(source)
        # Journal mirrors the applied ops (as the pool tap would).
        manager.journal.record_add(
            "pod-a", 1, [1, 2, 3], [11, 12, 13], [POD_A]
        )
        manager.journal.record_add("pod-b", 1, [2, 3], [12, 13], [POD_B])
        manager.snapshot(source)
        # Post-snapshot traffic lives only in the journal tail.
        source.add([5], [15], [POD_B])
        manager.journal.record_add("pod-b", 2, [5], [15], [POD_B])
        source.evict(2, [POD_A])
        manager.journal.record_evict("pod-a", 2, [2], [POD_A])
        manager.close()

        restored = make_index(kind)
        report = recover(restored, config)
        assert report.status == "warm"
        assert report.records_replayed == 2
        all_keys = keys + [15]
        assert restored.lookup(all_keys) == source.lookup(all_keys)

    def test_replay_applies_purge_in_order(self, tmp_path):
        """OP_PURGE closes a real gap: a purge_pod between the snapshot
        and the crash used to be lost on replay — the replayed adds
        resurrected exactly the entries the operator dropped.  The
        purge record must replay in journal order (adds before it come
        back, adds after it survive)."""
        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        source = make_index("in_memory")
        source.add([1, 2], [11, 12], [POD_A, POD_B])
        manager.journal.record_add(
            "pod-a", 1, [1, 2], [11, 12], [POD_A, POD_B]
        )
        source.purge_pod(POD_B.pod_identifier)
        manager.journal.record_purge(POD_B.pod_identifier)
        # POD_B re-claims one key AFTER the purge: must survive.
        source.add([2], [12], [POD_B])
        manager.journal.record_add("pod-b", 2, [2], [12], [POD_B])
        manager.close()

        restored = make_index("in_memory")
        recover(restored, config)
        assert restored.lookup([11, 12]) == source.lookup([11, 12])
        assert all(
            p.pod_identifier != POD_B.pod_identifier
            for p in restored.lookup([11]).get(11, [])
        )
        assert POD_B in restored.lookup([12])[12]

    def test_boundary_skips_uncompacted_covered_segments(self, tmp_path):
        """Snapshots carry their journal boundary: when compaction
        failed (crash between publish and compact), the covered
        pre-boundary segments must be skipped WHOLESALE — an
        uncompacted pre-boundary purge would otherwise replay against
        restored state whose covering re-adds the watermark skip
        elides."""
        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        source = make_index("in_memory")
        # History: purge pod-a, then re-admit it with seq<=watermark.
        manager.journal.record_purge(POD_A.pod_identifier)
        source.add([1], [11], [POD_A])
        manager.journal.record_add("pod-a", 5, [1], [11], [POD_A])
        info = manager.snapshot(source)
        assert info.journal_boundary is not None
        # Simulate the failed compaction: resurrect a covered segment
        # below the boundary holding the purge + re-add.
        import shutil

        from llm_d_kv_cache_manager_tpu.persistence.journal import (
            list_segments,
        )

        survivors = list_segments(config.journal_dir)
        stale = Journal(str(tmp_path / "stale"))
        stale.record_purge(POD_A.pod_identifier)
        stale.record_add("pod-a", 5, [1], [11], [POD_A])
        stale.close()
        for segment_id, path in list_segments(str(tmp_path / "stale")):
            low_id = info.journal_boundary - 1
            target = os.path.join(
                config.journal_dir,
                f"segment-{low_id:012d}.kvj",
            )
            assert all(sid != low_id for sid, _ in survivors)
            shutil.copy(path, target)
        manager.close()

        restored = make_index("in_memory")
        report = recover(restored, config)
        # The covered segment (purge + watermark-skippable re-add)
        # never replays: pod-a's snapshot state survives.
        assert restored.lookup([11]) == {11: [POD_A]}
        assert report.records_replayed == 0

    def test_recovery_gates_on_durable_backend(self, tmp_path):
        """Startup recovery must never pipeline a file snapshot or a
        journal replay into a durable (server-side, shared) backend —
        the server is authoritative (docs/persistence.md §6)."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            RedisIndexConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
            RedisIndex,
        )
        from tests.helpers.miniresp import MiniRespServer

        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        stale_state = make_index("in_memory")
        stale_state.add([1], [11], [POD_A])
        manager.journal.record_add("pod-a", 1, [1], [11], [POD_A])
        manager.snapshot(stale_state)
        manager.close()

        server = MiniRespServer()
        try:
            index = RedisIndex(RedisIndexConfig(address=server.address))
            report = recover(index, config)
            assert report.status == "cold"
            assert report.block_keys_restored == 0
            assert report.records_replayed == 0
            # Nothing was resurrected into the server.
            assert index.lookup([11]) == {}
        finally:
            server.close()

    def test_compact_keep_last_retains_newest_segments(self, tmp_path):
        journal = Journal(str(tmp_path), segment_max_bytes=1)
        for seq in range(1, 7):  # one segment per append at this size
            journal.record_add("pod-a", seq, [seq], [seq], [POD_A])
        from llm_d_kv_cache_manager_tpu.persistence.journal import (
            list_segments,
            tail,
        )

        assert len(list_segments(str(tmp_path))) >= 6
        removed = journal.compact_keep_last(2)
        assert removed >= 4
        assert len(list_segments(str(tmp_path))) == 2
        # The retained suffix still tails cleanly.
        records, _ = tail(str(tmp_path))
        assert [r.seq for r in records] == [5, 6]
        assert journal.compact_keep_last(0) == 0  # disabled
        journal.close()

    def test_replay_skips_records_strictly_below_watermark(
        self, tmp_path
    ):
        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        index = make_index("in_memory")
        index.add([1], [11], [POD_A])
        manager.journal.record_add("pod-a", 4, [1], [11], [POD_A])
        manager.snapshot(index)  # watermark pod-a=4, journal compacted
        # Late duplicate delivery BELOW the watermark (e.g. a replayed
        # publisher), a same-seq sibling AT the watermark (one
        # message's events share a seq and can straddle the snapshot
        # boundary — its effect may be missing from the dump, so it
        # MUST replay), and genuinely new traffic above it.
        manager.journal.record_add("pod-a", 3, [9], [19], [POD_A])
        manager.journal.record_add("pod-a", 4, [5], [15], [POD_A])
        manager.journal.record_add("pod-a", 6, [6], [16], [POD_A])
        manager.close()

        restored = make_index("in_memory")
        report = recover(restored, config)
        assert report.records_skipped == 1
        assert report.records_replayed == 2
        found = restored.lookup([11, 15, 16, 19])
        assert set(found) == {11, 15, 16}

    def test_failed_snapshot_publish_keeps_lag_truthful(
        self, tmp_path, monkeypatch
    ):
        """A failed snapshot write (ENOSPC class) must not zero the
        journal-lag telemetry: the replay cost it reports is real
        until a snapshot actually publishes."""
        import llm_d_kv_cache_manager_tpu.persistence.recovery as rec

        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        index = make_index("in_memory")
        index.add([1], [11], [POD_A])
        manager.journal.record_add("pod-a", 1, [1], [11], [POD_A])

        def boom(*a, **kw):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr(rec, "write_snapshot", boom)
        with pytest.raises(OSError):
            manager.snapshot(index)
        assert manager.status()["journal_records_since_snapshot"] == 1
        monkeypatch.undo()
        manager.snapshot(index)
        assert manager.status()["journal_records_since_snapshot"] == 0
        manager.close()

    def test_compaction_removes_covered_segments(self, tmp_path):
        config = PersistenceConfig(
            directory=str(tmp_path), journal_segment_max_bytes=128
        )
        manager = PersistenceManager(config)
        index = make_index("in_memory")
        for i in range(10):
            index.add([i], [100 + i], [POD_A])
            manager.journal.record_add(
                "pod-a", i + 1, [i], [100 + i], [POD_A]
            )
        assert len(list_segments(config.journal_dir)) > 1
        manager.snapshot(index)
        # Everything below the boundary is covered by the snapshot.
        assert list_segments(config.journal_dir) == []
        manager.close()
        restored = make_index("in_memory")
        recover(restored, config)
        keys = [100 + i for i in range(10)]
        assert restored.lookup(keys) == index.lookup(keys)


class TestBackendContractExtensions:
    def test_instrumented_delegates(self):
        inner = make_index("in_memory")
        wrapped = InstrumentedIndex(inner)
        wrapped.add([1], [11], [POD_A])
        entries, emap = wrapped.dump_entries()
        assert entries and emap
        other = InstrumentedIndex(make_index("in_memory"))
        assert other.restore_entries(entries, emap) == 1
        assert other.lookup([11]) == inner.lookup([11])

    def test_redis_backend_answers_dump_restore(self):
        """The long-documented Redis no-op was replaced by a SCAN-based
        dump when the backend was promoted to replica duty
        (docs/replication.md); the round trip must hold like every
        other backend's."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            RedisIndexConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
            RedisIndex,
        )
        from tests.helpers.miniresp import MiniRespServer

        server = MiniRespServer()
        try:
            index = RedisIndex(RedisIndexConfig(address=server.address))
            index.add([21, 22], [121, 122], [POD_A])
            entries, emap = index.dump_entries()
            assert {k for k, _ in entries} == {121, 122}
            assert dict(emap) == {21: 121, 22: 122}
            index._client.execute("FLUSHALL")
            assert index.restore_entries(entries, emap) == 2
            assert set(index.lookup([121, 122])) == {121, 122}
            assert index.get_request_key(21) == 121
        finally:
            server.close()


class TestPoolJournalTap:
    def test_applied_events_flow_to_journal_and_recover(self, tmp_path):
        """End to end through the real wire path: msgpack BlockStored/
        BlockRemoved -> sharded pool -> index apply -> journal tap ->
        recovery into a fresh index with identical lookups."""
        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = Pool(
            index,
            db,
            PoolConfig(concurrency=2),
            journal=manager.journal,
        )
        pool.start()

        def deliver(pod, seq, *events):
            batch = EventBatch(ts=1.0, events=list(events))
            pool.add_task(
                Message(
                    topic=f"kv@{pod}@m",
                    payload=batch.encode(),
                    pod_identifier=pod,
                    model_name="m",
                    seq=seq,
                )
            )
            pool.drain()

        deliver(
            "pod-a",
            1,
            BlockStored(
                block_hashes=[101, 102],
                parent_block_hash=None,
                token_ids=[1, 2, 3, 4, 5, 6, 7, 8],
                block_size=4,
                medium="hbm",
            ),
        )
        deliver("pod-a", 2, BlockRemoved(block_hashes=[102]))
        pool.shutdown()
        manager.close()

        request_keys = db.tokens_to_kv_block_keys(
            0, [1, 2, 3, 4, 5, 6, 7, 8], "m"
        )
        restored = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        report = recover(restored, config)
        assert report.records_replayed == 2
        assert report.pods == ["pod-a"]
        assert restored.lookup(request_keys) == index.lookup(request_keys)
        # The stored-then-removed second block is absent in both.
        assert request_keys[1] not in restored.lookup(request_keys)

    def test_failed_apply_is_not_journaled(self, tmp_path):
        """The tap sits AFTER the apply: an event whose parent cannot
        be resolved (skipped by the digest) must leave no record."""
        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = Pool(
            index, db, PoolConfig(concurrency=1), journal=manager.journal
        )
        pool.start()
        batch = EventBatch(
            ts=1.0,
            events=[
                BlockStored(
                    block_hashes=[7],
                    parent_block_hash=999999,  # unknown parent: skipped
                    token_ids=[1, 2, 3, 4],
                    block_size=4,
                )
            ],
        )
        pool.add_task(
            Message(
                topic="kv@pod-a@m",
                payload=batch.encode(),
                pod_identifier="pod-a",
                model_name="m",
                seq=1,
            )
        )
        pool.drain()
        pool.shutdown()
        manager.close()
        assert list(iter_journal(config.journal_dir)) == []


class TestManagerStatus:
    def test_status_reflects_snapshot_and_lag(self, tmp_path):
        config = PersistenceConfig(directory=str(tmp_path))
        manager = PersistenceManager(config)
        status = manager.status()
        assert status["snapshot_path"] is None
        index = make_index("in_memory")
        index.add([1], [11], [POD_A])
        manager.journal.record_add("pod-a", 1, [1], [11], [POD_A])
        assert manager.status()["journal_records_since_snapshot"] == 1
        manager.snapshot(index)
        status = manager.status()
        assert status["snapshot_path"]
        assert status["snapshot_bytes"] > 0
        assert status["journal_records_since_snapshot"] == 0
        manager.close()
