"""Continuous-profiling plane: sampling profiler, lock-contention
timing, gauge timelines, and their ``/debug`` endpoints (ISSUE 14;
docs/observability.md "Continuous profiling plane").

Covers the acceptance-relevant properties directly:

* the profiler samples real threads, attributes them to stable
  ``kvtpu-*`` roles, exports valid collapsed-stack text and a top-N
  self-time table, bounds its folded-stack memory, and is provably
  inert at ``PROFILE_HZ=0``;
* ``tracked()``'s timing mode counts contended acquires per lock
  name (with wait EWMA/max and prometheus families) while the
  disarmed path returns the raw lock object;
* the timeline rings record/bound/filter series and survive broken
  sources;
* ``GET /debug/``, ``/debug/profile`` and ``/debug/timeline`` work
  through the booted HTTP service, including the disabled-404 paths.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    METRICS,
    counter_total,
)
from llm_d_kv_cache_manager_tpu.obs.profiler import (
    ProfilerConfig,
    SamplingProfiler,
    is_attributed,
    thread_role,
)
from llm_d_kv_cache_manager_tpu.obs.timeline import (
    GaugeTimeline,
    register_default_series,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder


def _busy_thread(name: str, stop: threading.Event) -> threading.Thread:
    def spin() -> None:
        while not stop.is_set():
            sum(range(200))

    thread = threading.Thread(target=spin, name=name, daemon=True)
    thread.start()
    return thread


# ------------------------------ roles -----------------------------------


class TestThreadRole:
    def test_worker_index_folds(self):
        assert thread_role("kvtpu-events-3") == "events"
        assert thread_role("kvtpu-tokenize-0") == "tokenize"
        assert thread_role("kvtpu-evplane-poller-12") == "evplane-poller"
        # ThreadPoolExecutor names its threads "<prefix>_<n>".
        assert thread_role("kvtpu-grpc_0") == "grpc"
        assert thread_role("kvtpu-uds-tokenizer_3") == "uds-tokenizer"

    def test_singleton_roles(self):
        assert thread_role("kvtpu-metrics-beat") == "metrics-beat"
        assert thread_role("kvtpu-http-handler") == "http-handler"

    def test_main_and_anonymous(self):
        assert thread_role("MainThread") == "main"
        assert thread_role("Thread-7") == "other:Thread-7"
        assert is_attributed("kvtpu-anything")
        assert not is_attributed("MainThread")
        assert not is_attributed("Thread-7")


# ---------------------------- profiler ----------------------------------


class TestSamplingProfiler:
    def test_hz_zero_is_inert(self):
        prof = SamplingProfiler(ProfilerConfig(hz=0))
        before = threading.active_count()
        assert prof.start() is False
        assert not prof.running()
        assert threading.active_count() == before
        assert prof.status()["samples"] == 0
        prof.close()  # harmless

    def test_samples_attribute_to_roles(self):
        stop = threading.Event()
        thread = _busy_thread("kvtpu-busy-0", stop)
        prof = SamplingProfiler(ProfilerConfig(hz=200))
        try:
            assert prof.start()
            deadline = time.time() + 5.0
            while (
                prof.status()["samples"] < 50 and time.time() < deadline
            ):
                time.sleep(0.02)
        finally:
            stop.set()
            prof.close()
            thread.join(timeout=5)
        status = prof.status()
        assert status["samples"] >= 50
        assert "busy" in status["roles"]
        assert status["attributed_samples"] > 0
        # The sampler never samples itself.
        assert "profiler" not in status["roles"]

    def test_collapsed_format_and_top(self):
        stop = threading.Event()
        thread = _busy_thread("kvtpu-busy-1", stop)
        prof = SamplingProfiler(ProfilerConfig(hz=200))
        prof.start()
        time.sleep(0.4)
        stop.set()
        prof.close()
        thread.join(timeout=5)
        lines = [
            line for line in prof.collapsed().splitlines() if line
        ]
        assert lines
        for line in lines:
            stack, _, count = line.rpartition(" ")
            assert count.isdigit() and int(count) >= 1
            assert ";" in stack  # role;frame at minimum
        busy_lines = [
            line for line in lines if line.startswith("busy;")
        ]
        assert busy_lines, lines[:5]
        top = prof.top(5)
        assert top and top[0]["self_samples"] >= top[-1]["self_samples"]
        assert all(
            set(entry) >= {"role", "frame", "self_samples", "self_pct"}
            for entry in top
        )

    def test_bounded_stacks_overflow_bucket(self):
        prof = SamplingProfiler(ProfilerConfig(hz=100, max_stacks=1))
        stop = threading.Event()
        threads = [
            _busy_thread(f"kvtpu-busy-ov-{i}", stop) for i in range(2)
        ]
        prof.start()
        time.sleep(0.4)
        stop.set()
        prof.close()
        for thread in threads:
            thread.join(timeout=5)
        status = prof.status()
        assert status["overflowed_samples"] > 0
        # One kept stack plus at most one <other> bucket per role —
        # never proportional to the sample stream.
        roles = len(status["roles"])
        assert status["distinct_stacks"] <= 1 + roles
        assert any(
            ";<other> " in line
            for line in prof.collapsed().splitlines()
        )

    def test_reset_clears_aggregation(self):
        prof = SamplingProfiler(ProfilerConfig(hz=200))
        prof.start()
        time.sleep(0.1)
        prof.close()
        assert prof.status()["samples"] > 0
        prof.reset()
        status = prof.status()
        assert status["samples"] == 0
        assert status["roles"] == {}
        assert prof.collapsed() == ""


# ------------------------- lock contention ------------------------------


class TestLockContention:
    def setup_method(self):
        self._prev = lockorder.set_contention_sample(0)
        lockorder.reset_contention_stats()

    def teardown_method(self):
        lockorder.set_contention_sample(self._prev)

    def test_disarmed_returns_raw_lock(self):
        raw = threading.Lock()
        assert lockorder.tracked(raw, "T.off") is raw

    def test_contended_fight_is_counted(self):
        lockorder.set_contention_sample(1)
        lock = lockorder.tracked(threading.Lock(), "T.fight")
        assert type(lock).__name__ == "ContentionTimedLock"
        stop = threading.Event()

        def fight() -> None:
            while not stop.is_set():
                with lock:
                    time.sleep(0.001)

        threads = [
            threading.Thread(target=fight, daemon=True) for _ in range(2)
        ]
        before = counter_total(METRICS.lock_contention)
        for thread in threads:
            thread.start()
        time.sleep(0.5)
        stop.set()
        for thread in threads:
            thread.join(timeout=5)
        stats = lockorder.contention_stats()["T.fight"]
        assert stats["contended"] > 0
        assert stats["sampled"] >= stats["contended"]
        assert stats["wait_ewma_us"] > 0
        assert stats["wait_max_us"] >= stats["wait_ewma_us"] / 2
        assert 0.0 < stats["contention_ratio"] <= 1.0
        assert counter_total(METRICS.lock_contention) > before

    def test_uncontended_lock_records_no_contention(self):
        lockorder.set_contention_sample(1)
        lock = lockorder.tracked(threading.Lock(), "T.calm")
        for _ in range(100):
            with lock:
                pass
        stats = lockorder.contention_stats()["T.calm"]
        assert stats["sampled"] == 100
        assert stats["contended"] == 0
        assert stats["wait_ewma_us"] == 0.0

    def test_sampling_interval_thins_probes(self):
        lockorder.set_contention_sample(10)
        lock = lockorder.tracked(threading.Lock(), "T.sampled")
        for _ in range(100):
            with lock:
                pass
        stats = lockorder.contention_stats()["T.sampled"]
        assert stats["sampled"] == 10

    def test_nonblocking_contended_acquire(self):
        lockorder.set_contention_sample(1)
        lock = lockorder.tracked(threading.Lock(), "T.nonblock")
        lock.acquire()
        try:
            other = threading.Thread(
                target=lambda: lock.acquire(False), daemon=True
            )
            other.start()
            other.join(timeout=5)
        finally:
            lock.release()
        stats = lockorder.contention_stats()["T.nonblock"]
        assert stats["contended"] >= 1

    def test_watchdog_supersedes_timing(self):
        lockorder.set_contention_sample(1)
        prev = lockorder.enable(True)
        try:
            lock = lockorder.tracked(threading.Lock(), "T.debug")
            assert type(lock).__name__ == "TrackedLock"
        finally:
            lockorder.enable(prev)

    def test_condition_passthrough(self):
        lockorder.set_contention_sample(1)
        cond = lockorder.tracked(threading.Condition(), "T.cond")
        with cond:
            cond.notify_all()  # falls through via __getattr__


# ----------------------------- timeline ---------------------------------


class TestGaugeTimeline:
    def test_records_and_windows(self):
        timeline = GaugeTimeline(window_s=5)
        values = {"v": 0.0}
        assert timeline.register("v", lambda: values["v"], "test")
        for i in range(8):
            values["v"] = float(i)
            timeline.sample_once(now=1000.0 + i)
        snap = timeline.snapshot()
        points = snap["series"]["v"]["points"]
        # Ring bound: only the last window_s slots survive.
        assert [value for _, value in points] == [3.0, 4.0, 5.0, 6.0, 7.0]
        assert snap["ticks"] == 8

    def test_broken_source_records_none(self):
        timeline = GaugeTimeline(window_s=5)
        timeline.register("boom", lambda: 1 / 0, "bad")
        timeline.register("ok", lambda: 1.0, "good")
        timeline.sample_once(now=1.0)
        snap = timeline.snapshot()
        assert snap["series"]["boom"]["points"][0][1] is None
        assert snap["series"]["boom"]["errors"] == 1
        assert snap["series"]["ok"]["points"][0][1] == 1.0

    def test_series_filter_and_last(self):
        timeline = GaugeTimeline(window_s=30)
        timeline.register("a", lambda: 1.0)
        timeline.register("b", lambda: 2.0)
        now = time.time()
        for offset in (-20.0, -10.0, 0.0):
            timeline.sample_once(now=now + offset)
        only_a = timeline.snapshot(series="a")
        assert set(only_a["series"]) == {"a"}
        recent = timeline.snapshot(last_s=15.0)
        assert len(recent["series"]["b"]["points"]) == 2

    def test_unknown_series_returns_empty_not_everything(self):
        timeline = GaugeTimeline(window_s=5)
        timeline.register("real", lambda: 1.0)
        timeline.sample_once(now=1.0)
        snap = timeline.snapshot(series="typo")
        assert snap["series"] == {}

    def test_window_zero_never_starts(self):
        timeline = GaugeTimeline(window_s=0)
        assert timeline.start() is False
        assert not timeline.running()
        timeline.close()

    def test_register_is_idempotent_and_bounded(self):
        timeline = GaugeTimeline(window_s=5)
        assert timeline.register("x", lambda: 0.0)
        assert timeline.register("x", lambda: 1.0)  # same name: kept
        timeline.sample_once(now=1.0)
        assert timeline.snapshot()["series"]["x"]["points"][0][1] == 0.0

    def test_default_series_register(self):
        timeline = GaugeTimeline(window_s=5)
        register_default_series(timeline)
        timeline.sample_once(now=1.0)
        snap = timeline.snapshot()
        assert "score_requests_total" in snap["series"]
        assert "process_rss_bytes" in snap["series"]
        rss = snap["series"]["process_rss_bytes"]["points"][0][1]
        assert rss and rss > 0

    def test_live_sampler_thread_name(self):
        timeline = GaugeTimeline(window_s=5)
        timeline.register("t", lambda: 1.0)
        assert timeline.start()
        try:
            names = {thread.name for thread in threading.enumerate()}
            assert "kvtpu-timeline" in names
        finally:
            timeline.close()
        assert not timeline.running()


# ------------------------- debug endpoints ------------------------------


def _get(base: str, path: str, as_text: bool = False):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        if as_text:
            return response.read().decode()
        return json.load(response)


@pytest.fixture()
def service():
    indexer = Indexer(IndexerConfig())
    indexer.run()
    profiler = SamplingProfiler(ProfilerConfig(hz=100))
    profiler.start()
    timeline = GaugeTimeline(window_s=60)
    timeline.register("unit", lambda: 42.0, "constant")
    timeline.sample_once(now=time.time())
    server = serve(
        indexer,
        host="127.0.0.1",
        port=0,
        profiler=profiler,
        timeline=timeline,
    )
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        yield base
    finally:
        server.shutdown()
        profiler.close()
        timeline.close()
        indexer.shutdown()


@pytest.fixture()
def bare_service():
    indexer = Indexer(IndexerConfig())
    indexer.run()
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        yield base
    finally:
        server.shutdown()
        indexer.shutdown()


@pytest.fixture()
def off_service():
    """The shipped main() wiring with the planes OFF: profiler and
    timeline objects are passed but PROFILE_HZ=0 / TIMELINE_WINDOW_S=0
    — the index must read them disabled and the sampler views 404."""
    indexer = Indexer(IndexerConfig())
    indexer.run()
    profiler = SamplingProfiler(ProfilerConfig(hz=0))
    profiler.start()  # no-op by contract
    timeline = GaugeTimeline(window_s=0)
    server = serve(
        indexer,
        host="127.0.0.1",
        port=0,
        profiler=profiler,
        timeline=timeline,
    )
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        yield base
    finally:
        server.shutdown()
        profiler.close()
        timeline.close()
        indexer.shutdown()


class TestDebugEndpoints:
    def test_debug_index_lists_surfaces(self, service):
        payload = _get(service, "/debug/")
        by_path = {s["path"]: s for s in payload["surfaces"]}
        assert by_path["/debug/profile"]["enabled"]
        assert by_path["/debug/timeline"]["enabled"]
        assert by_path["/debug/traces"]["enabled"]
        assert not by_path["/debug/tiering"]["enabled"]
        assert all(s["description"] for s in payload["surfaces"])
        assert "/healthz" in payload["also"]
        # Both spellings resolve.
        assert _get(service, "/debug") == payload

    def test_profile_top(self, service):
        time.sleep(0.3)  # let the sampler accumulate
        payload = _get(service, "/debug/profile")
        assert payload["running"]
        assert payload["samples"] > 0
        assert isinstance(payload["top"], list)

    def test_profile_stacks_collapsed(self, service):
        time.sleep(0.2)
        text = _get(service, "/debug/profile?kind=stacks", as_text=True)
        for line in text.splitlines():
            if line:
                assert line.rsplit(" ", 1)[1].isdigit()

    def test_profile_locks_kind(self, service):
        payload = _get(service, "/debug/profile?kind=locks")
        assert "sample" in payload and "locks" in payload

    def test_profile_bad_kind(self, service):
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(service, "/debug/profile?kind=nope")
        assert err.value.code == 400

    def test_timeline_snapshot_and_filters(self, service):
        payload = _get(service, "/debug/timeline")
        assert payload["series"]["unit"]["points"][0][1] == 42.0
        one = _get(service, "/debug/timeline?series=unit&last=3600")
        assert set(one["series"]) == {"unit"}
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(service, "/debug/timeline?last=abc")
        assert err.value.code == 400

    def test_disabled_surfaces_404(self, bare_service):
        for path in ("/debug/profile", "/debug/timeline"):
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(bare_service, path)
            assert err.value.code == 404, path
        payload = _get(bare_service, "/debug/")
        by_path = {s["path"]: s for s in payload["surfaces"]}
        assert not by_path["/debug/profile"]["enabled"]
        assert not by_path["/debug/timeline"]["enabled"]
        # The contention table is module-global lockorder state: it
        # answers even with no profiler wired at all.
        locks = _get(bare_service, "/debug/profile?kind=locks")
        assert "locks" in locks

    def test_wired_but_off_reads_disabled(self, off_service):
        # PROFILE_HZ=0 / TIMELINE_WINDOW_S=0 with the objects still
        # wired (the shipped main() path): index says disabled, the
        # sampler views 404 — but ?kind=locks still answers, because
        # LOCK_CONTENTION_SAMPLE arms independently of the sampler.
        payload = _get(off_service, "/debug/")
        by_path = {s["path"]: s for s in payload["surfaces"]}
        assert not by_path["/debug/profile"]["enabled"]
        assert not by_path["/debug/timeline"]["enabled"]
        for path in (
            "/debug/profile",
            "/debug/profile?kind=stacks",
            "/debug/timeline",
        ):
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(off_service, path)
            assert err.value.code == 404, path
        locks = _get(off_service, "/debug/profile?kind=locks")
        assert "locks" in locks

    def test_timeline_unknown_series_is_empty(self, service):
        payload = _get(service, "/debug/timeline?series=typo")
        assert payload["series"] == {}
