"""Guarded-by runtime enforcement (utils/raceguard.py) + the
preemption fuzzer (hack/racefuzz.py).

The planted-defect gauntlet: raceguard must flag a planted guarded-by
violation at runtime, the runtime check must catch a caller-locked
claim that kvlint phase 1 trusted statically, and
``python -m hack.racefuzz --seed N`` must deterministically reproduce
a planted check-then-act race.  The inverse contract matters just as
much: with ``KVTPU_RACEGUARD`` unset nothing is instrumented and
attribute access stays native.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from llm_d_kv_cache_manager_tpu.utils import lockorder  # noqa: E402
from llm_d_kv_cache_manager_tpu.utils import raceguard  # noqa: E402


@pytest.fixture
def armed():
    """Recording on for the test, everything restored after."""
    previous = lockorder.set_guard_recording(True)
    try:
        yield
    finally:
        raceguard.uninstall()
        lockorder.set_guard_recording(previous)
        lockorder.set_fuzz_hook(None)


def make_cache():
    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._data = {}  # guarded-by: _lock

        def put(self, key, value):
            with self._lock:
                self._data[key] = value

        def get(self, key):
            with self._lock:
                return self._data.get(key)

        def bad_put(self, key, value):
            self._data[key] = value  # planted: no lock

    return Cache


class TestGuardedAttribute:
    def test_locked_access_passes_and_round_trips(self, armed):
        Cache = raceguard.guard_class(make_cache(), {"_data": "_lock"})
        cache = Cache()
        cache.put("a", 1)
        assert cache.get("a") == 1

    def test_planted_unguarded_write_caught(self, armed):
        Cache = raceguard.guard_class(make_cache(), {"_data": "_lock"})
        cache = Cache()
        with pytest.raises(raceguard.RaceGuardViolation) as excinfo:
            cache.bad_put("a", 1)
        message = str(excinfo.value)
        assert "Cache._data" in message
        assert "_lock" in message

    def test_planted_unguarded_read_caught(self, armed):
        Cache = raceguard.guard_class(make_cache(), {"_data": "_lock"})
        cache = Cache()
        with pytest.raises(raceguard.RaceGuardViolation):
            cache._data

    def test_caller_locked_false_claim_caught(self, armed):
        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def _push_locked(self, item):  # kvlint: caller-locked
                self._items.append(item)

            def honest(self, item):
                with self._lock:
                    self._push_locked(item)

            def lying(self, item):
                # kvlint phase 1 trusts the claim; runtime must not.
                self._push_locked(item)

        raceguard.guard_class(Ledger, {"_items": "_lock"})
        ledger = Ledger()
        ledger.honest(1)
        with pytest.raises(raceguard.RaceGuardViolation):
            ledger.lying(2)

    def test_violation_reports_both_thread_stacks(self, armed):
        Cache = raceguard.guard_class(make_cache(), {"_data": "_lock"})
        cache = Cache()
        holder_in = threading.Event()
        release = threading.Event()

        def hold_forever():
            with cache._lock:
                holder_in.set()
                release.wait(5.0)

        holder = threading.Thread(target=hold_forever, name="holder")
        holder.start()
        try:
            assert holder_in.wait(5.0)
            with pytest.raises(raceguard.RaceGuardViolation) as excinfo:
                cache._data
            message = str(excinfo.value)
            assert "accessing thread" in message
            assert "holder" in message  # the other stack, by name
            assert "hold_forever" in message
        finally:
            release.set()
            holder.join()

    def test_works_with_slots(self, armed):
        class Slotted:
            __slots__ = ("_lock", "_value")

            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0  # guarded-by: _lock

            def bump(self):
                with self._lock:
                    self._value += 1

        raceguard.guard_class(Slotted, {"_value": "_lock"})
        obj = Slotted()
        obj.bump()
        with obj._lock:
            assert obj._value == 1
        with pytest.raises(raceguard.RaceGuardViolation):
            obj._value

    def test_uninstall_restores_raw_access(self, armed):
        Cache = raceguard.guard_class(make_cache(), {"_data": "_lock"})
        assert isinstance(
            Cache.__dict__["_data"], raceguard.GuardedAttribute
        )
        raceguard.uninstall()
        assert "_data" not in Cache.__dict__
        cache = Cache()
        cache._data["a"] = 1  # lockless: fine again
        assert cache._data == {"a": 1}

    def test_composes_with_watchdog_wrapper(self, armed):
        """A TrackedLock (watchdog) feeds the same held-lock registry,
        so raceguard accepts it without double wrapping."""
        class Tracked:
            def __init__(self):
                self._lock = lockorder.TrackedLock(
                    threading.Lock(), "test.Tracked._lock", None
                )
                self._value = 0  # guarded-by: _lock

            def bump(self):
                with self._lock:
                    self._value += 1

        raceguard.guard_class(Tracked, {"_value": "_lock"})
        obj = Tracked()
        assert isinstance(obj._lock, lockorder.TrackedLock)  # untouched
        obj.bump()
        with pytest.raises(raceguard.RaceGuardViolation):
            obj._value


class TestZeroCostUnarmed:
    """KVTPU_RACEGUARD unset: raw attribute access, nothing installed."""

    pytestmark = pytest.mark.skipif(
        raceguard.armed_from_env(),
        reason="suite running with KVTPU_RACEGUARD armed",
    )

    def test_nothing_installed_by_default(self):
        assert not raceguard.installed()

    def test_manifest_class_keeps_raw_attributes(self):
        from llm_d_kv_cache_manager_tpu.utils.ttl_cache import TTLCache

        assert "_entries" not in TTLCache.__dict__
        assert not getattr(
            TTLCache.__init__, "__raceguard_wrapped__", False
        )
        cache = TTLCache(ttl_seconds=5.0)
        # Lockless access must be plain (no descriptor, no raise).
        assert cache._entries == {}
        # And the lock stays a raw primitive — no recording wrapper.
        assert not isinstance(
            cache._lock, lockorder.GuardRecordingLock
        )


class TestManifestInstall:
    def test_install_uninstall_roundtrip(self, armed, tmp_path):
        """Install from a manifest naming a real class, verify the
        descriptor is live, uninstall, verify raw access returns."""
        manifest = {
            "version": 1,
            "classes": {
                "llm_d_kv_cache_manager_tpu.utils.ttl_cache:TTLCache": {
                    "guarded": {"_entries": "_lock"},
                    "locks": ["_lock", "_cb_lock"],
                    "caller_locked": [],
                }
            },
        }
        path = tmp_path / "manifest.json"
        path.write_text(json.dumps(manifest))
        from llm_d_kv_cache_manager_tpu.utils.ttl_cache import TTLCache

        count = raceguard.install(str(path))
        assert count == 1
        assert isinstance(
            TTLCache.__dict__["_entries"], raceguard.GuardedAttribute
        )
        cache = TTLCache(ttl_seconds=5.0)
        assert isinstance(cache._lock, lockorder.GuardRecordingLock)
        cache.set("k", "v")
        assert cache.get("k") == "v"
        with pytest.raises(raceguard.RaceGuardViolation):
            cache._entries
        raceguard.uninstall()
        assert "_entries" not in TTLCache.__dict__
        fresh = TTLCache(ttl_seconds=5.0)
        assert fresh._entries == {}

    def test_checked_in_manifest_loads_and_names_real_classes(self):
        manifest = raceguard.load_manifest()
        assert manifest["version"] == 1
        classes = manifest["classes"]
        assert len(classes) >= 30
        key = "llm_d_kv_cache_manager_tpu.utils.ttl_cache:TTLCache"
        assert key in classes
        assert classes[key]["guarded"] == {"_entries": "_lock"}


def run_racefuzz(*args):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "hack.racefuzz", *args],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )


class TestRaceFuzz:
    def test_pinned_seed_reproduces_check_then_act(self):
        """The acceptance gauntlet's fuzzer leg: a pinned seed must
        deterministically reproduce the planted check-then-act race
        and report both thread stacks."""
        proc = run_racefuzz("--plant", "check-then-act", "--seed", "1337")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "REPRODUCED" in proc.stdout
        assert "lost update" in proc.stdout
        assert proc.stdout.count("thread ") >= 2  # both stacks
        assert "buggy_increment" in proc.stdout

    def test_planted_guarded_write_flagged(self):
        proc = run_racefuzz("--plant", "guarded-write", "--seed", "1")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "REPRODUCED" in proc.stdout
        assert "PlantedGuardedWrite._value" in proc.stdout

    def test_planted_caller_locked_lie_flagged(self):
        proc = run_racefuzz("--plant", "caller-locked", "--seed", "1")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "REPRODUCED" in proc.stdout
        assert "PlantedCallerLocked._items" in proc.stdout
