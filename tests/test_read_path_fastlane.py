"""Read-path fast lane: memoization, early exit, sharding, batching.

The fast lane (docs/performance.md) restructures the scoring read path
— memoized block keys from the prefix store, chunked early-exit
hashing/lookup, lock-striped index shards, batched kvevents applies —
under ONE invariant: scores must be bit-identical to the straight-line
path.  These tests pin that invariant property-style, plus the
correctness of each layer's machinery.
"""

import random

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    encode_chunk_payload,
    encode_hash_payload,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    CostAwareIndexConfig,
    IndexConfig,
    InMemoryIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    LongestPrefixScorer,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (
    LRUStoreConfig,
    LRUTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding

POD_A = PodEntry("pod-a", "hbm")
POD_B = PodEntry("pod-b", "host")
POD_C = PodEntry("pod-c", "hbm")


class WordTokenizer:
    """Deterministic test tokenizer: 't<id>' words -> stable ids with
    exact byte offsets (what the prefix store needs)."""

    def type(self) -> str:
        return "test-word"

    def encode(self, prompt, model_name, add_special_tokens):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]) if word and word[0] == "t" else 0)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def words(tokens):
    return " ".join(f"t{t}" for t in tokens)


# ---------------------------------------------------------------- hashing


class TestChunkPayloadEncoder:
    def test_matches_generic_encoder_randomized(self):
        rng = random.Random(7)
        boundary = [0, 1, 23, 24, 255, 256, 65535, 65536, 2**32 - 1,
                    2**32, 2**64 - 1]
        for trial in range(200):
            parent = rng.choice(boundary + [rng.getrandbits(64)])
            n = rng.randrange(0, 48)
            tokens = [
                rng.choice(boundary + [rng.randrange(0, 200_000)])
                for _ in range(n)
            ]
            fast = bytes(encode_chunk_payload(parent, tokens))
            generic = encode_hash_payload(parent, tokens, None)
            assert fast == generic, (trial, parent, tokens)

    def test_rejects_oversized_ints_like_generic(self):
        with pytest.raises(ValueError):
            encode_chunk_payload(2**64, [1])


class TestExtendBlockKeys:
    @pytest.mark.parametrize("use_native", [False, True])
    @pytest.mark.parametrize("block_size", [2, 4, 16])
    @pytest.mark.parametrize("seed", ["", "fleet-seed"])
    def test_resume_bit_identical_to_fresh(
        self, use_native, block_size, seed
    ):
        """Property: extend_block_keys off any full-block split point
        reproduces the fresh full-chain hash bit for bit."""
        db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=block_size, hash_seed=seed),
            use_native=use_native,
        )
        rng = random.Random(block_size * 1000 + len(seed))
        for model in ("model-a", "model-b"):
            tokens = [rng.randrange(0, 70_000) for _ in range(
                rng.randrange(block_size, 40 * block_size))]
            fresh = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, model
            )
            for _ in range(6):
                cut_blocks = rng.randrange(0, len(fresh) + 1)
                prefix = fresh[:cut_blocks]
                parent = prefix[-1] if prefix else EMPTY_BLOCK_HASH
                resumed = prefix + db.extend_block_keys(
                    parent, tokens[cut_blocks * block_size:], model
                )
                assert resumed == fresh, (model, cut_blocks)

    def test_key_space_distinguishes_configs(self):
        a = ChunkedTokenDatabase(TokenProcessorConfig(block_size=16))
        b = ChunkedTokenDatabase(TokenProcessorConfig(block_size=32))
        c = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=16, hash_seed="x")
        )
        assert a.key_space != b.key_space
        assert a.key_space != c.key_space
        assert a.key_space == ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=16)
        ).key_space


# ------------------------------------------------------ prefix-store memo


class TestPrefixStoreBlockKeyMemo:
    def _store_with(self, tokens, model="m", chunk_bytes=32):
        store = LRUTokenStore(LRUStoreConfig(block_size=chunk_bytes))
        prompt = words(tokens)
        enc = WordTokenizer().encode(prompt, model, True)
        store.add_tokenization(prompt, enc.tokens, enc.offsets, model)
        return store, prompt

    def test_attach_then_probe_returns_keys(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        tokens = list(range(100, 164))
        store, prompt = self._store_with(tokens)
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m")
        written = store.attach_block_keys(
            prompt, "m", db.key_space, keys, tokens
        )
        assert written > 0
        probe = store.probe(prompt, "m", db.key_space)
        assert probe.blocks > 0
        assert probe.blocks <= len(probe.tokens) // 4
        # The memoized keys ARE the chain prefix, bit for bit.
        assert list(probe.keys) == keys[: probe.blocks]

    def test_probe_without_key_space_skips_memo(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        tokens = list(range(64))
        store, prompt = self._store_with(tokens)
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m")
        store.attach_block_keys(prompt, "m", db.key_space, keys, tokens)
        probe = store.probe(prompt, "m")
        assert probe.blocks == 0 and probe.keys == ()
        assert probe.tokens  # token resolution unaffected

    def test_key_spaces_never_alias(self):
        """Keys attached under one (seed, block size) space must not
        serve another: a config change re-hashes, never replays."""
        db16 = ChunkedTokenDatabase(TokenProcessorConfig(block_size=16))
        db4 = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        tokens = list(range(64))
        store, prompt = self._store_with(tokens)
        keys16 = db16.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m")
        store.attach_block_keys(
            prompt, "m", db16.key_space, keys16, tokens
        )
        probe4 = store.probe(prompt, "m", db4.key_space)
        assert probe4.blocks == 0 and probe4.keys == ()
        probe16 = store.probe(prompt, "m", db16.key_space)
        assert list(probe16.keys) == keys16[: probe16.blocks]

    def test_longer_prompt_resumes_from_deepest_record(self):
        """A grown conversation probes back the old prefix's keys: only
        the suffix still needs hashing — the memoization contract."""
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        base = list(range(200, 264))
        store, base_prompt = self._store_with(base)
        base_keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, base, "m")
        store.attach_block_keys(
            base_prompt, "m", db.key_space, base_keys, base
        )

        grown = base + list(range(500, 532))
        grown_prompt = words(grown)
        enc = WordTokenizer().encode(grown_prompt, "m", True)
        # A full re-tokenization installs fresh chunk tuples, so the
        # old anchors no longer validate — memo is (conservatively)
        # rejected until the next attach, which is exactly what the
        # indexer does after re-hashing.
        store.add_tokenization(grown_prompt, enc.tokens, enc.offsets, "m")
        rejected = store.probe(grown_prompt, "m", db.key_space)
        assert rejected.blocks == 0
        grown_keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, rejected.tokens, "m"
        )
        store.attach_block_keys(
            grown_prompt, "m", db.key_space, grown_keys, rejected.tokens
        )

        probe = store.probe(grown_prompt, "m", db.key_space)
        assert probe.blocks > 0
        # Resume off the memo and compare against a fresh full chain.
        full = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, probe.tokens, "m")
        resumed = list(probe.keys) + db.extend_block_keys(
            probe.keys[-1], probe.tokens[probe.blocks * 4:], "m"
        )
        assert resumed == full

    def test_stale_record_rejected_when_token_split_changes(self):
        """A later tokenization of a longer prompt can overwrite a
        shared chunk's token tuple with a DIFFERENT boundary split
        (add_tokenization assigns straddling tokens to the later
        chunk).  A memo record attached under the old split must then
        be rejected — serving its keys against the new token stream
        would silently change scores vs the straight path."""
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        store = LRUTokenStore(LRUStoreConfig(block_size=8))
        prompt = "abcdefgh" * 4  # 4 chunks of 8 bytes

        # Tokenization 1: two 4-byte tokens per chunk.
        tokens_a = list(range(100, 108))
        offsets_a = [(i * 4, (i + 1) * 4) for i in range(8)]
        store.add_tokenization(prompt, tokens_a, offsets_a, "m")
        keys_a = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens_a, "m")
        assert store.attach_block_keys(
            prompt, "m", db.key_space, keys_a, tokens_a
        )
        probe = store.probe(prompt, "m", db.key_space)
        assert probe.blocks > 0  # record served while split matches

        # Tokenization 2: same bytes, different split (8 one-byte
        # tokens then 4-byte tokens) — overwrites the shared chunks.
        tokens_b = list(range(500, 508)) + list(range(600, 606))
        offsets_b = [(i, i + 1) for i in range(8)] + [
            (8 + i * 4, 8 + (i + 1) * 4) for i in range(6)
        ]
        store.add_tokenization(prompt, tokens_b, offsets_b, "m")

        probe2 = store.probe(prompt, "m", db.key_space)
        # The stale record must NOT pair keys_a with tokens_b.
        assert probe2.blocks == 0 and probe2.keys == ()
        assert probe2.tokens[: len(tokens_b)] == tokens_b[
            : len(probe2.tokens)
        ]


# ----------------------------------------------------- incremental scorer


class TestIncrementalScorer:
    WEIGHTS = {"hbm": 1.0, "host": 0.8, "shared_storage": 0.5}

    def _random_case(self, rng):
        n_keys = rng.randrange(0, 24)
        keys = list(range(1, n_keys + 1))
        pods = ["pod-a", "pod-b", "pod-c"]
        tiers = list(self.WEIGHTS) + ["unknown-tier"]
        key_to_pods = {}
        for key in keys:
            if rng.random() < 0.15:
                continue  # missing key
            entries = [
                PodEntry(rng.choice(pods), rng.choice(tiers))
                for _ in range(rng.randrange(0, 4))
            ]
            key_to_pods[key] = entries
        return keys, key_to_pods

    def test_chunked_advance_equals_score(self):
        scorer = LongestPrefixScorer(self.WEIGHTS)
        rng = random.Random(11)
        for trial in range(300):
            keys, key_to_pods = self._random_case(rng)
            expected = scorer.score(keys, key_to_pods)
            chain = scorer.begin()
            position = 0
            while position < len(keys):
                step = rng.randrange(1, 6)
                chunk = keys[position:position + step]
                pods_per_key = [key_to_pods.get(k, ()) for k in chunk]
                if not scorer.advance(chain, pods_per_key):
                    break
                position += step
            assert chain.scores == expected, trial

    def test_advance_with_filter_equals_filtered_score(self):
        """Filtering inside advance ≡ filtering before score (what the
        legacy lookup did)."""
        scorer = LongestPrefixScorer(self.WEIGHTS)
        rng = random.Random(13)
        for trial in range(200):
            keys, key_to_pods = self._random_case(rng)
            pod_set = set(rng.sample(["pod-a", "pod-b", "pod-c"],
                                     rng.randrange(0, 4)))
            filtered = {
                k: [e for e in v if e.pod_identifier in pod_set]
                for k, v in key_to_pods.items()
            }
            filtered = {k: v for k, v in filtered.items() if v}
            expected = scorer.score(keys, filtered)
            chain = scorer.begin()
            scorer.advance(
                chain,
                [key_to_pods.get(k, ()) for k in keys],
                pod_set or None,
            )
            if pod_set:
                assert chain.scores == expected, trial

    def test_advance_reports_dead_chain(self):
        scorer = LongestPrefixScorer(self.WEIGHTS)
        chain = scorer.begin()
        assert scorer.advance(chain, [[POD_A], [POD_A]])
        assert chain.alive
        assert not scorer.advance(chain, [[POD_B]])  # disjoint pod
        assert not chain.alive
        # Feeding more after death stays dead and changes nothing.
        scores_before = dict(chain.scores)
        assert not scorer.advance(chain, [[POD_A]])
        assert chain.scores == scores_before

    def test_resolve_cache_invalidates_on_new_snapshot(self):
        """The identity-keyed weight cache must never serve a mutated
        pod set: a new snapshot tuple resolves fresh."""
        scorer = LongestPrefixScorer(self.WEIGHTS)
        index = InMemoryIndex(InMemoryIndexConfig(size=64))
        index.add([1], [1], [POD_A])
        first = index.lookup_chain([1])
        chain = scorer.begin()
        scorer.advance(chain, first)
        assert chain.scores == {"pod-a": 1.0}
        index.add([1], [1], [POD_B])  # mutates -> new snapshot
        second = index.lookup_chain([1])
        chain2 = scorer.begin()
        scorer.advance(chain2, second)
        assert chain2.scores == {"pod-a": 1.0, "pod-b": 0.8}


# ----------------------------------------------------- sharded index


class TestShardedIndex:
    def test_lookup_chain_stops_at_missing_key(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=1000))
        index.add([1, 2], [1, 2], [POD_A])
        index.add([9], [9], [POD_A])
        chain = index.lookup_chain([1, 2, 5, 9])
        assert len(chain) == 2
        assert [set(c) for c in chain] == [{POD_A}, {POD_A}]

    def test_lookup_chain_stops_at_empty_pod_cache(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=1000))
        index.add([1, 2, 3], [1, 2, 3], [POD_A])
        index._shard(2).get(2).remove_all([POD_A])
        assert len(index.lookup_chain([1, 2, 3])) == 1

    def test_lookup_chain_default_adapter_on_cost_aware(self):
        """Backends without an override answer lookup_chain through
        the dict-based default — same truncation semantics."""
        index = CostAwareMemoryIndex(CostAwareIndexConfig())
        index.add([1, 2], [1, 2], [POD_A])
        index.add([9], [9], [POD_B])
        chain = index.lookup_chain([1, 2, 5, 9])
        assert len(chain) == 2

    @pytest.mark.parametrize("src_shards,dst_shards", [(1, 8), (8, 1),
                                                       (4, 8)])
    def test_cross_shard_dump_restore(self, src_shards, dst_shards):
        """A dump from one shard layout restores into any other: keys
        re-shard by value, lookups agree."""
        source = InMemoryIndex(
            InMemoryIndexConfig(size=10_000, shards=src_shards)
        )
        rng = random.Random(5)
        keys = [rng.getrandbits(64) for _ in range(200)]
        for i, key in enumerate(keys):
            source.add(
                [key ^ 0xABCD], [key],
                [POD_A if i % 2 else POD_B, POD_C][: 1 + i % 2],
            )
        block_entries, engine_map = source.dump_entries()
        assert len(block_entries) == len(keys)

        restored = InMemoryIndex(
            InMemoryIndexConfig(size=10_000, shards=dst_shards)
        )
        count = restored.restore_entries(block_entries, engine_map)
        assert count == len(keys)
        for key in keys:
            assert restored.lookup([key]) == source.lookup([key])
            assert restored.get_request_key(key ^ 0xABCD) == key

    def test_cross_shard_purge_pod(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=10_000, shards=8))
        rng = random.Random(6)
        keys = [rng.getrandbits(64) for _ in range(300)]
        solo, shared = [], []
        for key in keys:
            if key % 3 == 0:
                index.add([key], [key], [POD_A])
                solo.append(key)
            else:
                index.add([key], [key], [POD_A, POD_B])
                shared.append(key)
        removed = index.purge_pod("pod-a")
        assert removed == len(keys)
        # Keys held only by the purged pod vanish entirely (an empty
        # pod set would break other pods' chains at lookup)...
        for key in solo:
            assert index.lookup([key]) == {}
        # ...while co-held keys keep the surviving pod.
        for key in shared:
            assert index.lookup([key]) == {key: [POD_B]}

    def test_shard_count_rounds_to_power_of_two(self):
        assert len(InMemoryIndex(
            InMemoryIndexConfig(shards=3))._shards) == 4
        assert len(InMemoryIndex(
            InMemoryIndexConfig(shards=8))._shards) == 8
        assert len(InMemoryIndex(
            InMemoryIndexConfig(shards=0))._shards) == 1

    def test_filtered_lookup_skips_copy_only_when_covered(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=100))
        index.add([1], [1], [POD_A, POD_B])
        # Filter covers everything -> both entries back.
        assert set(index.lookup([1], {"pod-a", "pod-b"})[1]) == {
            POD_A, POD_B,
        }
        # Filter drops one -> filtered copy.
        assert index.lookup([1], {"pod-a"}) == {1: [POD_A]}
        # Filter drops all -> key absent (not an empty list).
        assert index.lookup([1], {"pod-z"}) == {}


# ----------------------------------------------------- batched kvevents


def _stored_message(pod, seq, engine_base, tokens, block_size=4,
                    parent=None, model="m"):
    event = BlockStored(
        block_hashes=[engine_base + i for i in range(
            len(tokens) // block_size)],
        parent_block_hash=parent,
        token_ids=tokens,
        block_size=block_size,
        medium="hbm",
    )
    batch = EventBatch(ts=1.0, events=[event])
    return Message(
        topic=f"kv@{pod}@{model}",
        payload=batch.encode(),
        pod_identifier=pod,
        model_name=model,
        seq=seq,
    )


class TestBatchedEventApply:
    @pytest.mark.parametrize("backend", ["in_memory", "cost_aware"])
    def test_batched_apply_equals_sequential(self, backend):
        """Flooding the pool before start forces multi-message batches;
        the applied state must equal a one-message-at-a-time pool's."""
        def build(batch_size):
            db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
            if backend == "in_memory":
                index = InMemoryIndex(InMemoryIndexConfig(size=100_000))
            else:
                index = CostAwareMemoryIndex(CostAwareIndexConfig())
            pool = Pool(index, db, PoolConfig(
                concurrency=2, apply_batch_size=batch_size))
            return index, pool

        results = []
        for batch_size in (1, 16):
            index, pool = build(batch_size)
            rng = random.Random(3)
            for pod_i in range(4):
                pod = f"pod-{pod_i}"
                for seq in range(12):
                    tokens = [rng.randrange(0, 5000) for _ in range(16)]
                    pool.add_task(_stored_message(
                        pod, seq, (pod_i + 1) * 10_000 + seq * 100, tokens))
            pool.start()
            pool.drain()
            pool.shutdown()
            results.append(index)

        sequential, batched = results
        s_entries, s_map = sequential.dump_entries()
        b_entries, b_map = batched.dump_entries()
        assert dict(s_map) == dict(b_map)
        assert {k: set(v) for k, v in s_entries} == {
            k: set(v) for k, v in b_entries
        }

    def test_add_then_evict_in_one_batch_stays_evicted(self):
        """The eviction barrier: an add and its evict drained in the
        same batch must apply in order."""
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        index = InMemoryIndex(InMemoryIndexConfig(size=1000))
        pool = Pool(index, db, PoolConfig(
            concurrency=1, apply_batch_size=64))
        tokens = list(range(8))
        pool.add_task(_stored_message("pod-x", 0, 500, tokens))
        removed = BlockRemoved(block_hashes=[500, 501], medium="hbm")
        pool.add_task(Message(
            topic="kv@pod-x@m",
            payload=EventBatch(ts=2.0, events=[removed]).encode(),
            pod_identifier="pod-x",
            model_name="m",
            seq=1,
        ))
        pool.start()
        pool.drain()
        pool.shutdown()
        request_keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, "m")
        for key in request_keys:
            assert index.lookup([key]) == {}

    def test_parent_chain_resolves_within_one_batch(self):
        """Eager engine-map publication: a child event whose parent
        arrived in the SAME drained batch still chains correctly."""
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        index = InMemoryIndex(InMemoryIndexConfig(size=1000))
        pool = Pool(index, db, PoolConfig(
            concurrency=1, apply_batch_size=64))
        pool.add_task(_stored_message("pod-y", 0, 700, list(range(4))))
        pool.add_task(_stored_message(
            "pod-y", 1, 701, list(range(4, 8)), parent=700))
        pool.start()
        pool.drain()
        pool.shutdown()
        full = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH,
                                          list(range(8)), "m")
        assert index.get_request_key(701) == full[1]
        assert index.lookup([full[1]]) != {}

    def test_flush_failure_never_journals_orphaned_adds(self):
        """A failed add flush must drop the deferred journal records
        with it: a later flush journaling admissions the index never
        held would corrupt warm restarts."""
        from llm_d_kv_cache_manager_tpu.kvevents.pool import _BatchApplier

        class ExplodingIndex(InMemoryIndex):
            def __init__(self):
                super().__init__(InMemoryIndexConfig(size=100))
                self.explode = True

            def add_entries_batch(self, items):
                if self.explode:
                    raise RuntimeError("backend down")
                super().add_entries_batch(items)

        class RecordingJournal:
            def __init__(self):
                self.adds = []

            def record_add(self, *args):
                self.adds.append(args)

        journal = RecordingJournal()
        index = ExplodingIndex()
        applier = _BatchApplier(index, journal)
        applier.add("pod-a", 0, [1], [1], [POD_A])
        with pytest.raises(RuntimeError):
            applier.flush()
        # The failed batch's records died with it; a later successful
        # flush journals only ITS adds.
        index.explode = False
        applier.add("pod-a", 1, [2], [2], [POD_A])
        applier.flush()
        assert [args[1] for args in journal.adds] == [1]  # seq 1 only
        assert index.lookup([2]) == {2: [POD_A]}

    def test_barrier_flush_failure_errors_earlier_message_traces(self):
        """A mid-batch eviction-barrier flush failure discards EARLIER
        messages' deferred admissions; their traces must finish errored
        — an "ok" trace for admissions that never landed would hide the
        loss from the flight recorder."""
        from llm_d_kv_cache_manager_tpu.obs.trace import (
            Tracer,
            TracerConfig,
        )

        class ExplodingIndex(InMemoryIndex):
            def __init__(self):
                super().__init__(InMemoryIndexConfig(size=1000))
                self.explode = True

            def add_entries_batch(self, items):
                if self.explode:
                    self.explode = False
                    raise RuntimeError("backend down")
                super().add_entries_batch(items)

        tracer = Tracer(TracerConfig(sample_rate=1.0))
        stored_trace = tracer.start_trace("kvevents.message", force=True)
        removed_trace = tracer.start_trace("kvevents.message", force=True)
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        index = ExplodingIndex()
        pool = Pool(index, db, PoolConfig(
            concurrency=1, apply_batch_size=64))
        stored = _stored_message("pod-x", 0, 500, list(range(4)))
        stored.trace = stored_trace
        removed = BlockRemoved(block_hashes=[500], medium="hbm")
        pool.add_task(stored)
        pool.add_task(Message(
            topic="kv@pod-x@m",
            payload=EventBatch(ts=2.0, events=[removed]).encode(),
            pod_identifier="pod-x",
            model_name="m",
            seq=1,
            trace=removed_trace,
        ))
        pool.start()
        pool.drain()
        # The stored message's add was discarded by the failed barrier
        # flush: its trace is errored, NOT ok, and the worker survived
        # (drain returned).
        assert stored_trace.status == "error"
        assert removed_trace.status == "error"
        later = _stored_message("pod-x", 2, 600, list(range(4, 8)))
        pool.add_task(later)
        pool.drain()
        pool.shutdown()
        keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, list(range(4, 8)), "m")
        assert index.lookup([keys[0]]) != {}

    def test_worker_survives_exception_outside_message_guards(self):
        """An exception escaping the per-message guards (here: the
        batch-size histogram observe) must not kill the shard worker —
        a dead worker silently sheds every later event for its pods."""
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        index = InMemoryIndex(InMemoryIndexConfig(size=1000))
        pool = Pool(index, db, PoolConfig(concurrency=1))
        original = METRICS.kvevents_batch_size.observe
        calls = {"n": 0}

        def observe_once_broken(value):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("metrics backend down")
            original(value)

        METRICS.kvevents_batch_size.observe = observe_once_broken
        try:
            pool.add_task(_stored_message("pod-w", 0, 800, list(range(4))))
            pool.start()
            pool.drain()  # first batch dropped, worker alive
            pool.add_task(_stored_message("pod-w", 1, 810, list(range(4))))
            pool.drain()
            pool.shutdown()
        finally:
            METRICS.kvevents_batch_size.observe = original
        keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, list(range(4)), "m")
        assert index.lookup([keys[0]]) != {}

    def test_batch_size_histogram_observed(self):
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        def histogram_count():
            total = 0.0
            for metric in METRICS.kvevents_batch_size.collect():
                for sample in metric.samples:
                    if sample.name.endswith("_count"):
                        total += sample.value
            return total

        before = histogram_count()
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        index = InMemoryIndex(InMemoryIndexConfig(size=1000))
        pool = Pool(index, db, PoolConfig(concurrency=1))
        pool.add_task(_stored_message("pod-z", 0, 900, list(range(4))))
        pool.start()
        pool.drain()
        pool.shutdown()
        assert histogram_count() > before


# ----------------------------------------------------- end-to-end parity


def make_indexer(fast, block_size=16, shards=8):
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=block_size
            ),
            kvblock_index_config=IndexConfig(
                in_memory_config=InMemoryIndexConfig(
                    size=200_000, shards=shards
                )
            ),
            read_path_fast_lane=fast,
            lookup_chunk_size=8,
        ),
        tokenizer=WordTokenizer(),
    )
    indexer.run()
    return indexer


class TestFastLaneParity:
    """Acceptance: get_pod_scores with the fast lane ≡ with it
    disabled, across multi-turn growth, tier mixes, pod filters, and
    broken chains."""

    def test_multi_turn_and_randomized_parity(self):
        fast = make_indexer(True)
        straight = make_indexer(False)
        pods = [f"pod-{i}" for i in range(4)]
        try:
            rng = random.Random(42)
            base = [rng.randrange(1, 60_000) for _ in range(800)]
            convo = list(base)
            for _ in range(5):  # seed both indexes, then grow
                for ix in (fast, straight):
                    keys = ix.token_processor.tokens_to_kv_block_keys(
                        EMPTY_BLOCK_HASH, convo, "m"
                    )
                    ix.kv_block_index.add(
                        keys, keys, [PodEntry("pod-0", "hbm")]
                    )
                    ix.kv_block_index.add(
                        keys[: len(keys) // 2], keys[: len(keys) // 2],
                        [PodEntry("pod-1", "host")],
                    )
                prompt = words(convo)
                for flt in (None, pods, pods[:2], ["pod-404"]):
                    # First pass: both cold (full tokenizer run).
                    a = fast.get_pod_scores(prompt, "m", flt)
                    b = straight.get_pod_scores(prompt, "m", flt)
                    assert a == b, (len(convo), flt, a, b)
                    # Warm pass: both sides now serve tokens from the
                    # prefix store (which covers only full text chunks
                    # — a pre-existing fast-path property, identical
                    # for both lanes) and the fast side adds memoized
                    # keys.  Warm-vs-warm must still agree exactly.
                    a2 = fast.get_pod_scores(prompt, "m", flt)
                    b2 = straight.get_pod_scores(prompt, "m", flt)
                    assert a2 == b2, (len(convo), flt, a2, b2)
                convo.extend(
                    rng.randrange(1, 60_000) for _ in range(48)
                )

            # Randomized partial/broken chains.
            for trial in range(25):
                t2 = [rng.randrange(1, 60_000)
                      for _ in range(rng.randrange(0, 400))]
                prompt = words(t2) if t2 else "t1"
                cut = rng.random()
                tier = rng.choice(["hbm", "host", "cpu", "weird"])
                pod = rng.choice(pods)
                for ix in (fast, straight):
                    keys = ix.token_processor.tokens_to_kv_block_keys(
                        EMPTY_BLOCK_HASH, t2, "m"
                    )
                    if keys:
                        c = max(1, int(cut * len(keys)))
                        ix.kv_block_index.add(
                            keys[:c], keys[:c], [PodEntry(pod, tier)]
                        )
                flt = rng.choice([None, pods, pods[:2]])
                a = fast.get_pod_scores(prompt, "m", flt)
                b = straight.get_pod_scores(prompt, "m", flt)
                assert a == b, (trial, a, b)
        finally:
            fast.shutdown()
            straight.shutdown()

    def test_empty_prompt_and_subblock_prompt(self):
        fast = make_indexer(True)
        try:
            assert fast.get_pod_scores("t1 t2", "m") == {}  # < one block
        finally:
            fast.shutdown()

    def test_env_knob_disables_fast_lane(self, monkeypatch):
        monkeypatch.setenv("READ_PATH_FAST_LANE", "0")
        indexer = Indexer(IndexerConfig(), tokenizer=WordTokenizer())
        assert indexer._fast_lane is False
        monkeypatch.setenv("READ_PATH_FAST_LANE", "1")
        indexer = Indexer(IndexerConfig(), tokenizer=WordTokenizer())
        assert indexer._fast_lane is True
        monkeypatch.delenv("READ_PATH_FAST_LANE")
        indexer = Indexer(IndexerConfig(), tokenizer=WordTokenizer())
        assert indexer._fast_lane is True
        # Explicit config wins over env.
        monkeypatch.setenv("READ_PATH_FAST_LANE", "1")
        indexer = Indexer(
            IndexerConfig(read_path_fast_lane=False),
            tokenizer=WordTokenizer(),
        )
        assert indexer._fast_lane is False

    def test_protocol_only_processor_falls_back_to_straight_path(self):
        """A custom TokenProcessor implementing only the Protocol
        (tokens_to_kv_block_keys) must still work: the fast lane needs
        block_size/extend_block_keys, so the Indexer silently takes
        the straight path instead of crashing."""

        class MinimalProcessor:
            def __init__(self):
                self._db = ChunkedTokenDatabase(
                    TokenProcessorConfig(block_size=16)
                )

            def tokens_to_kv_block_keys(self, parent, tokens, model):
                return self._db.tokens_to_kv_block_keys(
                    parent, tokens, model
                )

        indexer = Indexer(
            IndexerConfig(read_path_fast_lane=True),
            token_processor=MinimalProcessor(),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        try:
            assert indexer._fast_lane is False
            tokens = list(range(100, 164))
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, "m"
            )
            indexer.kv_block_index.add(keys, keys, [POD_A])
            scores = indexer.get_pod_scores(words(tokens), "m")
            assert scores == {"pod-a": float(len(keys))}
        finally:
            indexer.shutdown()

    def test_explain_matches_fast_lane_scores(self):
        """The explain surface (straight path) must report the same
        scores the fast lane routes on."""
        fast = make_indexer(True)
        try:
            rng = random.Random(9)
            tokens = [rng.randrange(1, 60_000) for _ in range(320)]
            keys = fast.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, "m"
            )
            fast.kv_block_index.add(keys, keys, [PodEntry("pod-0", "hbm")])
            prompt = words(tokens)
            fast.get_pod_scores(prompt, "m")  # warm the prefix store
            # Warm on both surfaces: the same token stream feeds the
            # fast lane and the explain (straight) path.
            scores = fast.get_pod_scores(prompt, "m")
            explained, _ = fast.get_pod_scores_explained(prompt, "m")
            assert scores == explained
        finally:
            fast.shutdown()


# ----------------------------------------------------- request score memo


class TestScoreMemo:
    """The request score memo: an exact-prompt repeat serves memoized
    scores when the index's per-shard version vector (and the served
    token count) is unchanged — and ONLY then, so scores stay
    bit-identical to a fresh walk through every mutation."""

    def test_memo_serves_without_walking_and_invalidates_on_mutation(
        self,
    ):
        indexer = make_indexer(True)
        straight = make_indexer(False)
        try:
            assert indexer._score_memo is not None
            rng = random.Random(11)
            tokens = [rng.randrange(1, 60_000) for _ in range(320)]
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, "m"
            )
            for ix in (indexer, straight):
                ix.kv_block_index.add(keys, keys, [POD_A])
                ix.kv_block_index.add(keys[:10], keys[:10], [POD_B])
            prompt = words(tokens)
            # Cold vs cold, then warm vs warm: the prefix store serves
            # full text chunks only, so a warm pass may score slightly
            # fewer blocks than the cold one — identically on BOTH
            # lanes (pre-existing fast-path property).
            first = indexer.get_pod_scores(prompt, "m")  # cold fill
            assert first == straight.get_pod_scores(prompt, "m")
            warm = indexer.get_pod_scores(prompt, "m")  # warm re-fill
            assert warm == straight.get_pod_scores(prompt, "m")

            # Prove the next repeat is a memo hit: a walk would have to
            # call lookup_chain, so booby-trap it.
            inner = indexer.kv_block_index

            def bomb(chain):  # pragma: no cover - must not run
                raise AssertionError("memo miss: lookup_chain called")

            original = inner.lookup_chain
            inner.lookup_chain = bomb
            try:
                hit = indexer.get_pod_scores(prompt, "m")
            finally:
                inner.lookup_chain = original
            assert hit == warm
            # The served dict is the caller's to mutate.
            hit["pod-a"] = -1.0
            assert indexer.get_pod_scores(prompt, "m") == warm

            # Every mutation class invalidates: add, evict, purge,
            # restore.  After each, fast scores == a straight indexer
            # driven through the same mutations.
            def both(op):
                for ix in (indexer, straight):
                    op(ix.kv_block_index)

            both(lambda ix: ix.add(keys[:4], keys[:4], [POD_C]))
            a = indexer.get_pod_scores(prompt, "m")
            assert a == straight.get_pod_scores(prompt, "m")
            assert a != warm

            both(lambda ix: ix.evict(keys[0], [POD_C]))
            assert indexer.get_pod_scores(
                prompt, "m"
            ) == straight.get_pod_scores(prompt, "m")

            both(lambda ix: ix.purge_pod("pod-b"))
            b = indexer.get_pod_scores(prompt, "m")
            assert b == straight.get_pod_scores(prompt, "m")

            dump = indexer.kv_block_index.dump_entries()
            both(lambda ix: ix.restore_entries(*dump))
            assert indexer.get_pod_scores(
                prompt, "m"
            ) == straight.get_pod_scores(prompt, "m")
        finally:
            indexer.shutdown()
            straight.shutdown()

    def test_memo_respects_pod_filter_keying(self):
        indexer = make_indexer(True)
        straight = make_indexer(False)
        try:
            tokens = list(range(1, 161))
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, "m"
            )
            for ix in (indexer, straight):
                ix.kv_block_index.add(keys, keys, [POD_A])
                ix.kv_block_index.add(keys[:3], keys[:3], [POD_B])
            prompt = words(tokens)
            for flt in (None, ["pod-a"], ["pod-b"], ["pod-a", "pod-b"]):
                for _ in range(3):  # cold, warm fill, memo hit
                    assert indexer.get_pod_scores(
                        prompt, "m", flt
                    ) == straight.get_pod_scores(prompt, "m", flt), flt
        finally:
            indexer.shutdown()
            straight.shutdown()

    def test_memo_hit_refreshes_chain_recency(self):
        """A memo hit must leave the same LRU recency the elided walk
        would have: chain keys it serves stay MRU, so index capacity
        pressure evicts colder keys first."""
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=16),
                kvblock_index_config=IndexConfig(
                    in_memory_config=InMemoryIndexConfig(
                        size=10, shards=1
                    )
                ),
                read_path_fast_lane=True,
            ),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        try:
            index = indexer.kv_block_index
            tokens = list(range(1, 65))  # 4 blocks
            chain = indexer.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, "m"
            )
            index.add(chain, chain, [POD_A])
            fillers = [10_000 + i for i in range(6)]
            for key in fillers:
                index.add([key], [key], [POD_B])
            prompt = words(tokens)
            expected = {"pod-a": float(len(chain))}
            assert indexer.get_pod_scores(prompt, "m") == expected
            assert indexer.get_pod_scores(prompt, "m") == expected  # fill

            # Make the chain the LRU victim-to-be WITHOUT mutating the
            # index (recency is not score-relevant, so no version bump),
            # then serve from the memo — the hit must re-touch the chain.
            index.touch_chain(fillers)
            assert indexer.get_pod_scores(prompt, "m") == expected  # hit

            # Capacity pressure: three new keys evict three fillers,
            # never the just-served chain.
            for key in (20_001, 20_002, 20_003):
                index.add([key], [key], [POD_C])
            assert indexer.get_pod_scores(prompt, "m") == expected
        finally:
            indexer.shutdown()

    def test_env_knob_and_config_disable_memo(self, monkeypatch):
        monkeypatch.setenv("READ_PATH_SCORE_MEMO", "0")
        indexer = Indexer(IndexerConfig(), tokenizer=WordTokenizer())
        assert indexer._score_memo is None
        monkeypatch.setenv("READ_PATH_SCORE_MEMO", "64")
        indexer = Indexer(IndexerConfig(), tokenizer=WordTokenizer())
        assert indexer._score_memo is not None
        assert indexer._score_memo.capacity == 64
        monkeypatch.delenv("READ_PATH_SCORE_MEMO")
        indexer = Indexer(
            IndexerConfig(score_memo_size=0), tokenizer=WordTokenizer()
        )
        assert indexer._score_memo is None
        # The straight path never builds one.
        indexer = Indexer(
            IndexerConfig(read_path_fast_lane=False),
            tokenizer=WordTokenizer(),
        )
        assert indexer._score_memo is None

    def test_memo_requires_version_vector_surface(self):
        """Backends without the optimistic-validation surface
        (version_vector/touch_chain) silently run without the memo."""
        indexer = Indexer(
            IndexerConfig(
                kvblock_index_config=IndexConfig(
                    in_memory_config=None,
                    cost_aware_config=CostAwareIndexConfig(
                        max_cost_bytes=10_000_000
                    ),
                ),
                read_path_fast_lane=True,
            ),
            tokenizer=WordTokenizer(),
        )
        assert indexer._score_memo is None
        # The instrumented wrapper passes the surface through.
        instrumented = Indexer(
            IndexerConfig(
                kvblock_index_config=IndexConfig(enable_metrics=True),
                read_path_fast_lane=True,
            ),
            tokenizer=WordTokenizer(),
        )
        assert instrumented._score_memo is not None
        instrumented.run()
        try:
            tokens = list(range(1, 33))
            keys = instrumented.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, "m"
            )
            instrumented.kv_block_index.add(keys, keys, [POD_A])
            prompt = words(tokens)
            expected = {"pod-a": float(len(keys))}
            for _ in range(3):
                assert instrumented.get_pod_scores(prompt, "m") == expected
        finally:
            instrumented.shutdown()

    def test_memo_invalidates_on_count_preserving_token_resplit(self):
        """A prefix-store chunk overwritten with a different token
        split of the SAME text (an overlapping prompt's
        add_tokenization; BPE boundaries depend on following context)
        can change the served token VALUES while preserving their
        count.  The memo must invalidate on token content, not count —
        serving the stale scores would break fast≡straight parity with
        the index unmutated."""
        fast = make_indexer(True)
        straight = make_indexer(False)
        try:
            assert fast._score_memo is not None
            tokens_a = list(range(1000, 1320))
            prompt = words(tokens_a)
            keys_a = fast.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens_a, "m"
            )
            for ix in (fast, straight):
                ix.kv_block_index.add(keys_a, keys_a, [POD_A])
            assert fast.get_pod_scores(prompt, "m") == {
                "pod-a": float(len(keys_a))
            }  # cold walk; warms the prefix store
            # Warm repeats serve the store's (possibly truncated)
            # stream; the second equals the first warm call via the
            # memo.
            warm = fast.get_pod_scores(prompt, "m")
            assert warm["pod-a"] > 0
            assert fast.get_pod_scores(prompt, "m") == warm  # memo hit

            # Same text, same token COUNT, different token values.
            words_list = prompt.split(" ")
            offsets, pos = [], 0
            for word in words_list:
                offsets.append((pos, pos + len(word)))
                pos += len(word) + 1
            tokens_b = [t + 500_000 for t in tokens_a]
            for ix in (fast, straight):
                ix.prefix_store.add_tokenization(
                    prompt, tokens_b, offsets, "m"
                )
                served = ix.tokenization_pool.tokenize(prompt, "m")
                assert served == tokens_b[: len(served)]  # B, same count
                assert served

            # Index untouched (version vector unchanged): only the
            # token check can reject the memo entry.
            a = fast.get_pod_scores(prompt, "m")
            b = straight.get_pod_scores(prompt, "m")
            assert a == b
            assert a != warm  # stale memo scores would be `warm`
        finally:
            fast.shutdown()
            straight.shutdown()


class TestVersionVector:
    """Per-shard mutation counters: score-relevant mutations bump, pure
    reads and recency touches do not."""

    @pytest.mark.parametrize("shards", [1, 8])
    def test_mutations_bump_reads_do_not(self, shards):
        index = InMemoryIndex(
            InMemoryIndexConfig(size=1000, shards=shards)
        )
        v0 = index.version_vector()
        assert v0 == tuple([0] * len(index._shards))

        index.add([1, 2, 3], [1, 2, 3], [POD_A])
        v1 = index.version_vector()
        assert v1 != v0

        index.lookup([1, 2, 3], None)
        index.lookup_chain((1, 2, 3))
        index.touch_chain([1, 2, 3])
        index.dump_entries()
        assert index.version_vector() == v1

        index.evict(1, [POD_A])
        v2 = index.version_vector()
        assert v2 != v1

        index.add_mappings([9], [9])  # engine map only: not score-relevant
        assert index.version_vector() == v2

        index.add_entries_batch([((9,), [POD_B])])
        v3 = index.version_vector()
        assert v3 != v2

        index.purge_pod("pod-b")
        v4 = index.version_vector()
        assert v4 != v3

        dump = index.dump_entries()
        index.restore_entries(*dump)
        assert index.version_vector() != v4
