"""RESP client URL parsing, AUTH/TLS handshakes, and eviction atomicity.

Covers the reference's credentialed/TLS URL acceptance (redis.go:61-119)
and the atomic Lua prune (redis.go:147-154) — including a controlled
interleave proving that an add racing into the HDEL->prune window is
never lost (the failure mode of a non-atomic HLEN->DEL sequence).
"""

import ssl
import subprocess
import threading

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    PodEntry,
    RedisIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
    _ENGINE_PREFIX,
    _PRUNE_SCRIPT,
    RedisIndex,
    RespClient,
    RespError,
    parse_redis_url,
)
from tests.helpers.miniresp import MiniRespServer

POD1 = PodEntry("pod-1", "hbm")
POD2 = PodEntry("pod-2", "hbm")


class TestParseRedisURL:
    def test_bare_host_port(self):
        ep = parse_redis_url("example.com:7000")
        assert (ep.host, ep.port, ep.tls) == ("example.com", 7000, False)
        assert ep.password is None and ep.db == 0

    def test_defaults(self):
        ep = parse_redis_url("redis://")
        assert (ep.host, ep.port) == ("127.0.0.1", 6379)

    def test_valkey_rewrites(self):
        assert not parse_redis_url("valkey://h:1").tls
        assert parse_redis_url("valkeys://h:1").tls

    def test_credentials_and_db(self):
        ep = parse_redis_url("redis://user:s%40cret@h:6380/3")
        assert ep.username == "user"
        assert ep.password == "s@cret"
        assert (ep.host, ep.port, ep.db) == ("h", 6380, 3)

    def test_password_only(self):
        ep = parse_redis_url("redis://:pw@h")
        assert ep.username is None or ep.username == ""
        assert ep.password == "pw"

    def test_unix_socket(self):
        ep = parse_redis_url("unix:///var/run/redis.sock")
        assert ep.unix_path == "/var/run/redis.sock"

    def test_unix_socket_db_query(self):
        ep = parse_redis_url("unix:///var/run/redis.sock?db=3")
        assert ep.db == 3

    def test_unknown_query_param_rejected(self):
        with pytest.raises(ValueError, match="query parameter"):
            parse_redis_url("redis://h:1?ssl_cert_reqs=none")

    def test_rejects_unknown_scheme(self):
        with pytest.raises(ValueError):
            parse_redis_url("http://h:1")

    def test_rejects_bad_db(self):
        with pytest.raises(ValueError):
            parse_redis_url("redis://h:1/notanumber")


class TestAuthHandshake:
    def test_authenticated_roundtrip(self):
        server = MiniRespServer(password="hunter2")
        try:
            idx = RedisIndex(
                RedisIndexConfig(
                    address=f"redis://:hunter2@{server.address}"
                )
            )
            idx.add([1], [101], [POD1])
            assert idx.lookup([101]) == {101: [POD1]}
        finally:
            server.close()

    def test_wrong_password_rejected(self):
        server = MiniRespServer(password="hunter2")
        try:
            with pytest.raises(RespError):
                RedisIndex(
                    RedisIndexConfig(
                        address=f"redis://:wrong@{server.address}"
                    )
                )
        finally:
            server.close()

    def test_unauthenticated_client_refused(self):
        server = MiniRespServer(password="hunter2")
        try:
            client = RespClient("127.0.0.1", server.port)
            with pytest.raises(RespError, match="NOAUTH"):
                client.execute("PING")
        finally:
            server.close()

    def test_username_password_pair(self):
        server = MiniRespServer(password="hunter2")
        try:
            client = RespClient(
                endpoint=parse_redis_url(
                    f"redis://default:hunter2@{server.address}"
                )
            )
            assert client.execute("PING") == "PONG"
        finally:
            server.close()

    def test_reconnect_replays_auth(self):
        server = MiniRespServer(password="hunter2")
        try:
            client = RespClient(
                endpoint=parse_redis_url(
                    f"redis://:hunter2@{server.address}"
                )
            )
            assert client.execute("PING") == "PONG"
            client.close()  # force the transparent-reconnect path
            assert client.execute("PING") == "PONG"
        finally:
            server.close()


@pytest.fixture(scope="module")
def tls_cert(tmp_path_factory):
    base = tmp_path_factory.mktemp("tls")
    key, cert = str(base / "key.pem"), str(base / "cert.pem")
    proc = subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key, "-out", cert, "-days", "1", "-nodes",
            "-subj", "/CN=127.0.0.1",
            "-addext", "subjectAltName=IP:127.0.0.1",
        ],
        capture_output=True,
    )
    if proc.returncode != 0:
        pytest.skip(f"openssl unavailable: {proc.stderr[-200:]}")
    return key, cert


class TestTLSHandshake:
    def _server(self, tls_cert, password=None):
        key, cert = tls_cert
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(cert, key)
        return MiniRespServer(password=password, ssl_context=context)

    def test_rediss_with_ca_file(self, tls_cert):
        server = self._server(tls_cert)
        try:
            idx = RedisIndex(
                RedisIndexConfig(
                    address=f"rediss://127.0.0.1:{server.port}",
                    tls_ca_file=tls_cert[1],
                )
            )
            idx.add([2], [202], [POD1])
            assert idx.lookup([202]) == {202: [POD1]}
        finally:
            server.close()

    def test_untrusted_cert_rejected(self, tls_cert):
        server = self._server(tls_cert)
        try:
            with pytest.raises((ssl.SSLError, OSError)):
                RedisIndex(
                    RedisIndexConfig(
                        address=f"rediss://127.0.0.1:{server.port}"
                    )
                )
        finally:
            server.close()

    def test_insecure_skip_verify(self, tls_cert):
        server = self._server(tls_cert)
        try:
            idx = RedisIndex(
                RedisIndexConfig(
                    address=f"valkeys://127.0.0.1:{server.port}",
                    tls_insecure_skip_verify=True,
                )
            )
            idx.add([3], [303], [POD1])
            assert idx.lookup([303]) == {303: [POD1]}
        finally:
            server.close()

    def test_tls_with_auth(self, tls_cert):
        server = self._server(tls_cert, password="pw")
        try:
            idx = RedisIndex(
                RedisIndexConfig(
                    address=f"rediss://:pw@127.0.0.1:{server.port}",
                    tls_ca_file=tls_cert[1],
                )
            )
            idx.add([4], [404], [POD1])
            assert idx.lookup([404]) == {404: [POD1]}
        finally:
            server.close()


class TestEvictionAtomicity:
    def test_add_racing_into_prune_window_survives(self):
        """Deterministic interleave of the historical lost-add race:

        evictor:  HDEL last field          (hash now empty)
        adder:            HSET pod2 + SET engine   <- lands in the window
        evictor:  prune script             (must NOT delete the new add)
        """
        server = MiniRespServer()
        try:
            evictor = RespClient("127.0.0.1", server.port)
            adder = RespClient("127.0.0.1", server.port)
            rk, ek = "9001", f"{_ENGINE_PREFIX}77"

            adder.execute("HSET", rk, "pod-1@hbm", "1")
            adder.execute("SET", ek, rk)

            evictor.execute("HDEL", rk, "pod-1@hbm")
            adder.pipeline(
                [("HSET", rk, "pod-2@hbm", "1"), ("SET", ek, rk)]
            )
            result = evictor.execute("EVAL", _PRUNE_SCRIPT, "2", rk, ek)

            assert result == 0  # hash non-empty: nothing pruned
            assert adder.execute("HKEYS", rk) == [b"pod-2@hbm"]
            assert adder.execute("GET", ek) == rk.encode()
        finally:
            server.close()

    def test_prune_after_true_emptiness(self):
        server = MiniRespServer()
        try:
            idx = RedisIndex(
                RedisIndexConfig(address=f"redis://{server.address}")
            )
            idx.add([7], [707], [POD1])
            idx.evict(7, [POD1])
            assert idx.lookup([707]) == {}
            with pytest.raises(KeyError):
                idx.get_request_key(7)
        finally:
            server.close()

    def test_concurrent_add_evict_stress_no_lost_adds(self):
        server = MiniRespServer()
        try:
            idx_a = RedisIndex(
                RedisIndexConfig(address=f"redis://{server.address}")
            )
            idx_b = RedisIndex(
                RedisIndexConfig(address=f"redis://{server.address}")
            )
            stop = threading.Event()
            errors = []

            def evictor():
                while not stop.is_set():
                    try:
                        idx_b.evict(11, [POD1, POD2])
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        return

            thread = threading.Thread(target=evictor)
            thread.start()
            try:
                for _ in range(300):
                    idx_a.add([11], [1111], [POD1])
            finally:
                stop.set()
                thread.join(timeout=10)
            assert not errors
            # The last operation was an add: the entry must exist no
            # matter how evictions interleaved.
            idx_a.add([11], [1111], [POD1])
            assert idx_a.lookup([1111]) == {1111: [POD1]}
            assert idx_a.get_request_key(11) == 1111
        finally:
            server.close()
