"""Seeded fuzz of the RESP2 client against a hostile/garbled server.

The index may be pointed at the wrong port (an HTTP server), sit behind
a garbling proxy, or face a malicious peer.  Totality invariant: the
client surfaces only ``ConnectionError`` (transport/framing, after
tearing the socket down) or ``RespError`` (server-reported) — never
ValueError / UnicodeDecodeError / RecursionError / MemoryError from the
frame parser — and recovers on the next call once the stream is sane.
"""

import random
import socket
import threading

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
    RedisEndpoint,
    RespClient,
    RespError,
)

GARBAGE_FRAMES = [
    b":\r\n",
    b":abc\r\n",
    b":9" * 40 + b"\r\n",
    b"$abc\r\n",
    b"$-5\r\nxx\r\n",
    b"$999999999999999\r\n",
    b"*xyz\r\n",
    b"*-7\r\n",
    b"*99999999999\r\n",
    b"*1\r\n" * 64 + b":1\r\n",  # deep nesting
    b"-\xff\xfe error\r\n",  # non-UTF-8 error line
    b"+\xc0\x80\r\n",  # non-UTF-8 simple string
    b"?what\r\n",
    b"HTTP/1.1 200 OK\r\n",
    b"\x00\x01\x02\r\n",
    b"+OK",  # missing terminator then close
    b"$1_0\r\n" + b"x" * 12,  # int() underscore liberalism
    b"$ 3\r\nabc\r\n",  # int() whitespace liberalism
    b"$+3\r\nabc\r\n",  # int() leading-plus liberalism
    b"$3\r\nabcde\r\n",  # wrong length: terminator check must fire
    b"+" + b"y" * (256 * 1024),  # newline-free flood: line cap must fire
]


class HostileServer:
    """Accepts connections; replies to each command with the configured
    payload (or a seeded garbage frame), then keeps the socket open so
    the client sees a garbled stream rather than a clean close."""

    def __init__(self):
        self._sock = socket.create_server(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self.mode = "garbage"  # or "ok"
        self._rng = random.Random(0)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        self._sock.settimeout(0.2)
        conns = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            conn.settimeout(0.2)
            conns.append(conn)
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._sock.close()

    def _handle(self, conn):
        buffer = b""
        while not self._stop.is_set():
            try:
                chunk = conn.recv(4096)
            except socket.timeout:
                continue
            except OSError:
                return
            if not chunk:
                return
            buffer += chunk
            # One reply per complete inline command array received; a
            # RESP command is "*N\r\n" + 2N lines.
            while True:
                reply = self._one_command_consumed(buffer)
                if reply is None:
                    break
                buffer = reply
                try:
                    if self.mode == "ok":
                        conn.sendall(b"+OK\r\n")
                    elif self.mode == "wrong_length":
                        conn.sendall(b"$3\r\nabcde\r\n")
                    else:
                        conn.sendall(self._rng.choice(GARBAGE_FRAMES))
                except OSError:
                    return

    @staticmethod
    def _one_command_consumed(buffer):
        if not buffer.startswith(b"*"):
            return b"" if buffer else None
        head, sep, rest = buffer.partition(b"\r\n")
        if not sep:
            return None
        try:
            n = int(head[1:])
        except ValueError:
            return b""
        for _ in range(2 * n):
            _, sep, rest = rest.partition(b"\r\n")
            if not sep:
                return None
        return rest

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)


@pytest.fixture()
def hostile():
    server = HostileServer()
    yield server
    server.close()


def make_client(port):
    return RespClient(
        endpoint=RedisEndpoint(host="127.0.0.1", port=port), timeout=2.0
    )


class TestRespFuzz:
    def test_garbage_replies_surface_as_connection_errors(self, hostile):
        client = make_client(hostile.port)
        for _ in range(40):
            try:
                client.execute("PING")
            except (ConnectionError, RespError):
                pass  # the two sanctioned failure modes
            except OSError:
                pass  # timeouts on withheld bytes are transport errors too
        client.close()

    def test_wrong_length_bulk_never_returns_data(self, hostile):
        """'$3\\r\\nabcde\\r\\n' must not come back as b'abc' — a garbled
        frame is a connection error, not a successful reply."""
        hostile.mode = "wrong_length"
        client = make_client(hostile.port)
        for _ in range(5):
            try:
                reply = client.execute("GET", "k")
            except (ConnectionError, RespError, OSError):
                continue
            raise AssertionError(
                f"garbled bulk returned as valid reply: {reply!r}"
            )
        client.close()

    def test_liberal_int_forms_rejected(self):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
            RespClient,
        )

        for bad in (b"1_0", b" 3", b"+3", b"", b"-", b"3a", b"0x10"):
            with pytest.raises(ConnectionError):
                RespClient._parse_int(bad)
        assert RespClient._parse_int(b"-1") == -1
        assert RespClient._parse_int(b"42") == 42

    def test_client_recovers_when_stream_heals(self, hostile):
        client = make_client(hostile.port)
        for _ in range(10):
            try:
                client.execute("PING")
            except (ConnectionError, RespError, OSError):
                pass
        hostile.mode = "ok"
        # The garbled socket was torn down; a fresh call reconnects.
        assert client.execute("PING") == "OK"
        client.close()
