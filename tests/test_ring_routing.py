"""Routing decision for ``ring_attention_sharded(impl="auto")``.

The auto impl must pick the Pallas flash body only where the kernel
runs (TPU mesh / explicit interpret) AND the per-step local K/V chunk
fits the kernel's VMEM staging budget; otherwise the einsum body, which
streams from HBM.  Pure-function tests (``resolve_auto_impl``) so they
run on any host — the shard_map plumbing itself is covered by the
model-level ring tests.
"""

from llm_d_kv_cache_manager_tpu.ops.flash_pallas import (
    VMEM_KV_BUDGET_BYTES,
    fits_vmem,
)
from llm_d_kv_cache_manager_tpu.ops.ring_attention import resolve_auto_impl

HEAD_DIM = 128
BF16 = 2


def max_fitting_tokens() -> int:
    """Largest local K/V chunk inside the staging budget at HEAD_DIM."""
    tokens = VMEM_KV_BUDGET_BYTES // (2 * HEAD_DIM * BF16)
    assert fits_vmem(tokens, HEAD_DIM, BF16)
    assert not fits_vmem(tokens + 1, HEAD_DIM, BF16)
    return tokens


class TestResolveAutoImpl:
    def test_tpu_within_budget_picks_flash(self):
        assert (
            resolve_auto_impl("tpu", 4096, HEAD_DIM, BF16) == "flash"
        )

    def test_tpu_over_budget_falls_back_to_einsum(self):
        """The shape that used to lower (or spill) a too-large Pallas
        staging block now routes to the streaming einsum body."""
        over = max_fitting_tokens() + 1
        assert resolve_auto_impl("tpu", over, HEAD_DIM, BF16) == "einsum"

    def test_boundary_is_the_fits_vmem_bound(self):
        at_bound = max_fitting_tokens()
        assert (
            resolve_auto_impl("tpu", at_bound, HEAD_DIM, BF16) == "flash"
        )

    def test_cpu_mesh_always_einsum(self):
        assert resolve_auto_impl("cpu", 128, HEAD_DIM, BF16) == "einsum"

    def test_interpret_forces_flash_regardless_of_budget(self):
        """interpret=True is an explicit request to exercise the
        Pallas kernel (no real VMEM involved): never silently resolve
        it away, even past the budget."""
        over = max_fitting_tokens() + 1
        assert (
            resolve_auto_impl("cpu", over, HEAD_DIM, BF16, interpret=True)
            == "flash"
        )

    def test_wider_dtype_tightens_the_bound(self):
        tokens = max_fitting_tokens()
        # The same chunk in f32 doubles the staging bytes.
        assert resolve_auto_impl("tpu", tokens, HEAD_DIM, 4) == "einsum"
