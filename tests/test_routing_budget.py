"""Budgets on the scoring RPC: regressions fail a test, not just
drift in a bench JSON.

Reference counterpart: the index microbenchmark
(tests/profiling/kv_cache_index/index_benchmark_test.go:97-197)
measures Add/Lookup at a 10k-key population; the precise scorer's
end-to-end cost (tokenize -> chained hashes -> lookup -> tier-weighted
score) is what bench.py reports as ``routing_precise_us``.

Budgets are deliberately regression tripwires, not perf claims: they
carry ~3x headroom over what this repo's slowest measured host (the
1-core CI VM: p50 ~2.2 ms, p99 ~2.6 ms at the full 8448-token /
528-block geometry) produces, so an order-of-magnitude blowup —
accidental O(n^2) in the prefix walk, a lost early-stop, a per-call
re-tokenization — fails here, while machine noise does not.  The
precise numbers live in BENCH_r*.json.
"""

import random
import time

import numpy as np

import bench
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    InMemoryIndexConfig,
    PodEntry,
)

# End-to-end scoring RPC at full bench geometry (8448-token prompts).
SCORING_P50_BUDGET_S = 8e-3
SCORING_P99_BUDGET_S = 15e-3

# Index lookup component at the reference microbench scale.
LOOKUP_CHAIN_BUDGET_S = 5e-3  # one 528-key chain against 10k keys
N_KEYS = 10_000


class TestScoringRpcBudget:
    def test_full_geometry_scoring_percentiles(self):
        requests, warmup, hashes_list = bench.make_workload()

        def percentiles():
            samples = bench.measure_routing_micro(
                requests, hashes_list, warmup
            )
            assert len(samples) >= 16
            return (
                float(np.percentile(samples, 50)),
                float(np.percentile(samples, 99)),
            )

        p50, p99 = percentiles()
        if p50 >= SCORING_P50_BUDGET_S or p99 >= SCORING_P99_BUDGET_S:
            # p99 over ~40 samples is nearly max-of-samples: one OS
            # scheduling stall on a shared CI runner can blow it.  A
            # REGRESSION reproduces on a fresh measurement; a stall
            # does not — retry exactly once before failing.
            p50, p99 = percentiles()
        assert p50 < SCORING_P50_BUDGET_S, (
            f"scoring RPC p50 {p50 * 1e3:.2f} ms exceeds "
            f"{SCORING_P50_BUDGET_S * 1e3:.0f} ms budget"
        )
        assert p99 < SCORING_P99_BUDGET_S, (
            f"scoring RPC p99 {p99 * 1e3:.2f} ms exceeds "
            f"{SCORING_P99_BUDGET_S * 1e3:.0f} ms budget"
        )

    def test_index_lookup_component_budget(self):
        """Lookup of one full-prompt chain against a 10k-key population
        (the reference microbench's axis) stays inside its budget."""
        rng = random.Random(5)
        index = InMemoryIndex(InMemoryIndexConfig(size=N_KEYS * 2))
        keys = [rng.getrandbits(64) for _ in range(N_KEYS)]
        entry_lists = [
            [PodEntry(f"pod-{i}", "hbm")] for i in range(4)
        ]
        for i, key in enumerate(keys):
            index.add([key], [key], entry_lists[i % 4])
        chain_len = bench.TOTAL_TOKENS // bench.BLOCK_SIZE
        chains = [
            keys[offset:offset + chain_len]
            for offset in range(0, N_KEYS - chain_len, chain_len)
        ]
        index.lookup(chains[0], None)  # warm

        def worst_lookup():
            times = []
            for chain in chains:
                t0 = time.perf_counter()
                index.lookup(chain, None)
                times.append(time.perf_counter() - t0)
            return max(times)

        worst = worst_lookup()
        if worst >= LOOKUP_CHAIN_BUDGET_S:
            worst = worst_lookup()  # stall-vs-regression retry (above)
        assert worst < LOOKUP_CHAIN_BUDGET_S, (
            f"index lookup {worst * 1e3:.2f} ms per {chain_len}-key "
            f"chain at {N_KEYS} keys exceeds "
            f"{LOOKUP_CHAIN_BUDGET_S * 1e3:.0f} ms budget"
        )
