"""Scheduler-plugin adapter + TTL subscriber lifecycle + pod reconciler.

Mirrors the reference's scorer-plugin behavior
(examples/kv_cache_aware_scorer) and reconciler predicates
(examples/kv_events/pod_reconciler), with the fleet simulated by
injected index entries and a fake k8s API server.
"""

import http.server
import json
import threading
import time

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.pod_reconciler import (
    PodReconciler,
    PodReconcilerConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.scheduler import (
    ChatCompletionsBody,
    ChatMessage,
    CompletionsBody,
    LLMRequest,
    Pod,
    PrecisePrefixCacheScorer,
    PrecisePrefixCacheScorerConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from llm_d_kv_cache_manager_tpu.utils.ttl_cache import TTLCache
from tests.helpers.tiny_tokenizer import (
    build_transformers_tokenizer,
    save_tokenizer_json,
)

MODEL = "test-model"
PROMPT = "the quick brown fox jumps over the lazy dog"


class TestTTLCache:
    def test_set_get_expire(self):
        evicted = []
        cache = TTLCache(0.15, on_evict=lambda k, v: evicted.append(k))
        cache.set("a", 1)
        assert cache.get("a") == 1
        time.sleep(0.2)
        assert cache.get("a") is None
        assert evicted == ["a"]

    def test_set_refreshes_deadline(self):
        cache = TTLCache(0.2)
        cache.set("a", 1)
        time.sleep(0.12)
        cache.set("a", 2)
        time.sleep(0.12)
        assert cache.get("a") == 2

    def test_sweep_and_delete(self):
        evicted = []
        cache = TTLCache(0.05, on_evict=lambda k, v: evicted.append(k))
        cache.set("a", 1)
        cache.set("b", 2, ttl_seconds=60)
        time.sleep(0.1)
        assert cache.sweep() == 1
        assert evicted == ["a"]
        # Explicit delete does not fire on_evict.
        assert cache.delete("b")
        assert evicted == ["a"]


@pytest.fixture()
def scorer(tmp_path):
    tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.chat_processor.register_tokenizer(
        MODEL, build_transformers_tokenizer()
    )
    scorer = PrecisePrefixCacheScorer(
        PrecisePrefixCacheScorerConfig(
            discover_pods=False,  # no live fleet in unit tests
        ),
        indexer=indexer,
    )
    yield scorer
    scorer.shutdown()


def seed(scorer, prompt, address, truncate=None):
    indexer = scorer.indexer
    tokens = indexer.tokenization_pool.tokenize(prompt, MODEL, None)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        EMPTY_BLOCK_HASH, tokens, MODEL
    )
    if truncate:
        keys = keys[:truncate]
    indexer.kv_block_index.add(keys, keys, [PodEntry(address, "hbm")])


class TestPrecisePrefixCacheScorer:
    def test_completions_scoring_normalized(self, scorer):
        seed(scorer, PROMPT, "10.0.0.1")
        seed(scorer, PROMPT, "10.0.0.2", truncate=1)
        pods = [
            Pod("ns/pod-a", "10.0.0.1"),
            Pod("ns/pod-b", "10.0.0.2"),
            Pod("ns/pod-c", "10.0.0.3"),
        ]
        request = LLMRequest(
            target_model=MODEL, completions=CompletionsBody(prompt=PROMPT)
        )
        scores = scorer.score(request, pods)
        assert scores[pods[0]] == 1.0
        assert 0 < scores[pods[1]] < 1.0
        assert scores[pods[2]] == 0.0

    def test_chat_completions_scoring(self, scorer):
        body = ChatCompletionsBody(
            messages=[ChatMessage("user", "hello world")]
        )
        rendered = scorer.indexer.chat_processor.apply_chat_template(
            MODEL,
            __import__(
                "llm_d_kv_cache_manager_tpu.preprocessing.chat_templating",
                fromlist=["ApplyChatTemplateRequest"],
            ).ApplyChatTemplateRequest(
                conversation=[{"role": "user", "content": "hello world"}]
            ),
        )
        # Seed the index with the rendered prompt's block chain.
        tokens = scorer.indexer.tokenization_pool.tokenize(
            rendered, MODEL, None
        )
        keys = scorer.indexer.token_processor.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, MODEL
        )
        scorer.indexer.kv_block_index.add(
            keys, keys, [PodEntry("10.0.0.9", "hbm")]
        )
        pods = [Pod("ns/pod-x", "10.0.0.9")]
        scores = scorer.score(
            LLMRequest(target_model=MODEL, chat_completions=body), pods
        )
        assert scores[pods[0]] == 1.0

    def test_nil_request_and_empty_body(self, scorer):
        pods = [Pod("ns/pod-a", "10.0.0.1")]
        assert scorer.score(None, pods) == {}
        # No body -> error swallowed, empty result.
        assert scorer.score(LLMRequest(target_model=MODEL), pods) == {}

    def test_cold_index_scores_zero(self, scorer):
        pods = [Pod("ns/pod-a", "10.0.0.1")]
        request = LLMRequest(
            target_model=MODEL, completions=CompletionsBody(prompt=PROMPT)
        )
        assert scorer.score(request, pods) == {pods[0]: 0.0}


class TestSubscriberTTLLifecycle:
    def test_unseen_pods_age_out(self):
        removed = []

        class FakeManager:
            def ensure_subscriber(self, pod, endpoint):
                return True

            def remove_subscriber(self, pod):
                removed.append(pod)
                return True

        cache = TTLCache(
            0.15, on_evict=lambda pod, _: FakeManager().remove_subscriber(pod)
        )
        cache.set("ns/pod-a", "10.0.0.1")
        time.sleep(0.2)
        cache.sweep()
        assert removed == ["ns/pod-a"]


class TestPurgeOnExpiry:
    def test_expired_pod_purged_from_index(self, tmp_path):
        """With purge_index_on_expiry, a pod whose subscription ages
        out also loses its index entries (stale claims stop attracting
        traffic); other pods' entries survive."""
        tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=1, model_name=MODEL
                ),
            ),
            tokenizer=LocalFastTokenizer(tokenizer_dir),
        )
        scorer = PrecisePrefixCacheScorer(
            PrecisePrefixCacheScorerConfig(
                indexer_config=IndexerConfig(),
                subscription_ttl_seconds=0.1,
                purge_index_on_expiry=True,
            ),
            indexer=indexer,
        )
        try:
            from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
                PodEntry,
            )

            indexer.kv_block_index.add(
                [0x51, 0x52],
                [0x61, 0x62],
                [PodEntry("10.0.0.1", "hbm"), PodEntry("10.0.0.2", "hbm")],
            )
            scorer._subscriptions.set("ns/pod-a", "10.0.0.1")
            time.sleep(0.2)
            scorer._subscriptions.sweep()
            found = indexer.kv_block_index.lookup([0x61, 0x62])
            survivors = {
                p.pod_identifier
                for pods in found.values()
                for p in pods
            }
            assert survivors == {"10.0.0.2"}
        finally:
            scorer.shutdown()


class TestDiscoveryTopicFilter:
    def test_discovered_subscriber_matches_engine_topics(self, tmp_path):
        """The plugin subscribes under the scheduler's namespaced pod
        name, but engines publish under their own id — the "kv@" filter
        must bridge the two (regression: a per-pod-identity filter
        silently drops every event)."""
        import time as _time

        from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher

        tokenizer_dir = save_tokenizer_json(str(tmp_path), MODEL)
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=1, model_name=MODEL
                ),
            ),
            tokenizer=LocalFastTokenizer(tokenizer_dir),
        )
        # Bind to port 0 so the OS picks a free port (fixed ports flake
        # under parallel test runs); the scorer then dials that port.
        publisher = Publisher(
            "tcp://127.0.0.1:0",
            pod_identifier="127.0.0.1",  # engine id != "ns/pod-a"
            model_name=MODEL,
            bind=True,
        )
        scorer = PrecisePrefixCacheScorer(
            PrecisePrefixCacheScorerConfig(
                discover_pods=True, pod_socket_port=publisher.port
            ),
            indexer=indexer,
        )
        pods = [Pod("ns/pod-a", "127.0.0.1")]
        request = LLMRequest(
            target_model=MODEL, completions=CompletionsBody(prompt=PROMPT)
        )
        try:
            assert scorer.score(request, pods)[pods[0]] == 0.0
            _time.sleep(1.0)  # slow joiner
            from llm_d_kv_cache_manager_tpu.kvevents.events import (
                BlockStored,
            )

            tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
            publisher.publish(
                *[
                    BlockStored(
                        block_hashes=[0x7000 + i],
                        parent_block_hash=0x7000 + i - 1 if i else None,
                        token_ids=tokens[i * 4:(i + 1) * 4],
                        block_size=4,
                        lora_id=None,
                        medium="hbm",
                    )
                    for i in range(len(tokens) // 4)
                ]
            )
            deadline = _time.time() + 10
            score = 0.0
            while _time.time() < deadline and score == 0.0:
                score = scorer.score(request, pods)[pods[0]]
                _time.sleep(0.2)
            assert score == 1.0
            publisher.close()
        finally:
            scorer.shutdown()


# ----------------------------- pod reconciler -----------------------------


def make_pod(name, ip="10.1.0.1", phase="Running", ready=True, rv="1"):
    return {
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "llm-d",
            "resourceVersion": rv,
            "labels": {"llm-d.ai/inferenceServing": "true"},
        },
        "status": {
            "phase": phase,
            "podIP": ip,
            "conditions": [
                {"type": "Ready", "status": "True" if ready else "False"}
            ],
        },
    }


class FakeKubeHandler(http.server.BaseHTTPRequestHandler):
    pods = []
    watch_events = []

    def log_message(self, *args):
        pass

    def do_GET(self):
        if "watch=true" in self.path:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            for event in self.watch_events:
                self.wfile.write(json.dumps(event).encode() + b"\n")
            return
        body = json.dumps(
            {
                "kind": "PodList",
                "metadata": {"resourceVersion": "10"},
                "items": self.pods,
            }
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def fake_kube():
    server = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0), FakeKubeHandler
    )
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    thread.join(timeout=5)


class RecordingManager(SubscriberManager):
    """Real manager against unroutable endpoints; records calls."""

    def __init__(self):
        super().__init__(sink=lambda message: None)
        self.calls = []

    def ensure_subscriber(self, pod, endpoint, topic_filter=None):
        self.calls.append(("ensure", pod, endpoint))
        return super().ensure_subscriber(pod, endpoint, topic_filter)

    def remove_subscriber(self, pod):
        self.calls.append(("remove", pod))
        return super().remove_subscriber(pod)


class TestPodReconciler:
    def test_predicates(self):
        assert PodReconciler.should_subscribe(make_pod("a"))
        assert not PodReconciler.should_subscribe(
            make_pod("a", phase="Pending")
        )
        assert not PodReconciler.should_subscribe(make_pod("a", ip=""))
        assert not PodReconciler.should_subscribe(
            make_pod("a", ready=False)
        )

    def test_list_watch_converges_subscribers(self, fake_kube):
        FakeKubeHandler.pods = [
            make_pod("pod-a", ip="10.1.0.1"),
            make_pod("pod-b", ip="10.1.0.2", ready=False),
        ]
        FakeKubeHandler.watch_events = [
            {"type": "ADDED", "object": make_pod("pod-c", ip="10.1.0.3")},
            {"type": "DELETED", "object": make_pod("pod-a")},
        ]
        manager = RecordingManager()
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(
                namespace="llm-d", api_server=fake_kube, token="t"
            ),
        )
        reconciler.run_once()
        assert manager.active_pods() == ["llm-d/pod-c"]
        assert (
            "ensure",
            "llm-d/pod-a",
            "tcp://10.1.0.1:5557",
        ) in manager.calls
        manager.shutdown()

    def test_resync_removes_stale_only_reconciler_owned(self, fake_kube):
        FakeKubeHandler.pods = [make_pod("pod-a")]
        FakeKubeHandler.watch_events = []
        manager = RecordingManager()
        # Pre-existing subscribers: one reconciler-shaped, one manual.
        manager.ensure_subscriber("llm-d/ghost", "tcp://10.9.9.9:5557")
        manager.ensure_subscriber("local-subscriber", "tcp://10.9.9.8:5557")
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(
                namespace="llm-d", api_server=fake_kube, token="t"
            ),
        )
        reconciler.run_once()
        assert manager.active_pods() == ["llm-d/pod-a", "local-subscriber"]
        manager.shutdown()

    def test_endpoint_ipv6_brackets(self):
        manager = RecordingManager()
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(api_server="http://127.0.0.1:1", token="t"),
        )
        pod = make_pod("pod-a", ip="fd00::1")
        assert reconciler._endpoint(pod) == "tcp://[fd00::1]:5557"
        manager.shutdown()

    def test_watch_requests_server_side_timeout(self, fake_kube):
        """The watch must carry timeoutSeconds so the API server ends the
        stream periodically — the liveness bound against half-open TCP
        connections that would otherwise block the loop forever."""
        FakeKubeHandler.pods = []
        FakeKubeHandler.watch_events = []
        seen_paths = []
        original = FakeKubeHandler.do_GET

        def spy(handler):
            seen_paths.append(handler.path)
            original(handler)

        FakeKubeHandler.do_GET = spy
        try:
            manager = RecordingManager()
            reconciler = PodReconciler(
                manager,
                PodReconcilerConfig(
                    namespace="llm-d",
                    api_server=fake_kube,
                    token="t",
                    watch_timeout_seconds=123,
                ),
            )
            reconciler.run_once()
            watch_paths = [p for p in seen_paths if "watch=true" in p]
            assert watch_paths and "timeoutSeconds=123" in watch_paths[0]
            manager.shutdown()
        finally:
            FakeKubeHandler.do_GET = original

    def test_read_timeout_is_a_normal_stream_end(self):
        """A dead (half-open) stream raises TimeoutError mid-iteration;
        run_once must swallow it and return so the loop re-lists."""
        manager = RecordingManager()
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(api_server="http://127.0.0.1:1", token="t"),
        )

        class DeadStreamClient:
            def list_pods(self):
                return {"metadata": {"resourceVersion": "1"}, "items": []}

            def watch_pods(self, resource_version):
                yield {
                    "type": "ADDED",
                    "object": make_pod("pod-a", ip="10.0.0.1"),
                }
                raise TimeoutError("read timed out")

        reconciler.client = DeadStreamClient()
        reconciler.run_once()  # must not raise
        assert manager.active_pods() == ["llm-d/pod-a"]
        manager.shutdown()


class TestReconcilerChaos:
    """Garbled watch events must not abort the watch: type-confused
    lines are skipped per-event (kvevents-pool poison philosophy) and
    later valid events still converge the subscriber set."""

    def test_garbage_events_skipped_valid_ones_applied(self, fake_kube):
        FakeKubeHandler.pods = []
        FakeKubeHandler.watch_events = [
            42,  # not an object
            "nope",
            [1, 2, 3],
            {"type": "ADDED", "object": "not-a-pod"},
            {"type": "ADDED", "object": {"status": "confused"}},
            {"type": 7, "object": {}},
            {"type": "ADDED", "object": make_pod("pod-z", ip="10.1.0.9")},
        ]
        manager = RecordingManager()
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(
                namespace="llm-d", api_server=fake_kube, token="t"
            ),
        )
        reconciler.run_once()
        # The single valid event at the end of the garbled stream landed.
        assert manager.active_pods() == ["llm-d/pod-z"]
        manager.shutdown()

    def test_poison_pod_in_list_does_not_wedge_resync(self, fake_kube):
        """A malformed pod in the LIST response (run_once re-lists
        first, every cycle) must be skipped per-item — otherwise the
        reconciler wedges for as long as the bad item exists."""
        FakeKubeHandler.pods = [
            42,
            {"metadata": {"name": "bad"}, "status": "confused"},
            # Dict pod whose metadata itself is type-confused: the key
            # computation runs OUTSIDE the per-item try (seen-marking),
            # so _pod_key must tolerate these rather than raise and
            # abort the whole resync.
            {"metadata": None, "status": {"phase": "Running"}},
            {"metadata": "nope", "status": {"phase": "Running"}},
            {"metadata": [1, 2], "status": {"phase": "Running"}},
            make_pod("pod-good", ip="10.1.0.7"),
        ]
        FakeKubeHandler.watch_events = []
        manager = RecordingManager()
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(
                namespace="llm-d", api_server=fake_kube, token="t"
            ),
        )
        reconciler.run_once()
        assert manager.active_pods() == ["llm-d/pod-good"]
        manager.shutdown()

    def test_malformed_list_response_does_not_raise(self, fake_kube):
        """Go serializes an empty slice as null ({"items": null}); a
        proxy may mangle worse.  reconcile_list must tolerate a
        type-confused items/metadata field — run_once re-lists first
        every cycle, so raising here wedges the reconciler for as long
        as the response shape persists."""
        manager = RecordingManager()
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(
                namespace="llm-d", api_server=fake_kube, token="t"
            ),
        )
        for bad_list in (
            {"items": None, "metadata": None},
            {"items": "nope", "metadata": "nope"},
            {"items": 42, "metadata": {"resourceVersion": 7}},
            {},
            None,
            "garbage",
        ):
            version = reconciler.reconcile_list(bad_list)
            assert isinstance(version, str)
        manager.shutdown()

    def test_failed_reconcile_does_not_prune_existing_subscriber(
        self, fake_kube
    ):
        """A pod PRESENT in the list whose reconcile raises (transient
        failure, type confusion) keeps its existing subscription — the
        stale-prune must only remove pods absent from the response."""
        FakeKubeHandler.pods = [
            {
                "metadata": {"namespace": "llm-d", "name": "flaky"},
                "status": "confused",  # reconcile raises on this
            },
        ]
        FakeKubeHandler.watch_events = []
        manager = RecordingManager()
        manager.ensure_subscriber("llm-d/flaky", "tcp://10.3.0.1:5557")
        reconciler = PodReconciler(
            manager,
            PodReconcilerConfig(
                namespace="llm-d", api_server=fake_kube, token="t"
            ),
        )
        reconciler.run_once()
        assert manager.active_pods() == ["llm-d/flaky"]
        manager.shutdown()
